"""Pretrained-7B convergence (VERDICT r04 missing-item #2).

The reference's recorded 7B trajectory fine-tunes *pretrained*
Llama-2-7B and goes 0.94 -> ~0.60-0.78 on glaive
(``/root/reference/training/train.ipynb:334`` ff., cell 18). Literal
Llama-2 weights are unreachable in this offline image (zero egress), so
this run reproduces the *semantics* at full 7B scale with the repo's own
trained artifact, exactly like ``results/hf_interop_pretrained_300m.json``
did at 300M:

  1. load the consolidated 7B glaive export (stage C of chip_day.sh)
     host-side (``load_exported_model`` — no device needed to read it)
  2. fine-tune from it on 400 *held-out* glaive pairs (variants
     20000-20399; training saw 0-19999) through the production
     ``Trainer(base_params=...)`` path with LoRA r=16 + int8 frozen base
     — the same config as the training headline
  3. a short random-init contrast run makes the pretrained-start gap
     explicit (corpus-level first-step loss vs ~11 cold)

Writes ``results/convergence_7b_pretrained_tpu.json`` with the full
per-step loss curve (all steps reported, no cherry-picking).

Smoke test (no chip, 300M export):
    python benchmarks_dev/pretrained_7b_convergence.py --cpu
"""

import argparse
import dataclasses
import json
import logging
import os
import re
import sys
import tempfile
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)


class _Capture(logging.Handler):
    """Per-step losses only reach the logger ('step N | loss X | ...')."""

    def __init__(self):
        super().__init__()
        self.losses = []

    def emit(self, record):
        m = re.match(r"step (\d+) \| loss ([0-9.]+)", record.getMessage())
        if m:
            self.losses.append(round(float(m.group(2)), 4))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--export", default="exports/glaive_7b_r05")
    ap.add_argument("--cpu", action="store_true",
                    help="smoke test: 300M export, no int8, tiny step count")
    ap.add_argument("--steps", type=int, default=48)
    ap.add_argument("--contrast-steps", type=int, default=3)
    ap.add_argument("--bs", type=int, default=0, help="0 = auto (4 chip / 2 cpu)")
    ap.add_argument("--json-out", default="")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        args.export = (args.export if os.path.isdir(args.export)
                       and "7b" not in args.export else "exports/glaive_300m")
        args.steps = min(args.steps, 8)
    bs = args.bs or (2 if args.cpu else 4)

    from dlti_tpu.checkpoint.export import load_exported_model
    from dlti_tpu.config import (
        CheckpointConfig, Config, DataConfig, LoRAConfig, OptimizerConfig,
        ParallelConfig, TrainConfig,
    )
    from dlti_tpu.data import ByteTokenizer, make_batches
    from dlti_tpu.training.trainer import Trainer
    from datasets import load_from_disk

    t0 = time.time()
    params, full_cfg = load_exported_model(args.export)
    mc = full_cfg.model
    print(f"export {args.export} loaded in {time.time()-t0:.0f}s", flush=True)

    texts = list(load_from_disk("data/glaive_eval")["text"])
    print(f"{len(texts)} held-out texts (variants 20000+)", flush=True)

    # Same winning config as the training headline (int8 frozen base, no
    # remat) so the convergence run and the throughput claim share a
    # config; CPU smoke keeps bf16->fp32 and remat off for speed.
    mc_ft = dataclasses.replace(mc, remat=False, max_seq_len=512)
    tmp = tempfile.mkdtemp(prefix="conv7b_")

    def run(tag, base_params, max_steps):
        cfg = Config(
            model=mc_ft,
            lora=LoRAConfig(enabled=True, r=16, alpha=32, dropout=0.0),
            optimizer=OptimizerConfig(learning_rate=2e-4, warmup_steps=4),
            parallel=ParallelConfig(),
            data=DataConfig(max_seq_len=512, tokenizer="byte"),
            checkpoint=CheckpointConfig(output_dir=os.path.join(tmp, tag),
                                        save_strategy="no"),
            train=TrainConfig(micro_batch_size=bs, grad_accum_steps=1,
                              max_steps=max_steps, logging_steps=1,
                              num_epochs=10,
                              quantize_frozen_base="" if args.cpu else "int8",
                              metrics_csv=os.path.join(tmp, f"{tag}.csv")),
            experiment_name=tag,
        )
        ds = make_batches(texts, ByteTokenizer(), seq_len=512,
                          micro_batch_size=bs, grad_accum_steps=1,
                          shard_by_host=False)
        tr = Trainer(cfg, base_params=base_params)
        cap = _Capture()
        tr.logger.addHandler(cap)
        t = time.time()
        try:
            state, record = tr.train(dataset=ds)
        finally:
            tr.logger.removeHandler(cap)
        dt = time.time() - t
        print(f"{tag}: {len(cap.losses)} steps in {dt:.0f}s "
              f"first={cap.losses[0] if cap.losses else None} "
              f"final={record.final_loss:.4f}", flush=True)
        return cap.losses, round(float(record.final_loss), 4), round(dt, 1)

    ft_losses, ft_final, ft_s = run("from_pretrained", params, args.steps)
    ri_losses, ri_final, ri_s = run("random_init", None, args.contrast_steps)

    scale = ("CPU SMOKE of the runner on the 300M export (NOT 7B-scale "
             "evidence — proves the script end-to-end)" if args.cpu
             else "consolidated trained 7B glaive export")
    art = {
        "what": f"pretrained convergence semantics: {scale} -> "
                "Trainer(base_params=...) LoRA r=16 "
                f"{'' if args.cpu else 'int8-base '}fine-tune on 400 "
                "HELD-OUT glaive pairs; random-init contrast shows the "
                "pretrained base starts at corpus loss, not cold. "
                "Reference trajectory: pretrained Llama-2-7B 0.94 -> "
                "~0.60-0.78 (train.ipynb:334 ff.). Literal Llama-2 "
                "weights are unreachable offline (zero egress), so the "
                "repo's own trained export stands in as the pretrained "
                "base — same mechanism.",
        "export": args.export,
        "steps": len(ft_losses),
        "micro_batch_size": bs,
        "finetune_losses_from_pretrained": ft_losses,
        "finetune_final_loss_from_pretrained": ft_final,
        "finetune_seconds": ft_s,
        "finetune_losses_random_init_contrast": ri_losses,
        "finetune_final_loss_random_init_contrast": ri_final,
        "reference_parity": "train.ipynb:334 ff. (pretrained 7B base, "
                            "loss starts ~0.94 not ~11)",
        "platform": "cpu-smoke" if args.cpu else "tpu (axon relay)",
        "date": "2026-08-01",
    }
    out = args.json_out or ("results/convergence_7b_pretrained_cpu_smoke.json"
                            if args.cpu
                            else "results/convergence_7b_pretrained_tpu.json")
    with open(out, "w") as f:
        json.dump(art, f, indent=1)
    print("ARTIFACT_WRITTEN", out, flush=True)
    assert ft_losses[0] < 2.5, f"pretrained start too high: {ft_losses[0]}"
    assert ri_losses[0] > 5.0, f"random-init start too low: {ri_losses[0]}"
    print("CONVERGENCE_OK", flush=True)


if __name__ == "__main__":
    main()
