"""End-to-end from_pretrained demonstration on a REAL artifact (VERDICT r03 #3).

Round-trips the trained 300M glaive export through the HF checkpoint
layer, then fine-tunes from it, proving the
``AutoModelForCausalLM.from_pretrained`` semantics of the reference
(``training/train_baseline.py:122-126``) on a real checkpoint instead of
synthetic tensors:

  1. load the consolidated Orbax export (``exports/glaive_300m``)
  2. ``save_hf_checkpoint`` with a small shard budget -> sharded
     ``model-XXXXX-of-XXXXX.safetensors`` + index (the multi-file layout
     real 7B checkpoints use)
  3. ``load_hf_checkpoint`` back (exercises the index path) and verify
     numerical identity
  4. fine-tune from the loaded base on held-out glaive pairs through the
     production ``Trainer(base_params=...)`` path -> loss starts at the
     trained-corpus level (~0.2, vs ~11 from random init) and drops
  5. a short random-init contrast run makes the gap explicit

Writes ``results/hf_interop_pretrained_300m.json``.
"""

import dataclasses
import json
import os
import sys
import tempfile
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def tree_close(a, b, atol=0.0):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = {jax.tree_util.keystr(p): v for p, v in
          jax.tree_util.tree_leaves_with_path(b)}
    assert len(la) == len(lb), (len(la), len(lb))
    worst = 0.0
    for p, v in la:
        w = lb[jax.tree_util.keystr(p)]
        d = float(np.max(np.abs(np.asarray(v, np.float32)
                                - np.asarray(w, np.float32))))
        worst = max(worst, d)
        assert d <= atol, (jax.tree_util.keystr(p), d)
    return worst


def main():
    from dlti_tpu.checkpoint.export import load_exported_model
    from dlti_tpu.models.hf_interop import (
        load_hf_checkpoint, save_hf_checkpoint,
    )

    t0 = time.time()
    params, full_cfg = load_exported_model("exports/glaive_300m")
    mc = full_cfg.model
    print(f"export loaded in {time.time()-t0:.0f}s", flush=True)

    hf_dir = os.path.join(tempfile.mkdtemp(prefix="hf300m_"), "ckpt")
    save_hf_checkpoint(hf_dir, params, mc, max_shard_bytes=120 * 1024**2)
    files = sorted(os.listdir(hf_dir))
    print("HF checkpoint files:", files, flush=True)
    assert "model.safetensors.index.json" in files, "sharded path not taken"
    n_shards = len([f for f in files if f.endswith(".safetensors")])

    # fp32 load (CPU fine-tune; bf16 emulation is slow on CPU). bf16->fp32
    # is exact, so identity still checks bitwise.
    params2, mc2 = load_hf_checkpoint(hf_dir, dtype="float32",
                                      param_dtype="float32")
    worst = tree_close(params, params2, atol=0.0)
    print(f"round-trip identity ok (max abs diff {worst})", flush=True)

    # ------------------------------------------------------------------
    # Fine-tune from the loaded base on held-out glaive pairs.
    # ------------------------------------------------------------------
    from dlti_tpu.config import (
        CheckpointConfig, Config, DataConfig, LoRAConfig, OptimizerConfig,
        ParallelConfig, TrainConfig,
    )
    from dlti_tpu.data import ByteTokenizer, make_batches
    from dlti_tpu.training.trainer import Trainer
    from datasets import load_from_disk

    texts = list(load_from_disk("data/glaive_eval")["text"])
    print(f"{len(texts)} held-out texts", flush=True)

    mc_ft = dataclasses.replace(mc2, remat=False, max_seq_len=512)
    tmp = tempfile.mkdtemp(prefix="hf300m_ft_")

    import logging
    import re

    class _Capture(logging.Handler):
        """Per-step losses only reach the logger ('step N | loss X | ...');
        the metrics CSV is a per-run record."""

        def __init__(self):
            super().__init__()
            self.losses = []

        def emit(self, record):
            m = re.match(r"step (\d+) \| loss ([0-9.]+)", record.getMessage())
            if m:
                self.losses.append(round(float(m.group(2)), 4))

    def run(tag, base_params, max_steps):
        cfg = Config(
            model=mc_ft,
            lora=LoRAConfig(enabled=True, r=8, alpha=16, dropout=0.0),
            optimizer=OptimizerConfig(learning_rate=1e-4, warmup_steps=2),
            parallel=ParallelConfig(),
            data=DataConfig(max_seq_len=512, tokenizer="byte"),
            checkpoint=CheckpointConfig(output_dir=os.path.join(tmp, tag),
                                        save_strategy="no"),
            train=TrainConfig(micro_batch_size=2, grad_accum_steps=1,
                              max_steps=max_steps, logging_steps=1,
                              num_epochs=1,
                              metrics_csv=os.path.join(tmp, f"{tag}.csv")),
            experiment_name=tag,
        )
        ds = make_batches(texts, ByteTokenizer(), seq_len=512,
                          micro_batch_size=2, grad_accum_steps=1,
                          shard_by_host=False)
        tr = Trainer(cfg, base_params=base_params)
        cap = _Capture()
        tr.logger.addHandler(cap)
        t = time.time()
        try:
            state, record = tr.train(dataset=ds)
        finally:
            tr.logger.removeHandler(cap)
        dt = time.time() - t
        losses = cap.losses
        print(f"{tag}: {len(losses)} steps in {dt:.0f}s losses={losses} "
              f"final={record.final_loss:.4f}", flush=True)
        return losses, round(float(record.final_loss), 4)

    ft_losses, ft_final = run("from_pretrained", params2, max_steps=14)
    ri_losses, ri_final = run("random_init", None, max_steps=3)

    art = {
        "what": "from_pretrained semantics on a real artifact: trained 300M "
                "glaive export -> save_hf_checkpoint (sharded safetensors + "
                "index) -> load_hf_checkpoint -> LoRA fine-tune on 400 "
                "held-out glaive pairs via Trainer(base_params=...); "
                "random-init contrast shows the pretrained base starts at "
                "corpus loss, not cold.",
        "export": "exports/glaive_300m (bf16, 24L/1024h, byte tokenizer)",
        "hf_checkpoint_shards": n_shards,
        "roundtrip_max_abs_diff": worst,
        "finetune_losses_from_pretrained": ft_losses,
        "finetune_final_loss_from_pretrained": ft_final,
        "finetune_losses_random_init_contrast": ri_losses,
        "finetune_final_loss_random_init_contrast": ri_final,
        "reference_parity": "train_baseline.py:122-126 "
                            "(AutoModelForCausalLM.from_pretrained)",
        "platform": "cpu (single process; chip was down this session)",
        "date": "2026-08-01",
    }
    with open("results/hf_interop_pretrained_300m.json", "w") as f:
        json.dump(art, f, indent=1)
    print("ARTIFACT_WRITTEN", flush=True)
    assert ft_losses[0] < 2.0, f"pretrained start too high: {ft_losses[0]}"
    assert ri_losses[0] > 5.0, f"random-init start too low: {ri_losses[0]}"
    assert ft_final < ft_losses[0], "no improvement while fine-tuning"
    print("E2E_OK", flush=True)


if __name__ == "__main__":
    main()
