#!/bin/bash
# Relay ambush (r05): probe the axon relay every ~10 min; the moment it
# answers, fire chip_day.sh. Exits after chip_day completes (or
# immediately if another instance is already watching), so a supervising
# session gets notified exactly once per recovery.
#
#   bash benchmarks_dev/relay_watch.sh [max_hours]
set -u
cd "$(dirname "$0")/.."
MAX_HOURS=${1:-11}
LOCK=/tmp/relay_watch.lock
LOG=/tmp/relay_watch.log

if ! mkdir "$LOCK" 2>/dev/null; then
  echo "relay_watch: another instance holds $LOCK; exiting" | tee -a "$LOG"
  exit 2
fi
trap 'rmdir "$LOCK" 2>/dev/null' EXIT

log() { echo "[relay_watch $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

DEADLINE=$(( $(date +%s) + MAX_HOURS * 3600 ))
ATTEMPT=0
log "watching (max ${MAX_HOURS}h, probe every ~10 min)"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  ATTEMPT=$((ATTEMPT + 1))
  T0=$(date +%s)
  if timeout 240 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1; then
    log "probe $ATTEMPT: RELAY UP after $(( $(date +%s) - T0 ))s - firing chip_day"
    bash benchmarks_dev/chip_day.sh >> "$LOG" 2>&1
    log "chip_day finished (rc=$?)"
    exit 0
  fi
  log "probe $ATTEMPT: down ($(( $(date +%s) - T0 ))s)"
  sleep 600
done
log "gave up after ${MAX_HOURS}h without a relay window"
exit 1
