"""Make speculation win (VERDICT r03 #6): measure the fused n-gram
speculative path on its FAVORABLE workload — repetitive/code-like text,
greedy, engine-direct, long outputs — vs plain multi-step decode at the
same steps_per_sync, and report tokens/s over >= 3 runs each.

Usage:
  python benchmarks_dev/spec_win.py                 # real chip, 300M export
  python benchmarks_dev/spec_win.py --cpu           # CPU, llama_tiny (mechanism check)
  python benchmarks_dev/spec_win.py --export exports/glaive_300m

The favorable construction: prompts containing repeated boilerplate
blocks (the shape of real config/code templating), greedy sampling, long
outputs. A trained model continues the repetition, so the on-device
n-gram prompt-lookup proposer gets long accepted prefixes; the adaptive
gate never engages. Writes results/speculative_win.json (or _cpu variant).
"""

import argparse
import json
import os
import statistics
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--export", default="exports/glaive_300m")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--max-tokens", type=int, default=160)
    ap.add_argument("--sync", type=int, default=8)
    ap.add_argument("--draft", type=int, default=6)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving.engine import (
        EngineConfig, InferenceEngine, SamplingParams,
    )

    if args.cpu:
        cfg = dataclasses.replace(MODEL_PRESETS["llama_tiny"],
                                  dtype="float32", param_dtype="float32")
        model = LlamaForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        tok = None
    else:
        from dlti_tpu.checkpoint.export import load_exported_model
        from dlti_tpu.data import ByteTokenizer

        params, full_cfg = load_exported_model(args.export)
        cfg = full_cfg.model
        tok = ByteTokenizer()

    # Repetitive, code-shaped prompts: boilerplate blocks the greedy
    # continuation keeps extending (prompt-lookup heaven).
    if tok is None:
        # token-id world for the tiny model: a strict 8-token cycle
        base = [11, 12, 13, 14, 15, 16, 17, 18]
        prompts = [(base * 6)[:48] for _ in range(4)]
    else:
        block = ("def check_{i}(value):\n"
                 "    if value is None:\n"
                 "        return default\n"
                 "    return transform(value)\n\n")
        texts = ["".join(block.replace("{i}", str(i)) for i in range(4))
                 for _ in range(4)]
        prompts = [tok.encode(t)[:512] for t in texts]

    def build(spec: bool):
        ec = EngineConfig(
            max_seqs=4, block_size=16,
            num_blocks=max(256, (args.max_tokens + 600) // 16 * 8),
            max_model_len=1024, eos_token_id=-1,
            cache_dtype="float32" if args.cpu else "bfloat16",
            steps_per_sync=args.sync,
            speculative="ngram" if spec else "none",
            num_draft_tokens=args.draft,
        )
        return InferenceEngine(cfg, params, ec)

    def measure(spec: bool):
        eng = build(spec)
        sp = SamplingParams(temperature=0.0, max_tokens=args.max_tokens)
        rates, toks = [], None
        # warmup (compile): decode ladder + spec program + prefill buckets
        eng.warmup_decode_ladder()
        eng.generate([p[:16] for p in prompts], SamplingParams(
            temperature=0.0, max_tokens=args.sync * (args.draft + 1) + 2))
        eng.generate(prompts, SamplingParams(
            temperature=0.0, max_tokens=args.sync * (args.draft + 1) + 2))
        for _ in range(args.runs):
            t0 = time.perf_counter()
            res = eng.generate(prompts, sp)
            dt = time.perf_counter() - t0
            n = sum(len(r.output_token_ids) for r in res)
            rates.append(n / dt)
            toks = [r.output_token_ids for r in res]
        st = dict(eng.stats)
        return rates, toks, st

    plain_rates, plain_toks, plain_st = measure(False)
    spec_rates, spec_toks, spec_st = measure(True)
    assert spec_toks == plain_toks, "speculation changed greedy outputs"

    med_p = statistics.median(plain_rates)
    med_s = statistics.median(spec_rates)
    acc = (spec_st["spec_accepted"] / spec_st["spec_proposed"]
           if spec_st.get("spec_proposed") else 0.0)
    out = {
        "what": "speculation on its favorable workload (repetitive "
                "code-shaped prompts, greedy, engine-direct, long outputs) "
                "vs plain multi-step at the same steps_per_sync",
        "platform": "cpu/llama_tiny" if args.cpu else f"tpu/{args.export}",
        "steps_per_sync": args.sync, "num_draft_tokens": args.draft,
        "max_tokens": args.max_tokens, "runs": args.runs,
        "plain_tok_s_all": [round(r, 1) for r in plain_rates],
        "spec_tok_s_all": [round(r, 1) for r in spec_rates],
        "plain_tok_s_median": round(med_p, 1),
        "spec_tok_s_median": round(med_s, 1),
        "speedup": round(med_s / med_p, 3),
        "outputs_identical": True,
        "draft_acceptance": round(acc, 3),
        "decode_rounds_plain": plain_st["decode_steps"],
        "decode_rounds_spec": spec_st["decode_steps"],
        "date": "2026-08-01",
    }
    name = ("results/speculative_win_cpu.json" if args.cpu
            else "results/speculative_win.json")
    with open(name, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
