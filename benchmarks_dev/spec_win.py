"""Adaptive speculative decoding A/B: favorable AND adversarial traces,
plus the ragged multi-admission prefill TTFT wave.

Methodology fixes over the r03 version (whose committed artifact
recorded a 0.103 "speedup"): the measured window previously included
XLA compiles — run 1 of the plain arm compiled the decode ladder
mid-measurement and the spec arm compiled a fresh draft-length rung
mid-run-2, so the medians compared compile time, not decode time. Every
arm now runs its FULL measured workload once before timing (compiling
prefill buckets, the decode ladder, and every spec-k rung the per-slot
controller will visit), reports the median of >= 3 measured runs, and
asserts byte-identical outputs against the plain-greedy reference
before a single number is written.

Traces:

* **favorable** — prompts whose greedy continuation locks into a short
  loop (repetitive/code-template shape): the n-gram proposer gets long
  accepted prefixes and the ladder stays at the top rung.
* **adversarial** — prompts whose continuation wanders: near-zero
  acceptance, so the per-slot gate pauses speculation and the ladder
  collapses toward k=1; the claim is bounded overhead, not a win.
* **ragged wave** — a burst of mixed-length admissions, prefill TTFT
  p99 with ragged packing on vs off at byte-identical outputs.

Usage:
  python benchmarks_dev/spec_win.py --cpu            # llama_tiny check
  python benchmarks_dev/spec_win.py                  # real chip, export
  python benchmarks_dev/spec_win.py --cpu --runs 1 --max-tokens 48 \
      --wave 8 --json-out /tmp/x.json                # CI smoke shape
"""

import argparse
import json
import os
import statistics
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--export", default="exports/glaive_300m")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--max-tokens", type=int, default=160)
    ap.add_argument("--sync", type=int, default=8)
    ap.add_argument("--draft", type=int, default=6)
    ap.add_argument("--wave", type=int, default=24,
                    help="requests in the ragged-prefill admission wave")
    ap.add_argument("--json-out", default="")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving.engine import (
        EngineConfig, InferenceEngine, SamplingParams,
    )

    if args.cpu:
        cfg = dataclasses.replace(MODEL_PRESETS["llama_tiny"],
                                  dtype="float32", param_dtype="float32")
        model = LlamaForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        tok = None
    else:
        from dlti_tpu.checkpoint.export import load_exported_model
        from dlti_tpu.data import ByteTokenizer

        params, full_cfg = load_exported_model(args.export)
        cfg = full_cfg.model
        tok = ByteTokenizer()

    if tok is None:
        # llama_tiny's greedy continuation of [6,6,7,7,...] is a
        # period-1 loop (prompt-lookup heaven); the adversarial prompts
        # wander through distinct tokens for many rounds.
        favorable = [([6, 6, 7, 7] * 4)[: 8 + i] for i in range(4)]
        adversarial = [[2, 7, 1, 8, 2, 8], [11, 13, 17, 19, 23],
                       [10, 20, 30, 40, 50, 60], [19, 28, 37, 46, 55]]
    else:
        block = ("def check_{i}(value):\n"
                 "    if value is None:\n"
                 "        return default\n"
                 "    return transform(value)\n\n")
        favorable = [tok.encode("".join(
            block.replace("{i}", str(i)) for i in range(4)))[:512]
            for _ in range(4)]
        prose = ("the quarterly throughput review considered seventeen "
                 "distinct mitigation strategies across regions, none "
                 "repeated verbatim anywhere in the corpus; ")
        adversarial = [tok.encode(prose * (3 + i))[:256] for i in range(4)]

    def build(spec: bool):
        ec = EngineConfig(
            max_seqs=4, block_size=16,
            num_blocks=max(256, (args.max_tokens + 600) // 16 * 8),
            max_model_len=1024, eos_token_id=-1,
            cache_dtype="float32" if args.cpu else "bfloat16",
            steps_per_sync=args.sync,
            speculative="ngram" if spec else "none",
            num_draft_tokens=args.draft,
        )
        return InferenceEngine(cfg, params, ec)

    sp = SamplingParams(temperature=0.0, max_tokens=args.max_tokens)

    def measure(spec: bool, prompts):
        eng = build(spec)
        # Compile warmup OUTSIDE the measured window: the decode ladder,
        # prefill buckets, and — by running the full measured workload
        # once — every spec-k rung the adaptive controller will visit.
        eng.warmup_decode_ladder()
        eng.generate(prompts, sp)
        rates, toks = [], None
        for _ in range(args.runs):
            t0 = time.perf_counter()
            res = eng.generate(prompts, sp)
            dt = time.perf_counter() - t0
            n = sum(len(r.output_token_ids) for r in res)
            rates.append(n / dt)
            run_toks = [r.output_token_ids for r in res]
            assert toks is None or run_toks == toks, "non-deterministic run"
            toks = run_toks
        return rates, toks, dict(eng.stats)

    def trace(name, prompts):
        plain_rates, plain_toks, _ = measure(False, prompts)
        spec_rates, spec_toks, st = measure(True, prompts)
        # Per-arm outputs-equal assert BEFORE any number is reported.
        assert spec_toks == plain_toks, \
            f"{name}: speculation changed greedy outputs"
        med_p = statistics.median(plain_rates)
        med_s = statistics.median(spec_rates)
        acc = (st["spec_accepted"] / st["spec_proposed"]
               if st.get("spec_proposed") else 0.0)
        return {
            "plain_tok_s_all": [round(r, 1) for r in plain_rates],
            "spec_tok_s_all": [round(r, 1) for r in spec_rates],
            "plain_tok_s_median": round(med_p, 1),
            "spec_tok_s_median": round(med_s, 1),
            "speedup": round(med_s / med_p, 3),
            "draft_acceptance": round(acc, 3),
            "spec_paused_rounds": st.get("spec_paused_rounds", 0),
            "outputs_equal": True,
        }

    # ------------------------------------------------------------------
    # Ragged multi-admission prefill: TTFT over an admission wave
    # ------------------------------------------------------------------
    # Lengths straddling four pow2 buckets: under a chunked-prefill token
    # budget every step carries chunks from several admissions in several
    # buckets — the bucketed path pays one program call per bucket per
    # step, ragged packing merges them, so each step (and therefore every
    # queued request's first token) lands sooner.
    rng = np.random.RandomState(0)
    wave_lens = [(5, 9, 17, 33)[i % 4] for i in range(args.wave)]
    wave_prompts = [
        [int(t) for t in rng.randint(2, cfg.vocab_size - 2, size=n)]
        for n in wave_lens]
    wave_sp = SamplingParams(temperature=0.0, max_tokens=8)

    def ttft_wave(ragged: bool):
        ec = EngineConfig(
            max_seqs=max(8, args.wave), block_size=16, num_blocks=512,
            max_model_len=128, eos_token_id=-1,
            cache_dtype="float32" if args.cpu else "bfloat16",
            max_prefill_tokens_per_step=64,
            ragged_prefill=ragged)
        eng = InferenceEngine(cfg, params, ec)
        eng.generate(wave_prompts, wave_sp)  # compile warmup
        p99s, p50s, toks = [], [], None
        for _ in range(args.runs):
            reqs = [eng.submit(p, wave_sp) for p in wave_prompts]
            first = {}
            t0 = time.perf_counter()
            while eng.has_work:
                eng.step()
                now = time.perf_counter()
                for r in reqs:
                    if r.output_token_ids and r.request_id not in first:
                        first[r.request_id] = now - t0
            lat = sorted(first.values())
            p99s.append(float(np.percentile(lat, 99)))
            p50s.append(float(np.percentile(lat, 50)))
            toks = [r.output_token_ids for r in reqs]
        return (statistics.median(p99s), statistics.median(p50s), toks,
                eng.stats["prefill_batches"])

    p99_off, p50_off, toks_off, batches_off = ttft_wave(False)
    p99_on, p50_on, toks_on, batches_on = ttft_wave(True)
    assert toks_on == toks_off, "ragged packing changed outputs"

    out = {
        "what": "adaptive speculation (per-slot gate + draft-length "
                "ladder) vs plain multi-step at the same steps_per_sync, "
                "on favorable AND adversarial traces; plus ragged "
                "multi-admission prefill TTFT",
        "platform": "cpu/llama_tiny" if args.cpu else f"tpu/{args.export}",
        "steps_per_sync": args.sync, "num_draft_tokens": args.draft,
        "max_tokens": args.max_tokens, "runs": args.runs,
        "favorable": trace("favorable", favorable),
        "adversarial": trace("adversarial", adversarial),
        "ragged_prefill": {
            "wave_requests": args.wave,
            "ttft_p99_s_off": round(p99_off, 4),
            "ttft_p99_s_on": round(p99_on, 4),
            "ttft_p50_s_off": round(p50_off, 4),
            "ttft_p50_s_on": round(p50_on, 4),
            "prefill_batches_off": batches_off,
            "prefill_batches_on": batches_on,
            "outputs_equal": True,
        },
        "date": time.strftime("%Y-%m-%d"),
    }
    out["outputs_equal"] = (out["favorable"]["outputs_equal"]
                            and out["adversarial"]["outputs_equal"]
                            and out["ragged_prefill"]["outputs_equal"])
    name = args.json_out or ("results/spec_adaptive_cpu.json" if args.cpu
                             else "results/spec_adaptive.json")
    with open(name, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
