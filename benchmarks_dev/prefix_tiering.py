#!/usr/bin/env python
"""Prefix-cache tiering microbench (CPU-hermetic): quantify the
HBM → host → disk hierarchy plus cache-affinity routing on a
recurring-session (chat-shaped) workload, and emit one JSON artifact.

* **Engine A/B**: the same session schedule — N sessions, K turns each,
  every turn's prompt a strict extension of the last — runs through two
  engines whose HBM pool is deliberately too small to hold every
  session's prefix at once. Tiering OFF evicts-and-discards, so a
  returning session re-prefills from scratch; tiering ON demotes evicted
  blocks host→disk and restores them with a scatter. Headlines:
  ``prefill_tokens_saved`` (> 0 means restores replaced re-prefill on
  the measured path) and warm-turn wall time, with outputs asserted
  byte-identical between the two engines call-for-call.
* **Serving end-to-end**: a 2-replica tiered fleet behind the admission
  gateway with cache-affinity routing serves the recurring-session
  loadgen (``--sessions``); the report's cold-vs-warm TTFT split and the
  scraped cache hit rate are the serving-level proof, and the replica
  affinity counters show sessions actually stuck to their warm replica.

Run:  JAX_PLATFORMS=cpu python benchmarks_dev/prefix_tiering.py
Artifact: results/prefix_tiering_cpu.json (path override: first CLI arg).
Wired into `pytest -m slow` as a smoke: tests/test_prefix_tiering_bench.py.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The serving section runs 2 replicas on host devices.
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")

SESSIONS = 4
TURNS = 3
SYSTEM_TOKENS = 32       # shared system prompt (4 full blocks of 8)
TURN_TOKENS = 16         # history growth per turn
GEN_TOKENS = 6


def _session_prompt(vocab: int, session: int, turn: int) -> list:
    """Turn ``turn``'s prompt for ``session``: shared system prefix plus
    a growing per-session history — turn t strictly extends turn t-1."""
    system = [(37 * j + 11) % vocab for j in range(SYSTEM_TOKENS)]
    history = [(session * 101 + j * 13 + 7) % vocab
               for j in range((turn + 1) * TURN_TOKENS)]
    return system + history


def _engine(tiered: bool, disk_dir: str):
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine

    mc = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(mc, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(
        max_seqs=1, block_size=8,
        # 15 allocatable blocks vs ~12 per in-flight request: the cached
        # chains of 4 sessions cannot coexist — the pool MUST evict, and
        # only the tiers decide whether that costs a re-prefill later.
        num_blocks=16, max_model_len=96,
        cache_dtype="float32", eos_token_id=-1, enable_prefix_caching=True,
        prefix_host_blocks=8 if tiered else 0,
        prefix_disk_dir=disk_dir if tiered else "",
        prefix_disk_blocks=64 if tiered else 0)
    return InferenceEngine(mc, params, ec), mc


def bench_engine_ab(disk_dir: str) -> dict:
    from dlti_tpu.serving import SamplingParams

    tiered, mc = _engine(True, disk_dir)
    plain, _ = _engine(False, disk_dir)
    sp = SamplingParams(temperature=0.0, max_tokens=GEN_TOKENS)

    walls = {"on": {"cold": [], "warm": []}, "off": {"cold": [], "warm": []}}
    outputs_equal = True
    # Round-robin by turn: between a session's turns, the other sessions'
    # traffic evicts its blocks — exactly the chat fleet access pattern.
    for turn in range(TURNS):
        for s in range(SESSIONS):
            prompt = _session_prompt(mc.vocab_size, s, turn)
            kind = "warm" if turn > 0 else "cold"
            t0 = time.perf_counter()
            [r_on] = tiered.generate([prompt], sp)
            walls["on"][kind].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            [r_off] = plain.generate([prompt], sp)
            walls["off"][kind].append(time.perf_counter() - t0)
            outputs_equal &= (r_on.output_token_ids == r_off.output_token_ids)

    def _mean(xs):
        return round(sum(xs) / len(xs), 6) if xs else 0.0

    saved = plain.stats["prefill_tokens"] - tiered.stats["prefill_tokens"]
    ts = tiered.prefix_cache.tier_store.stats
    return {
        "sessions": SESSIONS, "turns": TURNS,
        "hbm_blocks": 16, "host_blocks": 8, "disk_blocks": 64,
        "outputs_equal": outputs_equal,
        "prefill_tokens_off": plain.stats["prefill_tokens"],
        "prefill_tokens_on": tiered.stats["prefill_tokens"],
        "prefill_tokens_saved": saved,
        "prefix_restored_tokens": tiered.stats["prefix_restored_tokens"],
        "hbm_evictions": tiered.prefix_cache.stats["evictions"],
        "demotions": tiered.prefix_cache.stats["demotions"],
        "tier_traffic": ts,
        "cold_turn_wall_mean_s": {"off": _mean(walls["off"]["cold"]),
                                  "on": _mean(walls["on"]["cold"])},
        "warm_turn_wall_mean_s": {"off": _mean(walls["off"]["warm"]),
                                  "on": _mean(walls["on"]["warm"])},
    }


def bench_serving_e2e(disk_dir: str) -> dict:
    import jax
    import jax.numpy as jnp

    from dlti_tpu.benchmarks.loadgen import LoadGenConfig, run_load_test
    from dlti_tpu.config import GatewayConfig, MODEL_PRESETS
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import (
        EngineConfig, ReplicatedEngine, SamplingParams,
    )
    from dlti_tpu.serving.server import ServerConfig, make_server

    mc = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(mc, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(
        # Per-replica pool of 13 allocatable blocks vs ~4 sessions x up
        # to 10 cached blocks each: warm turns can only stay cheap if
        # evicted chains demote to the tiers and restore on revisit.
        max_seqs=1, block_size=8, num_blocks=14, max_model_len=96,
        cache_dtype="float32", eos_token_id=-1, enable_prefix_caching=True,
        prefix_host_blocks=8, prefix_disk_dir=disk_dir, prefix_disk_blocks=64)
    rep = ReplicatedEngine(mc, params, ec, replicas=2, tensor=1)
    httpd, aeng = make_server(
        rep, IdTokenizer(vocab_size=mc.vocab_size),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=GEN_TOKENS),
                     gateway=GatewayConfig(enabled=True,
                                           max_queued_requests=64,
                                           affinity=True)))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        # concurrency < sessions: the semaphore's FIFO interleaves the
        # fleet's turns (all first turns, then all seconds, ...), so a
        # returning session finds its blocks demoted — the tier restore
        # path is ON the measured TTFT path, not just the engine A/B's.
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=httpd.server_address[1],
            sessions=8, turns=TURNS, reuse_frac=1.0,
            concurrency=4, max_tokens=GEN_TOKENS, temperature=0.0,
            timeout_s=180.0))
        stats = rep.stats
        return {
            "replicas": 2, "sessions": 8, "turns": TURNS,
            "num_ok": report.num_ok, "errors": report.errors,
            "num_cold": report.num_cold, "num_warm": report.num_warm,
            "cold_ttft_p50_s": report.cold_ttft_p50_s,
            "cold_ttft_p90_s": report.cold_ttft_p90_s,
            "warm_ttft_p50_s": report.warm_ttft_p50_s,
            "warm_ttft_p90_s": report.warm_ttft_p90_s,
            "cache_hit_rate": report.cache_hit_rate,
            "prefix_cached_tokens": stats.get("prefix_cached_tokens", 0),
            "prefix_restored_tokens": stats.get("prefix_restored_tokens", 0),
            "affinity": dict(rep.affinity),
        }
    finally:
        httpd.shutdown()
        if httpd.gateway is not None:
            httpd.gateway.shutdown()
        aeng.shutdown()
        httpd.server_close()


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        _repo, "results", "prefix_tiering_cpu.json")
    with tempfile.TemporaryDirectory(prefix="prefix-tiers-") as d1, \
            tempfile.TemporaryDirectory(prefix="prefix-tiers-srv-") as d2:
        engine_ab = bench_engine_ab(d1)
        serving = bench_serving_e2e(d2)
    report = {
        "benchmark": "prefix_tiering_cpu",
        "platform": os.environ.get("JAX_PLATFORMS", "cpu"),
        "engine_ab": engine_ab,
        "serving": serving,
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    ok = (engine_ab["outputs_equal"]
          and engine_ab["prefill_tokens_saved"] > 0
          and engine_ab["prefix_restored_tokens"] > 0
          and serving["num_ok"] > 0
          and not serving["errors"]
          and serving["warm_ttft_p50_s"] < serving["cold_ttft_p50_s"]
          and serving["prefix_restored_tokens"] > 0
          and serving["affinity"]["sticky"] > 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
