"""Build a servable random-init 7B export HOST-SIDE (no chip needed).

Decouples the chip-day serving measurements (occupancy/headline, int8-KV
A/B — both weight-value-independent, as the r03 methodology notes in
``results/serving_7b_report.json``) from the ~2 h chip-bound 7B retrain:
with this export on disk, stages D/E fire the moment the relay answers
instead of waiting behind stage C.

    python benchmarks_dev/make_random_7b_export.py [--out exports/random_7b]
"""

import argparse
import os
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="exports/random_7b")
    ap.add_argument("--model", default="llama2_7b")
    args = ap.parse_args()

    from dlti_tpu.checkpoint.export import export_merged_model
    from dlti_tpu.config import Config, LoRAConfig, MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM

    cfg = Config(model=MODEL_PRESETS[args.model],
                 lora=LoRAConfig(enabled=False))
    model = LlamaForCausalLM(cfg.model, None)
    t0 = time.time()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    n = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(f"init {n/1e9:.2f}B params in {time.time()-t0:.0f}s", flush=True)
    t0 = time.time()
    export_merged_model(args.out, params, cfg, merge_lora=False)
    print(f"exported to {args.out} in {time.time()-t0:.0f}s", flush=True)
    print("EXPORT_OK", flush=True)


if __name__ == "__main__":
    main()
