#!/usr/bin/env python
"""Host-overlap microbench (CPU-hermetic): quantify the host-latency-hiding
layer on both hot paths and emit one JSON artifact.

* **Training**: a tiny model trains twice over the same dataset — prefetch
  off (legacy inline fetch) vs on (``Config.data.prefetch_depth=2``) — with
  a synthetic per-batch host delay standing in for corpus-scale gather/pack
  cost. The metric is *host stall*: time the step thread blocked waiting
  for a batch (the ``train/batch_fetch`` tracer span). With prefetch on the
  gather overlaps the in-flight step, so the stall collapses toward zero.
* **Serving**: the engine decodes twice — dirty tracking off (legacy full
  re-upload every dispatch) vs on (device-resident decode-state cache) —
  and reports host-prep time per dispatch plus the upload counters,
  including a controlled steady-state window where the batch composition is
  fixed and a correct cache must issue ZERO uploads.

Run:  JAX_PLATFORMS=cpu python benchmarks_dev/host_overlap.py
Artifact: results/host_overlap_cpu.json (path override: first CLI arg).
Wired into `pytest -m slow` as a smoke: tests/test_host_overlap_bench.py.
"""

from __future__ import annotations

import json
import os
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

GATHER_DELAY_S = 0.008   # synthetic per-batch host gather/pack cost
TRAIN_STEPS = 12
DECODE_TOKENS = 48


def _make_dataset(delay_s: float):
    from dlti_tpu.data import TokenBatchDataset

    rng = np.random.default_rng(0)
    seqs = [list(map(int, rng.integers(1, 500, size=24)))
            for _ in range(4 * (TRAIN_STEPS + 4))]
    ds = TokenBatchDataset(sequences=seqs, seq_len=32, pad_id=0,
                           micro_batch_size=4, grad_accum_steps=1)

    class SlowGather:
        """Proxy adding a fixed host delay per batch — the stand-in for
        corpus-scale gather/pack/stack cost on the step thread."""

        def steps_per_epoch(self):
            return ds.steps_per_epoch()

        def epoch(self, epoch_idx=0, skip_steps=0):
            for b in ds.epoch(epoch_idx, skip_steps):
                time.sleep(delay_s)
                yield b

    return SlowGather()


def bench_training(prefetch_depth: int) -> dict:
    from dlti_tpu.config import (
        CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
        OptimizerConfig, ParallelConfig, TrainConfig,
    )
    from dlti_tpu.telemetry import configure_tracer
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=32, prefetch_depth=prefetch_depth),
        train=TrainConfig(num_epochs=1, max_steps=TRAIN_STEPS,
                          micro_batch_size=4, grad_accum_steps=1,
                          logging_steps=1000, metrics_csv=os.devnull),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )
    tracer = configure_tracer(enabled=True)
    tracer.clear()
    trainer = Trainer(cfg)
    t0 = time.perf_counter()
    _, record = trainer.train(dataset=_make_dataset(GATHER_DELAY_S))
    wall = time.perf_counter() - t0
    # Chrome-trace events: dur is microseconds.
    stall_us = sum(e.get("dur", 0) for e in tracer.events()
                   if e.get("name") == "train/batch_fetch")
    configure_tracer(enabled=False)
    return {
        "prefetch_depth": prefetch_depth,
        "steps": TRAIN_STEPS,
        "synthetic_gather_delay_s": GATHER_DELAY_S,
        "host_stall_s": round(stall_us / 1e6, 6),
        "wall_s": round(wall, 4),
        "final_loss": round(float(record.final_loss), 6),
    }


def bench_serving(cache_on: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams

    mc = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(mc, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=4, block_size=64, num_blocks=16,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, decode_state_cache=cache_on)
    eng = InferenceEngine(mc, params, ec)
    prompts = [[1, 2, 3, 4], [5, 6, 7], [8, 9, 10, 11], [12, 13]]
    sp = SamplingParams(temperature=0.0, max_tokens=DECODE_TOKENS)
    t0 = time.perf_counter()
    eng.generate(prompts, sp)
    wall = time.perf_counter() - t0

    # Controlled steady-state window: one resident request, fixed batch
    # composition, one block per sequence — every dispatch is CLEAN and a
    # correct cache must upload nothing.
    eng2 = InferenceEngine(mc, params, ec)
    eng2.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=40))
    eng2.step()  # admit + prefill
    eng2.step()  # first decode: uploads the admitted row
    up0 = eng2.stats["decode_state_uploads"]
    for _ in range(10):
        eng2.step()
    clean_window_uploads = eng2.stats["decode_state_uploads"] - up0

    prep = eng.telemetry.host_prep.summary()
    return {
        "decode_state_cache": cache_on,
        "decode_steps": eng.stats["decode_steps"],
        "generated_tokens": eng.stats["generated_tokens"],
        "decode_state_uploads": eng.stats["decode_state_uploads"],
        "decode_state_rows": eng.stats["decode_state_rows"],
        "decode_state_clean_syncs": eng.stats["decode_state_clean_syncs"],
        "clean_window_steps": 10,
        "clean_window_uploads": clean_window_uploads,
        "host_prep_mean_s": prep["mean"],
        "host_prep_p99_s": prep["p99"],
        "wall_s": round(wall, 4),
    }


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        _repo, "results", "host_overlap_cpu.json")
    train_off = bench_training(prefetch_depth=0)
    train_on = bench_training(prefetch_depth=2)
    serve_off = bench_serving(cache_on=False)
    serve_on = bench_serving(cache_on=True)
    stall_off, stall_on = train_off["host_stall_s"], train_on["host_stall_s"]
    report = {
        "benchmark": "host_overlap_cpu",
        "platform": os.environ.get("JAX_PLATFORMS", "cpu"),
        "train": {
            "prefetch_off": train_off,
            "prefetch_on": train_on,
            "stall_reduction": round(1.0 - stall_on / stall_off, 4)
            if stall_off > 0 else 0.0,
        },
        "serving": {
            "reupload": serve_off,
            "dirty_tracking": serve_on,
        },
    }
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    ok = (stall_on < stall_off
          and serve_on["clean_window_uploads"] == 0
          and train_on["final_loss"] == train_off["final_loss"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
