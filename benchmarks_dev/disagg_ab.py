"""Prefill/decode disaggregation A/B under mixed long-prefill load.

The claim to prove (or honestly demote): at EQUAL total replica count and
EQUAL total KV blocks, splitting the fleet into a prefill pool and a
decode pool removes prefill→decode interference — short requests' decode
TPOT p99 stops inflating when long prompts are in flight — while outputs
stay byte-identical (the paged-KV handoff carries exact state).

  A (colocated):     ReplicatedEngine, R replicas, each prefills + decodes
  B (disaggregated): DisaggController, R/2 prefill + R/2 decode replicas,
                     concurrent pool stepping (prefill thread overlaps
                     decode dispatch — the production --disagg serve mode)

Engine-direct (no server/HTTP noise), open-loop paced arrivals: a steady
stream of short chat-shaped prompts with periodic long documents
interleaved. Greedy, so outputs_equal is a hard byte comparison.

  python benchmarks_dev/disagg_ab.py            # CPU mechanism check
  python benchmarks_dev/disagg_ab.py --runs 5
"""

import argparse
import json
import os
import statistics
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")


def _percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[i]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--shorts", type=int, default=48,
                    help="short requests per run")
    ap.add_argument("--longs", type=int, default=6,
                    help="long-prompt requests interleaved per run")
    ap.add_argument("--short-prompt-tokens", type=int, default=16)
    ap.add_argument("--long-prompt-tokens", type=int, default=448)
    ap.add_argument("--max-tokens", type=int, default=24)
    ap.add_argument("--short-gap-ms", type=float, default=8.0,
                    help="arrival gap between short requests")
    ap.add_argument("--json-out", default="results/disagg_cpu.json")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import (
        DisaggController, EngineConfig, ReplicatedEngine, SamplingParams,
    )

    cfg = MODEL_PRESETS["llama_tiny"]
    params = LlamaForCausalLM(cfg, None).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=8, block_size=16, num_blocks=128,
                      max_model_len=512, cache_dtype="float32",
                      eos_token_id=-1)
    sp = SamplingParams(max_tokens=args.max_tokens, temperature=0.0)
    devices = jax.devices()[:2]

    # Mixed schedule: shorts arrive on a steady clock; every
    # shorts/longs-th slot a long document lands alongside. Prompts are
    # per-request distinct (no accidental prefix-cache collapse) and
    # identical across arms (outputs_equal compares token-for-token).
    V = cfg.vocab_size
    schedule = []  # (t_offset_s, prompt, is_long)
    gap = args.short_gap_ms / 1000.0
    every = max(1, args.shorts // max(1, args.longs))
    for i in range(args.shorts):
        prompt = [(7 + 13 * i + j) % V for j in range(args.short_prompt_tokens)]
        schedule.append((i * gap, prompt, False))
        if i % every == 0 and i // every < args.longs:
            lp = [(3 + 5 * i + j) % V for j in range(args.long_prompt_tokens)]
            schedule.append((i * gap + gap / 2, lp, True))
    schedule.sort(key=lambda s: s[0])

    def drive(engine, concurrent):
        """Open-loop: submit per schedule while stepping; returns
        [(request, is_long)] after full drain."""
        reqs = []
        i = 0
        t0 = time.monotonic()
        while i < len(schedule) or engine.has_work:
            now = time.monotonic() - t0
            while i < len(schedule) and schedule[i][0] <= now:
                r = engine.submit(schedule[i][1], sp)
                reqs.append((r, schedule[i][2]))
                i += 1
            if engine.has_work:
                engine.step()
            elif i < len(schedule):
                time.sleep(min(0.001, schedule[i][0] - now))
        return reqs

    def warm(engine):
        # Compile every program both arms will hit (prefill buckets for
        # short and long prompts on every engine, decode ladder, and the
        # handoff restore fn) before any timed run.
        engine.warmup_decode_ladder()
        engines = (engine.engines if hasattr(engine, "engines")
                   else engine.prefill.engines + engine.decode.engines)
        for k in range(2 * len(engines)):
            pl = (args.long_prompt_tokens if k % 2
                  else args.short_prompt_tokens)
            engine.submit([1 + k] * pl, SamplingParams(max_tokens=4))
        while engine.has_work:
            engine.step()

    def tpots_ms(reqs, want_long):
        out = []
        for r, is_long in reqs:
            n = len(r.output_token_ids)
            if (is_long != want_long or r.finish_reason == "error"
                    or r.first_token_time is None or n < 2):
                continue
            out.append((r.finish_time - r.first_token_time) / (n - 1) * 1e3)
        return out

    def outputs_of(reqs):
        return [r.output_token_ids for r, _ in reqs]

    results = {"arms": {"colocated": [], "disagg": []}, "runs": args.runs}
    baseline_outputs = None
    outputs_equal = True
    handoff_totals = {"completed": 0, "bytes": 0}

    for run in range(args.runs):
        # A: colocated, 2 replicas.
        rep = ReplicatedEngine(cfg, params, ec, replicas=2, tensor=1,
                               devices=devices)
        warm(rep)
        reqs_a = drive(rep, concurrent=False)
        # B: disaggregated, 1 prefill + 1 decode, concurrent stepping.
        ctl = DisaggController(cfg, params, ec, prefill_replicas=1,
                               decode_replicas=1, devices=devices)
        warm(ctl)
        ctl.start()
        try:
            reqs_b = drive(ctl, concurrent=True)
        finally:
            ctl.stop()

        out_a, out_b = outputs_of(reqs_a), outputs_of(reqs_b)
        if out_a != out_b:
            outputs_equal = False
        if baseline_outputs is None:
            baseline_outputs = out_a
        elif baseline_outputs != out_a:
            outputs_equal = False

        for name, reqs in (("colocated", reqs_a), ("disagg", reqs_b)):
            short = tpots_ms(reqs, want_long=False)
            results["arms"][name].append({
                "run": run,
                "short_tpot_p50_ms": round(_percentile(short, 50), 3),
                "short_tpot_p99_ms": round(_percentile(short, 99), 3),
                "short_tpot_mean_ms": (round(statistics.mean(short), 3)
                                       if short else 0.0),
                "long_tpot_p50_ms": round(
                    _percentile(tpots_ms(reqs, want_long=True), 50), 3),
                "num_short_ok": len(short),
            })
        ka = ctl.stats["kv_handoff"]
        handoff_totals["completed"] += ka["completed"]
        handoff_totals["bytes"] += ka["bytes"]
        print(f"run {run}: colocated short p99="
              f"{results['arms']['colocated'][-1]['short_tpot_p99_ms']}ms  "
              f"disagg short p99="
              f"{results['arms']['disagg'][-1]['short_tpot_p99_ms']}ms  "
              f"handoffs={ka['completed']} outputs_equal={out_a == out_b}")

    # Median-of-runs headline (robust to one noisy CPU run).
    p99_a = statistics.median(
        r["short_tpot_p99_ms"] for r in results["arms"]["colocated"])
    p99_b = statistics.median(
        r["short_tpot_p99_ms"] for r in results["arms"]["disagg"])
    improvement = (p99_a - p99_b) / p99_a if p99_a else 0.0
    from dlti_tpu.serving.disagg import handoff_seconds

    h = handoff_seconds.summary()
    report = {
        "benchmark": "disagg_ab",
        "platform": jax.devices()[0].platform,
        "workload": {
            "shorts": args.shorts, "longs": args.longs,
            "short_prompt_tokens": args.short_prompt_tokens,
            "long_prompt_tokens": args.long_prompt_tokens,
            "max_tokens": args.max_tokens,
            "short_gap_ms": args.short_gap_ms,
        },
        "arms": results["arms"],
        "decode_tpot_p99_ms": {"colocated": p99_a, "disagg": p99_b},
        "decode_tpot_p99_improvement": round(improvement, 4),
        "outputs_equal": outputs_equal,
        "kv_handoff": {
            "completed_total": handoff_totals["completed"],
            "bytes_total": handoff_totals["bytes"],
            "mean_bytes_per_handoff": (
                handoff_totals["bytes"] // handoff_totals["completed"]
                if handoff_totals["completed"] else 0),
            "latency_histogram": h,
        },
    }
    assert outputs_equal, "disagg arm outputs diverged from colocated arm"
    os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
    with open(args.json_out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"\ndecode TPOT p99: colocated {p99_a}ms -> disagg {p99_b}ms "
          f"({improvement:+.1%}); outputs_equal={outputs_equal}")
    print(f"report -> {args.json_out}")


if __name__ == "__main__":
    main()
