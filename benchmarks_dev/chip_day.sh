#!/bin/bash
# Chip-day orchestrator (r04): run every chip-dependent measurement in
# value order the moment the relay comes back, each stage bounded and
# resumable (stages skip when their artifact already exists; rm the
# artifact to re-run). Survives relay wedges: every chip call is under
# `timeout`, and a failed stage doesn't block the next.
#
#   bash benchmarks_dev/chip_day.sh            # all stages
#   bash benchmarks_dev/chip_day.sh A B        # just stages A, B
#
# Stages:
#   A  bench.py (the #1 verdict item: driver-verifiable >=60% MFU)
#   B  speculation win on the trained 300M export (favorable workload)
#   C  7B retrain (~120 steps) + host-side consolidated export
#   D  serve 7B int8 + loadgen headline (28 slots, K=64) x5 + occupancy
#   E  int8 KV A/B at fixed HBM (bf16@20 slots vs int8@40 slots)
set -u
cd "$(dirname "$0")/.."
mkdir -p results
STAGES=${@:-A B C D E}

probe() {
  timeout 240 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

log() { echo "[chip_day $(date +%H:%M:%S)] $*"; }

if ! probe; then
  log "relay probe FAILED - chip still unreachable; aborting"
  exit 3
fi
log "relay probe ok"

for s in $STAGES; do case $s in
A)
  if [ -s results/bench_r04_local.json ]; then log "A: exists, skip"; continue; fi
  log "A: bench.py (MFU headline)"
  BENCH_DEADLINE_S=1500 timeout 1700 python bench.py \
      2> results/bench_r04_local.err | tail -1 > results/bench_r04_local.json
  log "A: $(cat results/bench_r04_local.json)"
  ;;
B)
  if [ -s results/speculative_win.json ]; then log "B: exists, skip"; continue; fi
  log "B: speculation win (300M export, repetitive workload)"
  timeout 2400 python benchmarks_dev/spec_win.py --runs 4 \
      > results/spec_win_stage.log 2>&1
  tail -3 results/spec_win_stage.log
  ;;
C)
  if [ -d exports/glaive_7b_r04 ]; then log "C: exists, skip"; continue; fi
  log "C: 7B retrain (~120 steps) + export (host-side)"
  [ -d data/glaive_synth ] || timeout 900 python scripts/prepare_dataset.py \
      --synthetic 20000 --output-dir data/glaive_synth > /dev/null 2>&1
  timeout 5400 python scripts/train.py --model llama2_7b \
      --dataset-path data/glaive_synth --lora-r 16 \
      --quantize-base int8 --remat-policy none --per-device-batch-size 4 \
      --steps-per-sync 10 --max-steps 120 --save-steps 120 \
      --output-dir checkpoints/glaive_7b_r04 \
      2>&1 | tail -5
  timeout 3600 python scripts/export_from_checkpoint.py \
      --checkpoint-dir checkpoints/glaive_7b_r04 --model llama2_7b \
      --lora-r 16 --quantize-base int8 --out exports/glaive_7b_r04 \
      2>&1 | tail -2
  ;;
D)
  if [ -s results/serving_headline_r04.json ]; then log "D: exists, skip"; continue; fi
  if [ ! -d exports/glaive_7b_r04 ]; then log "D: no 7B export (run C)"; continue; fi
  log "D: serve 7B int8 + loadgen headline x5"
  timeout 900 python scripts/serve.py --model-dir exports/glaive_7b_r04 \
      --quantization int8 --max-seqs 28 --num-blocks 910 --block-size 16 \
      --max-model-len 512 --steps-per-sync 64 --port 8077 \
      > results/serve_r04.log 2>&1 &
  SRV=$!
  for i in $(seq 90); do
    sleep 10
    grep -q "serving on" results/serve_r04.log && break
  done
  if ! grep -q "serving on" results/serve_r04.log; then
    log "D: server never came up"; kill $SRV 2>/dev/null; continue
  fi
  for run in 1 2 3 4 5; do
    timeout 900 python scripts/benchmark_serving.py --port 8077 \
        --num-requests 112 --concurrency 56 --max-tokens 256 --no-stream \
        --json-out results/serving_headline_r04_run$run.json 2>&1 | tail -1
  done
  timeout 60 curl -s http://127.0.0.1:8077/stats > results/serving_r04_stats.json
  kill $SRV 2>/dev/null
  python - <<'PY'
import json, statistics
runs = []
for i in range(1, 6):
    try:
        runs.append(json.load(open(f"results/serving_headline_r04_run{i}.json")))
    except Exception:
        pass
rates = [r["output_tokens_per_s"] for r in runs if "output_tokens_per_s" in r]
st = json.load(open("results/serving_r04_stats.json"))
occ = (st.get("decode_slot_steps", 0)
       / max(1, 28 * st.get("decode_steps", 1)))
out = {"what": "r04 serving headline re-measurement after the budget-"
              "clamped windows + per-step occupancy accounting",
       "runs_tok_s": rates,
       "warm_median_tok_s": statistics.median(rates[1:]) if len(rates) > 1 else None,
       "occupancy": round(occ, 4), "stats": st}
json.dump(out, open("results/serving_headline_r04.json", "w"), indent=1)
print(json.dumps({k: out[k] for k in ("runs_tok_s", "warm_median_tok_s", "occupancy")}))
PY
  ;;
E)
  if [ -s results/int8_kv_ab_r04.json ]; then log "E: exists, skip"; continue; fi
  if [ ! -d exports/glaive_7b_r04 ]; then log "E: no 7B export (run C)"; continue; fi
  log "E: int8 KV A/B at fixed HBM (bf16@20 vs int8@40 slots)"
  timeout 5400 python benchmarks_dev/int8_kv_ab.py --export exports/glaive_7b_r04 \
      2>&1 | tail -3
  ;;
esac; done
log "done"
