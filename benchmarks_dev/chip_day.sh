#!/bin/bash
# Chip-day orchestrator (r05): run every chip-dependent measurement in
# value order the moment the relay comes back, each stage bounded and
# resumable (stages skip when their artifact already exists; rm the
# artifact to re-run). Survives relay wedges: every chip call is under
# `timeout`, and a failed stage doesn't block the next.
#
#   bash benchmarks_dev/chip_day.sh            # all stages
#   bash benchmarks_dev/chip_day.sh A C        # just stages A, C
#
# Stages (r05 order = VERDICT r04 priority; D/E use the host-built
# random-init 7B export (benchmarks_dev/make_random_7b_export.py —
# serving throughput is weight-value-independent, the r03 methodology)
# so they no longer wait behind the ~2 h chip-bound retrain):
#   A  bench.py x3 (the #1 verdict item: >=60% MFU, local verification
#      ahead of the driver's official run)
#   D  serve 7B int8 + loadgen headline (28 slots, K=64) x5 + occupancy
#      (budget-clamped windows fix, CPU-verified in r04, measured here)
#   E  int8 KV A/B at fixed HBM (bf16@20 slots vs int8@40 slots)
#   C  7B retrain (~120 steps) + host-side consolidated export
#   F  pretrained-7B convergence: fine-tune from the stage-C export
#      (VERDICT r04 missing-item #2; needs the TRAINED export)
#   B  speculation win on the trained 300M export (favorable workload)
set -u
cd "$(dirname "$0")/.."
mkdir -p results
STAGES=${@:-A D E C F B}

# Servable 7B export for the weight-independent stages: the trained one
# when stage C has run, else the host-built random-init one.
serving_export() {
  if [ -d exports/glaive_7b_r05 ]; then echo exports/glaive_7b_r05;
  elif [ -d exports/random_7b ]; then echo exports/random_7b;
  else echo ""; fi
}

probe() {
  timeout 240 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

log() { echo "[chip_day $(date +%H:%M:%S)] $*"; }

if ! probe; then
  log "relay probe FAILED - chip still unreachable; aborting"
  exit 3
fi
log "relay probe ok"

for s in $STAGES; do case $s in
A)
  # No outer skip: the per-run check below resumes exactly the runs
  # that are missing (an outer run3-only check would never retry a
  # failed run1/run2).
  log "A: bench.py x3 (MFU headline; relay variance demands repeats)"
  for run in 1 2 3; do
    # Resume skip: only a non-error result counts as done.
    if [ -s results/bench_r05_local_run$run.json ] \
        && ! grep -q '"error"' results/bench_r05_local_run$run.json; then
      continue
    fi
    BENCH_DEADLINE_S=1500 timeout 1700 python bench.py \
        2> results/bench_r05_local_run$run.err \
        | tail -1 > results/bench_r05_local_run$run.json
    log "A run$run: $(cat results/bench_r05_local_run$run.json)"
  done
  ;;
C)
  if [ -d exports/glaive_7b_r05 ]; then log "C: exists, skip"; continue; fi
  log "C: 7B retrain (~120 steps) + export (host-side)"
  [ -d data/glaive_synth ] || timeout 900 python scripts/prepare_dataset.py \
      --synthetic 20000 --output-dir data/glaive_synth > /dev/null 2>&1
  timeout 5400 python scripts/train.py --model llama2_7b \
      --dataset-path data/glaive_synth --lora-r 16 \
      --quantize-base int8 --remat-policy none --per-device-batch-size 4 \
      --steps-per-sync 10 --max-steps 120 --save-steps 120 \
      --output-dir checkpoints/glaive_7b_r05 \
      --metrics-csv results/training_metrics_7b_r05.csv \
      2>&1 | tail -5
  timeout 3600 python scripts/export_from_checkpoint.py \
      --checkpoint-dir checkpoints/glaive_7b_r05 --model llama2_7b \
      --lora-r 16 --quantize-base int8 --out exports/glaive_7b_r05 \
      2>&1 | tail -2
  ;;
D)
  if [ -s results/serving_headline_r05.json ]; then log "D: exists, skip"; continue; fi
  EXP=$(serving_export)
  if [ -z "$EXP" ]; then
    # Host-side build, no chip needed (~10 min): never let the serving
    # headline (#1 verdict item after bench) wait behind stage C.
    log "D: no servable 7B export; building random-init export host-side"
    timeout 2400 python benchmarks_dev/make_random_7b_export.py \
        > results/make_random_7b.log 2>&1
    EXP=$(serving_export)
  fi
  if [ -z "$EXP" ]; then log "D: export build failed (results/make_random_7b.log)"; continue; fi
  log "D: serve 7B int8 ($EXP) + loadgen headline x5"
  # Stale run files from a previous (possibly different-export)
  # invocation must not backfill this one's aggregate.
  rm -f results/serving_headline_r05_run*.json
  # Server timeout covers load+compile (~5 min) + readiness wait + five
  # loadgen runs; the stage kills it explicitly when done.
  timeout 7200 python scripts/serve.py --model-dir "$EXP" \
      --quantization int8 --max-seqs 28 --num-blocks 910 --block-size 16 \
      --max-model-len 512 --steps-per-sync 64 --port 8077 \
      > results/serve_r05.log 2>&1 &
  SRV=$!
  for i in $(seq 90); do
    sleep 10
    grep -q "serving on" results/serve_r05.log && break
  done
  if ! grep -q "serving on" results/serve_r05.log; then
    log "D: server never came up"; kill $SRV 2>/dev/null; continue
  fi
  for run in 1 2 3 4 5; do
    timeout 900 python scripts/benchmark_serving.py --port 8077 \
        --num-requests 112 --concurrency 56 --max-tokens 256 --no-stream \
        --json-out results/serving_headline_r05_run$run.json 2>&1 | tail -1
  done
  timeout 60 curl -s http://127.0.0.1:8077/stats > results/serving_r05_stats.json
  kill $SRV 2>/dev/null
  CHIP_DAY_EXPORT="$EXP" python - <<'PY'
import json, os, statistics
runs = []
for i in range(1, 6):
    try:
        runs.append(json.load(open(f"results/serving_headline_r05_run{i}.json")))
    except Exception:
        pass
rates = [r["output_tokens_per_s"] for r in runs if "output_tokens_per_s" in r]
if not rates:
    # All runs failed (relay wedge mid-stage): write NOTHING so the
    # [ -s ] resume check retries the stage next invocation.
    raise SystemExit("no successful runs; leaving stage D incomplete")
st = json.load(open("results/serving_r05_stats.json"))
occ = (st.get("decode_slot_steps", 0)
       / max(1, 28 * st.get("decode_steps", 1)))
out = {"what": "r05 serving headline with budget-clamped windows + "
              "per-step occupancy accounting (x5, all runs reported). "
              "NOTE which export was served: random weights decode the "
              "full token budget (no early EOS), trained weights may "
              "stop early — rates are only comparable per-export.",
       "export": os.environ.get("CHIP_DAY_EXPORT", "?"),
       "runs_tok_s": rates,
       "warm_median_tok_s": statistics.median(rates[1:]) if len(rates) > 1 else None,
       "occupancy": round(occ, 4), "stats": st}
json.dump(out, open("results/serving_headline_r05.json", "w"), indent=1)
print(json.dumps({k: out[k] for k in ("runs_tok_s", "warm_median_tok_s", "occupancy")}))
PY
  ;;
F)
  if [ -s results/convergence_7b_pretrained_tpu.json ]; then log "F: exists, skip"; continue; fi
  if [ ! -d exports/glaive_7b_r05 ]; then log "F: no 7B export (run C)"; continue; fi
  log "F: pretrained-7B convergence (fine-tune from stage-C export)"
  timeout 5400 python benchmarks_dev/pretrained_7b_convergence.py \
      --export exports/glaive_7b_r05 2>&1 | tail -3
  ;;
E)
  if [ -s results/int8_kv_ab_r05.json ]; then log "E: exists, skip"; continue; fi
  EXP=$(serving_export)
  if [ -z "$EXP" ]; then log "E: no servable 7B export (run make_random_7b_export.py or C)"; continue; fi
  log "E: int8 KV A/B at fixed HBM (bf16@20 vs int8@40 slots, $EXP)"
  timeout 5400 python benchmarks_dev/int8_kv_ab.py --export "$EXP" \
      --json-out results/int8_kv_ab_r05.json 2>&1 | tail -3
  ;;
B)
  if [ -s results/speculative_win.json ]; then log "B: exists, skip"; continue; fi
  log "B: speculation win (300M export, repetitive workload)"
  timeout 2400 python benchmarks_dev/spec_win.py --runs 4 \
      > results/spec_win_stage.log 2>&1
  tail -3 results/spec_win_stage.log
  ;;
esac; done
log "done"
