"""int8 KV cache A/B at FIXED KV HBM (VERDICT r03 #5).

The claim to prove (or honestly demote): halving KV bytes buys double
the decode slots, which buys throughput. Both arms get the SAME KV pool
byte budget; the int8 arm spends it on 2x the slots:

  A: bf16 KV, 20 slots,  N blocks
  B: int8 KV, 40 slots, 2N blocks  (same bytes: int8 = half + scales)

Engine-direct (no server/link noise in scheduling), deep queue, greedy,
fixed-length outputs, >= 3 repeats per arm, all runs reported.

  python benchmarks_dev/int8_kv_ab.py --export exports/glaive_7b_r05
  python benchmarks_dev/int8_kv_ab.py --cpu          # mechanism check
"""

import argparse
import json
import os
import statistics
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)
os.chdir(_repo)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--export", default="exports/glaive_7b_r05")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--requests", type=int, default=112)
    ap.add_argument("--max-tokens", type=int, default=256)
    ap.add_argument("--sync", type=int, default=64)
    ap.add_argument("--json-out", default="",
                    help="output path (default: results/int8_kv_ab_{cpu,r05}.json)")
    ap.add_argument("--blocks", type=int, default=455,
                    help="bf16-arm block count (int8 arm gets 2x)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import dataclasses
    import jax.numpy as jnp

    from dlti_tpu.serving.engine import (
        EngineConfig, InferenceEngine, SamplingParams,
    )

    if args.cpu:
        from dlti_tpu.config import MODEL_PRESETS
        from dlti_tpu.models import LlamaForCausalLM

        cfg = dataclasses.replace(MODEL_PRESETS["llama_tiny"],
                                  dtype="float32", param_dtype="float32")
        params = LlamaForCausalLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        lora = None
        quant = False
        args.requests, args.max_tokens, args.sync, args.blocks = 24, 32, 8, 64
        slots_a, slots_b = 4, 8
    else:
        from dlti_tpu.checkpoint.export import load_exported_model
        from dlti_tpu.models.quantization import quantize_params_int8

        params, full_cfg = load_exported_model(args.export)
        cfg = full_cfg.model
        lora = full_cfg.lora if full_cfg.lora.enabled else None
        params = quantize_params_int8(params, donate=True)  # int8 weights
        quant = True
        slots_a, slots_b = 20, 40

    prompt_base = list(range(5, 69))  # 64-token prompt

    def measure(kv_dtype, slots, blocks):
        ec = EngineConfig(
            max_seqs=slots, block_size=16, num_blocks=blocks,
            max_model_len=512, eos_token_id=-1,
            cache_dtype=kv_dtype if not args.cpu else (
                "int8" if kv_dtype == "int8" else "float32"),
            steps_per_sync=args.sync)
        eng = InferenceEngine(cfg, params, ec, lora)
        sp = SamplingParams(temperature=0.0, max_tokens=args.max_tokens)
        # compile warmup
        eng.generate([prompt_base[:8]], SamplingParams(temperature=0.0,
                                                       max_tokens=2))
        eng.warmup_decode_ladder()
        rates = []
        for r in range(args.runs):
            prompts = [prompt_base[: 16 + (i % 48)]
                       for i in range(args.requests)]
            t0 = time.perf_counter()
            res = eng.generate(prompts, sp)
            dt = time.perf_counter() - t0
            n = sum(len(x.output_token_ids) for x in res)
            rates.append(round(n / dt, 1))
            print(f"  {kv_dtype}@{slots}: run {r}: {rates[-1]} tok/s",
                  flush=True)
        st = dict(eng.stats)
        occ = st["decode_slot_steps"] / max(1, slots * st["decode_steps"])
        del eng
        return rates, round(occ, 4)

    a_rates, a_occ = measure("bfloat16", slots_a, args.blocks)
    b_rates, b_occ = measure("int8", slots_b, args.blocks * 2)

    med_a, med_b = statistics.median(a_rates), statistics.median(b_rates)
    out = {
        "what": "int8 KV A/B at fixed KV pool bytes: bf16 KV with S slots "
                "vs int8 KV (half bytes/token + fp32 scales) with 2S slots "
                "and 2x blocks; engine-direct deep queue, greedy, "
                "fixed-length outputs",
        "platform": "cpu/llama_tiny" if args.cpu else f"tpu/{args.export}",
        "arm_a": {"kv": "bfloat16", "slots": slots_a, "blocks": args.blocks,
                  "runs_tok_s": a_rates, "median": med_a, "occupancy": a_occ},
        "arm_b": {"kv": "int8", "slots": slots_b, "blocks": args.blocks * 2,
                  "runs_tok_s": b_rates, "median": med_b, "occupancy": b_occ},
        "speedup_b_over_a": round(med_b / med_a, 3),
        "int8_weights": quant,
        "steps_per_sync": args.sync, "max_tokens": args.max_tokens,
        "requests": args.requests, "date": "2026-08-01",
    }
    name = args.json_out or ("results/int8_kv_ab_cpu.json" if args.cpu
                             else "results/int8_kv_ab_r05.json")
    with open(name, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
