// dlti_tpu native runtime: KV block allocator core.
//
// The reference outsources all native runtime code to external wheels
// (torch/NCCL/DeepSpeed ops — SURVEY.md §2b); this is the in-tree TPU-side
// equivalent for the serving engine's hot host path: block allocation runs
// between every decode step, so it must never contend with Python object
// churn. Exposed through a C ABI consumed via ctypes
// (dlti_tpu/utils/native.py); contract tested against the pure-Python
// fallback in tests/test_serving.py.
//
// Block 0 is reserved as the trash block (inactive decode slots write
// there); the allocator never hands it out.

#include <cstdint>
#include <mutex>
#include <vector>

namespace {

struct Allocator {
  std::mutex mu;
  std::vector<int32_t> free_list;  // LIFO for cache locality
  std::vector<uint8_t> live;       // live[b]: handed out, not yet freed
  int32_t num_blocks;
};

}  // namespace

extern "C" {

void* dlti_allocator_create(int32_t num_blocks) {
  if (num_blocks < 2) return nullptr;
  auto* a = new Allocator();
  a->num_blocks = num_blocks;
  a->live.assign(num_blocks, 0);
  a->free_list.reserve(num_blocks - 1);
  // Matches the Python fallback: pop() yields ascending block ids first.
  for (int32_t b = num_blocks - 1; b >= 1; --b) a->free_list.push_back(b);
  return a;
}

void dlti_allocator_destroy(void* handle) {
  delete static_cast<Allocator*>(handle);
}

int32_t dlti_allocator_num_free(void* handle) {
  auto* a = static_cast<Allocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  return static_cast<int32_t>(a->free_list.size());
}

// All-or-nothing: returns 1 and fills `out[n]` on success, 0 otherwise.
int32_t dlti_allocator_allocate(void* handle, int32_t n, int32_t* out) {
  auto* a = static_cast<Allocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  if (n < 0 || static_cast<size_t>(n) > a->free_list.size()) return 0;
  for (int32_t i = 0; i < n; ++i) {
    out[i] = a->free_list.back();
    a->free_list.pop_back();
    a->live[out[i]] = 1;
  }
  return 1;
}

void dlti_allocator_free(void* handle, int32_t n, const int32_t* blocks) {
  auto* a = static_cast<Allocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  for (int32_t i = 0; i < n; ++i) {
    int32_t b = blocks[i];
    if (b >= 1 && b < a->num_blocks) {
      a->free_list.push_back(b);
      a->live[b] = 0;
    }
  }
}

// Guarded free: O(1) live-flag check per block. Returns 1 and frees the
// whole batch, or returns 0 and frees NOTHING if any id is out of range,
// not currently allocated (double free), or duplicated within the batch —
// mirroring the Python free-list guard: a silent double free would hand
// one block to two sequences and corrupt their KV far from the cause.
int32_t dlti_allocator_free_checked(void* handle, int32_t n,
                                    const int32_t* blocks) {
  auto* a = static_cast<Allocator*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  for (int32_t i = 0; i < n; ++i) {
    int32_t b = blocks[i];
    if (b < 1 || b >= a->num_blocks || !a->live[b]) {
      for (int32_t j = 0; j < i; ++j) a->live[blocks[j]] = 1;  // roll back
      return 0;
    }
    a->live[b] = 0;  // also catches duplicates within this batch
  }
  for (int32_t i = 0; i < n; ++i) a->free_list.push_back(blocks[i]);
  return 1;
}

}  // extern "C"
