// Sequence-packing assignment: the host-side hot loop of the data
// pipeline, in C++. Matches dlti_tpu.data.pipeline.pack_sequences'
// greedy windowed first-fit semantics exactly (same placements, same
// segment ids) — the Python implementation remains as the fallback and
// the differential-test oracle.
//
// Only the *assignment* runs here (O(docs * open_rows) scalar work that
// dominates in Python); the token scatter into the packed matrix is a
// single vectorized numpy put on the Python side.

#include <algorithm>
#include <cstdint>
#include <deque>
#include <vector>

extern "C" {

// doc_lens: per-document token counts (callers pre-truncate to seq_len).
// Outputs (all length n_docs): row index, start column, 1-based segment id
// within the row. Returns the number of packed rows.
int32_t dlti_pack_assign(const int64_t* doc_lens, int32_t n_docs,
                         int32_t seq_len, int32_t open_rows,
                         int32_t* out_row, int32_t* out_col,
                         int32_t* out_seg) {
  std::vector<int32_t> row_len;
  std::vector<int32_t> row_last_seg;
  std::deque<int32_t> open;  // still-open rows, oldest first
  row_len.reserve(n_docs);
  row_last_seg.reserve(n_docs);

  for (int32_t d = 0; d < n_docs; ++d) {
    const int32_t L =
        static_cast<int32_t>(std::min<int64_t>(doc_lens[d], seq_len));
    bool placed = false;
    for (auto it = open.begin(); it != open.end(); ++it) {
      const int32_t r = *it;
      if (row_len[r] + L <= seq_len) {
        out_row[d] = r;
        out_col[d] = row_len[r];
        out_seg[d] = ++row_last_seg[r];
        row_len[r] += L;
        if (row_len[r] == seq_len) open.erase(it);
        placed = true;
        break;
      }
    }
    if (!placed) {
      const int32_t r = static_cast<int32_t>(row_len.size());
      row_len.push_back(L);
      row_last_seg.push_back(1);
      out_row[d] = r;
      out_col[d] = 0;
      out_seg[d] = 1;
      open.push_back(r);
      if (static_cast<int32_t>(open.size()) > open_rows) open.pop_front();
    }
  }
  return static_cast<int32_t>(row_len.size());
}

}  // extern "C"
