#!/usr/bin/env python
"""Render a flight-record dump into a human-readable incident summary.

The reading half of the flight recorder
(``dlti_tpu/telemetry/flightrecorder.py``): point it at a ``flight-*/``
directory — or at the parent dir, where it picks the newest dump — and it
prints what an on-call human needs first: why the process died, the last
completed step, the phase active at death, the final span timeline, the
watchdog alerts that preceded it, and whether any of the evidence is
truncated (dropped span events) or damaged (manifest digest mismatch).

Usage:
    python scripts/postmortem.py runs/flightrecords            # newest
    python scripts/postmortem.py runs/flightrecords/flight-step00000042
    python scripts/postmortem.py ... --spans 30                # longer tail
    python scripts/postmortem.py ... --json                    # machine-readable
    python scripts/postmortem.py runs/flightrecords --all      # elastic job:
        # one incident summary across every per-rank flight-*-gG-rR dump
    python scripts/postmortem.py runs/flightrecords --all      # fleet run:
        # also walks one level of subdirs (worker0/, worker1/, ...) — the
        # per-process dump namespaces a multi-process serving fleet writes
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.telemetry.flightrecorder import (  # noqa: E402
    list_dumps, load_dump, verify_dump,
)

# Metrics promoted into the summary when present (everything else is in
# metrics.json for the deep read).
_KEY_METRICS = (
    "train_step", "train_loss", "train_tokens_per_s", "train_step_time_s",
    "ckpt_save_retries", "ckpt_corrupt_skipped", "ckpt_last_verified_step",
    "requests", "generated_tokens", "active_seqs", "waiting", "free_blocks",
    "gateway_queue_depth", "gateway_inflight", "preemptions",
    "trace_dropped_events",
    # Numeric-fault sentinel (dlti_tpu.training.sentinel).
    "sentinel_nonfinite_steps", "sentinel_loss_spikes",
    "sentinel_grad_spikes", "sentinel_skipped_updates",
    "sentinel_rollbacks", "sentinel_quarantined_windows",
    "sentinel_windows_skipped", "sdc_probes", "sdc_mismatches",
    "numeric_faults",
    # Disaggregated serving (dlti_tpu.serving.disagg).
    "pool_prefill_replicas_alive", "pool_decode_replicas_alive",
    "pool_prefill_waiting", "pool_decode_waiting",
    "pool_prefill_active", "pool_decode_active",
    "kv_handoff_total", "kv_handoff_staged",
    "kv_handoff_fallbacks_total", "kv_handoff_sheds_total",
    # Replica lifecycle / self-healing (dlti_tpu.serving.lifecycle).
    "dlti_replica_lifecycle_quarantines_total",
    "dlti_replica_lifecycle_reinstates_total",
    "dlti_replica_lifecycle_flaps_total",
    "dlti_replica_lifecycle_migrations_total",
    "dlti_replica_lifecycle_migration_fallbacks_total",
    # Multi-process fleet (dlti_tpu.serving.fleet).
    "fleet_workers", "fleet_workers_live", "fleet_respawns",
    # Speculative decode (dlti_tpu.serving.engine): draft economics at
    # the moment of the incident — a collapsed acceptance rate or a
    # pause storm reads very differently from a throughput stall.
    "spec_proposed", "spec_accepted", "spec_paused_rounds",
    "dlti_spec_acceptance_rate", "dlti_spec_draft_len",
)

# Sentinel dump reasons / context keys surfaced as their own report
# section (a numeric incident reads differently from a crash: the
# process is healthy, the NUMBERS died).
_SENTINEL_REASONS = ("sentinel_rollback", "sdc_mismatch")


def discover_dumps(path: str) -> list:
    """Flight dumps under ``path`` and ONE level of subdirectories,
    oldest first. An elastic training job writes its per-rank dumps flat
    (``flight-*-gG-rR/``); a multi-process serving fleet namespaces each
    process — the supervisor dumps at the root, every worker under its
    own ``worker{N}/`` subdir — and ``--all`` merges the whole tree into
    one incident."""
    path = os.path.abspath(path)
    dumps = list(list_dumps(path))
    if os.path.isdir(path):
        for entry in sorted(os.listdir(path)):
            sub = os.path.join(path, entry)
            if os.path.isdir(sub) and not entry.startswith("flight-"):
                dumps.extend(list_dumps(sub))
    return sorted(dumps, key=os.path.getmtime)


def _resolve_dump(path: str) -> str:
    path = os.path.abspath(path)
    if os.path.isdir(path) and os.path.exists(
            os.path.join(path, "MANIFEST.json")):
        return path
    dumps = list_dumps(path)
    if not dumps:
        raise SystemExit(f"no flight-*/ dump under {path}")
    return dumps[-1]


def summarize(dump_dir: str, span_tail: int = 15) -> dict:
    """Machine-readable incident summary for one dump directory."""
    data = load_dump(dump_dir)
    problems = verify_dump(dump_dir)
    ctx_file = data.get("context.json", {})
    context = ctx_file.get("context", {})
    spans = data.get("spans.json", {})
    events = spans.get("traceEvents", [])
    metrics = data.get("metrics.json", {})
    ts = data.get("timeseries.json", {}).get("samples", [])

    # The phase at death: the recorder's live context is authoritative;
    # the last span in the tail corroborates (or supplies it for dumps
    # taken without context notes).
    last_span = next((e for e in reversed(events) if e.get("ph") == "X"),
                     None)
    phase = context.get("phase") or (last_span or {}).get("name")

    alerts = context.get("watchdog_alerts", [])
    span_counts: dict = {}
    for e in events:
        span_counts[e.get("name", "?")] = span_counts.get(
            e.get("name", "?"), 0) + 1

    exc = ctx_file.get("exception")
    # Goodput ledger (telemetry.ledger): the metrics snapshot carries the
    # run's bucket totals at death — "where the time went" belongs in an
    # incident summary, since recovery work is usually WHY a run that
    # "still steps" is failing its throughput target.
    goodput = None
    buckets = {k[len("goodput_"):-len("_seconds")]: v
               for k, v in metrics.items()
               if k.startswith("goodput_") and k.endswith("_seconds")
               and k != "goodput_wall_seconds"}
    if buckets:
        goodput = {
            "fraction": metrics.get("goodput_fraction"),
            "wall_s": metrics.get("goodput_wall_seconds",
                                  round(sum(buckets.values()), 3)),
            "buckets": dict(sorted(buckets.items(),
                                   key=lambda kv: -kv[1])),
        }
    # HBM memory ledger (telemetry.memledger): every dump carries
    # memory.json — "where the memory went" is THE question after an OOM,
    # and useful context for any other death. None when the dump predates
    # the ledger or the source produced nothing.
    mem = data.get("memory.json") or {}
    memory = None
    if mem.get("owners") or mem.get("bytes_in_use"):
        in_use = mem.get("bytes_in_use", 0) or 0
        buckets = {o: d.get("bytes", 0)
                   for o, d in (mem.get("owners") or {}).items()}
        for k in ("untracked", "residual"):
            v = mem.get(f"{k}_bytes", 0)
            if v:
                buckets[k] = v
        memory = {
            "source": mem.get("source"),
            "bytes_in_use": in_use,
            "peak_bytes": mem.get("peak_bytes", 0),
            "capacity_bytes": mem.get("capacity_bytes", 0),
            "headroom_bytes": mem.get("headroom_bytes"),
            "buckets": dict(sorted(buckets.items(), key=lambda kv: -kv[1])),
            "activation_peak": mem.get("activation_peak"),
            "top_untracked_arrays": (mem.get("top_untracked_arrays")
                                     or [])[:5],
        }
    # SLO engine (telemetry.slo): every dump carries slo.json — the
    # per-(objective, class) compliance / error-budget / burn state at
    # death. "Were we already burning budget when it died, and on which
    # objective" is the first SLO question an incident review asks.
    slo_file = data.get("slo.json") or {}
    slo = None
    if slo_file.get("objectives"):
        per_obj = {}
        for key, st in slo_file["objectives"].items():
            per_obj[key] = {
                "compliance": st.get("compliance"),
                "error_budget_remaining": st.get(
                    "error_budget_remaining"),
                "target": st.get("target"),
                "breaching": bool(st.get("breaching")),
                "worst_burn": max(
                    (v for v in (st.get("burn_rates") or {}).values()
                     if isinstance(v, (int, float))), default=0.0),
            }
        slo = {
            "window_s": slo_file.get("window_s"),
            "breaching": list(slo_file.get("breaching") or []),
            "objectives": dict(sorted(
                per_obj.items(),
                key=lambda kv: kv[1]["error_budget_remaining"]
                if kv[1]["error_budget_remaining"] is not None else 1.0)),
        }
    # Continuous delivery (serving.deploy): every dump carries
    # deploy.json — {} unless a deploy controller was wired. "What was
    # the fleet serving, what was being canaried, and had anything been
    # rolled back" places a serving incident relative to the last
    # deployment.
    dep_file = data.get("deploy.json") or {}
    deploy = None
    if dep_file.get("incumbent") is not None:
        deploy = {
            "enabled": dep_file.get("enabled"),
            "state": dep_file.get("state"),
            "incumbent": dep_file.get("incumbent"),
            "candidate": dep_file.get("candidate"),
            "refused_steps": dep_file.get("refused_steps") or {},
            "consecutive_rollbacks": dep_file.get(
                "consecutive_rollbacks", 0),
            "last_result": dep_file.get("last_result"),
            "counters": dep_file.get("counters") or {},
        }
    # Numeric-fault evidence: sentinel dumps carry their verdict in
    # context.json's top level (rollback streak / SDC alert), and any
    # dump may carry the last anomaly the trainer noted.
    sentinel: dict = {}
    if ctx_file.get("reason") in _SENTINEL_REASONS:
        for k in ("streak", "restored_step", "struck_windows",
                  "quarantined", "rollbacks", "alert", "suspect_self"):
            if k in ctx_file:
                sentinel[k] = ctx_file[k]
    if context.get("sentinel_last_anomaly"):
        sentinel["last_anomaly"] = context["sentinel_last_anomaly"]
    # Disaggregated serving (serving.disagg): a controller-backed server's
    # stats carry per-pool detail under "pools" and handoff counters under
    # "kv_handoff" — a decode-pool slot famine or a handoff shed storm
    # reads very differently from a colocated engine stall, so the
    # incident summary surfaces the split. None for colocated dumps.
    disagg = None
    if isinstance(metrics.get("pools"), dict):
        per_pool = {}
        for pool, ps in metrics["pools"].items():
            if isinstance(ps, dict):
                per_pool[pool] = {
                    k: ps[k] for k in ("requests", "generated_tokens",
                                       "prefill_tokens", "preemptions",
                                       "decode_steps")
                    if k in ps}
        disagg = {
            "per_pool": per_pool,
            "replicas_alive": {
                p: metrics.get(f"pool_{p}_replicas_alive")
                for p in ("prefill", "decode")},
            "kv_handoff": (metrics.get("kv_handoff")
                           if isinstance(metrics.get("kv_handoff"), dict)
                           else None),
        }
    return {
        "dump": dump_dir,
        "reason": ctx_file.get("reason"),
        "when": ctx_file.get("iso_time"),
        "wall": ctx_file.get("wall_time"),
        "pid": ctx_file.get("pid"),
        "process_id": ctx_file.get("process_id"),
        "generation": ctx_file.get("generation"),
        # Fleet worker id (engine_worker.py notes it into the recorder
        # context; == process_id for fleet dumps, None for training
        # ranks) — the incident view groups on it when present.
        "worker": context.get("worker"),
        "role": context.get("role"),
        "config_fingerprint": ctx_file.get("config_fingerprint"),
        "last_completed_step": context.get("last_completed_step",
                                           context.get("step")),
        "phase_at_death": phase,
        "exception_tail": (exc.strip().splitlines()[-3:] if exc else None),
        "sentinel": sentinel or None,
        "goodput": goodput,
        "memory": memory,
        "slo": slo,
        "deploy": deploy,
        "disagg": disagg,
        "watchdog_alerts": alerts,
        "dropped_span_events": spans.get("droppedEvents", 0),
        "tracer_enabled": spans.get("tracerEnabled"),
        "num_spans": len(events),
        "span_names": dict(sorted(span_counts.items(),
                                  key=lambda kv: -kv[1])[:12]),
        "last_spans": [
            {"name": e.get("name"), "cat": e.get("cat"),
             "dur_ms": round(e.get("dur", 0) / 1000.0, 3)
             if e.get("ph") == "X" else None,
             "args": e.get("args")}
            for e in events[-span_tail:]
        ],
        "key_metrics": {k: metrics[k] for k in _KEY_METRICS
                        if k in metrics},
        "timeseries_samples": len(ts),
        "timeseries_span_s": (round(ts[-1]["ts"] - ts[0]["ts"], 1)
                              if len(ts) >= 2 else 0.0),
        "integrity_problems": problems,
    }


def find_stitched_ledger(path: str) -> Optional[str]:
    """Locate the elastic supervisor's stitched goodput ledger near a
    dump path: the path itself, its parent, or an ``elastic/`` sibling
    (the common --flight-dir / --elastic-dir layout)."""
    path = os.path.abspath(path)
    if os.path.isfile(path):
        return path
    parent = os.path.dirname(path)
    for cand in (os.path.join(path, "ledger_stitched.json"),
                 os.path.join(parent, "ledger_stitched.json"),
                 os.path.join(parent, "elastic", "ledger_stitched.json"),
                 os.path.join(path, "elastic", "ledger_stitched.json")):
        if os.path.isfile(cand):
            return cand
    return None


def load_stitched_ledger(path: Optional[str]) -> Optional[dict]:
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def merge_incident_trace(dump_dirs: list) -> Optional[dict]:
    """Clock-aligned Perfetto merge of every dump's span tail.

    Each fleet worker persisted the supervisor's clock-offset estimate
    for it (``clock_offset_s`` / ``clock_uncertainty_s``, noted into the
    recorder context on every FT_STEP/FT_HEALTH downlink), so the tails
    can be rebased onto the supervisor's clock after the fact — the
    post-hoc twin of the live ``/debug/trace`` federation. Dumps with no
    offset (the supervisor's own, single-process runs, pre-tracing
    dumps) merge at offset 0.
    """
    from dlti_tpu.telemetry.distributed_trace import merge_dump_tails

    tails = []
    for d in dump_dirs:
        data = load_dump(d)
        ctx_file = data.get("context.json", {})
        context = ctx_file.get("context", {}) or {}
        events = [e for e in (data.get("spans.json", {})
                              .get("traceEvents", []) or [])
                  if isinstance(e, dict) and e.get("ph") != "M"]
        if not events:
            continue
        parent = os.path.basename(os.path.dirname(d))
        who = parent if parent.startswith("worker") else "supervisor"
        try:
            off = float(context.get("clock_offset_s") or 0.0)
        except (TypeError, ValueError):
            off = 0.0
        tails.append({
            "label": f"{who} {os.path.basename(d)}",
            "pid": ctx_file.get("pid"),
            "offset_s": off,
            "uncertainty_s": context.get("clock_uncertainty_s"),
            "events": events,
            "dropped": data.get("spans.json", {}).get("droppedEvents", 0),
        })
    if not tails:
        return None
    return merge_dump_tails(tails)


def summarize_incident(dump_dirs: list, span_tail: int = 15,
                       stitched: Optional[dict] = None) -> dict:
    """One incident summary over a *directory of per-rank dumps* (an
    elastic / multi-process job writes one black box per dying rank,
    tagged with ``process_id`` + ``generation``): per-dump digest lines
    grouped by generation, plus the full summary of the root-cause dump
    (the earliest non-preemption death — preemption stops are the
    supervisor's own drains, consequences rather than causes)."""
    dumps = [summarize(d, span_tail=span_tail) for d in dump_dirs]
    dumps.sort(key=lambda s: (s.get("wall") or 0.0))
    failures = [s for s in dumps
                if s.get("reason") not in ("preemption_stop",)]
    root = (failures or dumps)[0] if dumps else None
    by_gen: dict = {}
    for s in dumps:
        # Fleet dumps live in per-process subdirs (worker{N}/flight-*);
        # keep the namespace in the label so two workers' same-named
        # dumps stay distinguishable in one incident.
        parent = os.path.basename(os.path.dirname(s["dump"]))
        label = os.path.basename(s["dump"])
        if parent.startswith("worker"):
            label = os.path.join(parent, label)
        by_gen.setdefault(s.get("generation"), []).append({
            "dump": label,
            "rank": s.get("process_id"),
            "worker": s.get("worker"),
            "reason": s.get("reason"),
            "when": s.get("when"),
            "last_completed_step": s.get("last_completed_step"),
            "phase_at_death": s.get("phase_at_death"),
            "damaged": bool(s["integrity_problems"]),
        })
    return {
        "num_dumps": len(dumps),
        "generations": {str(g): v for g, v in sorted(
            by_gen.items(), key=lambda kv: (kv[0] is None, kv[0]))},
        "root_cause": root,
        "stitched_ledger": stitched,
        "integrity_problems": sorted(
            {p for s in dumps for p in s["integrity_problems"]}),
    }


def render_incident(incident: dict) -> str:
    out = []
    w = out.append
    w("=" * 72)
    w(f"INCIDENT  ({incident['num_dumps']} flight record(s))")
    w("=" * 72)
    for gen, rows in incident["generations"].items():
        w(f"generation {gen}:")
        for r in rows:
            dmg = "  !!DAMAGED" if r["damaged"] else ""
            # A fleet worker identifies as "worker N" (its supervisor
            # slot), a training process as "rank N".
            if r.get("worker") is not None:
                who = f"worker {r['worker']!s:>3}"
            else:
                who = f"rank {r['rank'] if r['rank'] is not None else '?':>3}"
            w(f"    {who}  "
              f"{(r['reason'] or '?'):24s} last step "
              f"{r['last_completed_step']!s:>6}  "
              f"phase {(r['phase_at_death'] or '?')}{dmg}")
    mt = incident.get("merged_trace")
    if mt:
        w("")
        w(f"merged trace: {mt['events']} span(s) across "
          f"{mt['processes']} process(es), clock-rebased "
          f"(max offset {mt['max_offset_ms']:.2f}ms"
          f"{', ' + str(mt['dropped']) + ' dropped' if mt['dropped'] else ''})"
          + (f" -> {mt['path']}" if mt.get("path") else
             "  [--trace-out FILE to save Perfetto JSON]"))
    st = incident.get("stitched_ledger")
    if st:
        w("")
        w("where the time went (stitched across generations):")
        buckets = st.get("buckets") or {}
        wall = st.get("wall_s") or sum(buckets.values()) or 1.0
        frac = st.get("goodput_fraction")
        if frac is not None:
            w(f"    goodput {100 * frac:.1f}% over {wall:.1f}s booked "
              f"({st.get('num_generations', '?')} generation(s), "
              f"restart downtime {st.get('restart_downtime_s', 0):.1f}s, "
              f"shrunk-world {st.get('shrunk_world_s', 0):.1f}s ="
              f" {st.get('shrunk_world_capacity_loss_s', 0):.1f}s of "
              f"capacity)")
        for k, v in sorted(buckets.items(), key=lambda kv: -kv[1])[:10]:
            w(f"    {k:20s} {v:10.2f}s  {100 * v / wall:5.1f}%")
    root = incident["root_cause"]
    if root is not None:
        w("")
        w("root cause (earliest failure):")
        w(render(root))
    return "\n".join(out)


def render(summary: dict) -> str:
    """The human-readable report (one incident, terminal-width prose)."""
    out = []
    w = out.append
    w("=" * 72)
    w(f"FLIGHT RECORD  {summary['dump']}")
    w("=" * 72)
    if summary["integrity_problems"]:
        w("!! DUMP DAMAGED: " + "; ".join(summary["integrity_problems"]))
    w(f"reason:        {summary['reason']}")
    who = f"pid {summary['pid']}, role {summary['role'] or '?'}"
    if summary.get("process_id") is not None:
        who += f", rank {summary['process_id']}"
    if summary.get("generation") is not None:
        who += f", generation {summary['generation']}"
    w(f"when:          {summary['when']}   ({who})")
    w(f"config:        fingerprint {summary['config_fingerprint']}")
    w(f"last step:     {summary['last_completed_step']}")
    w(f"phase:         {summary['phase_at_death'] or 'unknown'} "
      f"(active at death)")
    if summary["exception_tail"]:
        w("exception:")
        for line in summary["exception_tail"]:
            w(f"    {line}")
    if summary.get("sentinel"):
        s = summary["sentinel"]
        w("sentinel:       (numeric-fault evidence)")
        if s.get("last_anomaly"):
            la = s["last_anomaly"]
            w(f"    last anomaly: {la.get('kind')} at step "
              f"{la.get('step')} (data window {la.get('data_pos')})")
        if s.get("streak") is not None:
            w(f"    rollback #{s.get('rollbacks')}: streak "
              f"{s['streak']} -> restored step {s.get('restored_step')}, "
              f"struck windows {s.get('struck_windows')}"
              + (f", QUARANTINED {s['quarantined']}"
                 if s.get("quarantined") else ""))
        if s.get("alert"):
            w(f"    sdc: {s['alert'].get('message')}"
              + ("  << THIS RANK IS THE SUSPECT"
                 if s.get("suspect_self") else ""))
    if summary.get("goodput"):
        g = summary["goodput"]
        wall = g.get("wall_s") or sum(g["buckets"].values()) or 1.0
        frac = g.get("fraction")
        w("where the time went:" + (
            f"   (goodput {100 * frac:.1f}%)" if frac is not None else ""))
        for k, v in list(g["buckets"].items())[:8]:
            w(f"    {k:20s} {v:10.2f}s  {100 * v / wall:5.1f}%")
    if summary.get("memory"):
        m = summary["memory"]
        gib = 1024.0 ** 3
        in_use = m.get("bytes_in_use", 0) or 1
        line = f"where the memory went:   ({in_use / gib:.2f} GiB in use"
        cap = m.get("capacity_bytes") or 0
        if cap:
            line += f" of {cap / gib:.2f} GiB"
        hr = m.get("headroom_bytes")
        if hr is not None:
            line += f", headroom {hr / gib:.2f} GiB"
        w(line + f", source {m.get('source')})")
        for k, v in list(m["buckets"].items())[:10]:
            w(f"    {k:20s} {v / gib:9.3f} GiB  {100 * v / in_use:5.1f}%")
        act = m.get("activation_peak") or {}
        if act.get("activation_peak_bytes"):
            w(f"    (compiled-step activation peak estimate: "
              f"{act['activation_peak_bytes'] / gib:.3f} GiB)")
        for a in m.get("top_untracked_arrays") or []:
            w(f"    untracked: {a.get('nbytes', 0) / gib:9.3f} GiB  "
              f"{a.get('shape')} {a.get('dtype')}")
    if summary.get("slo"):
        s = summary["slo"]
        breaching = s.get("breaching") or []
        w("SLO state at death:" + (
            f"   (!! BURNING: {', '.join(breaching)})" if breaching
            else "   (no objective burning)"))
        for key, o in s["objectives"].items():
            comp = o.get("compliance")
            budget = o.get("error_budget_remaining")
            mark = "  << BREACHING" if o.get("breaching") else ""
            w(f"    {key:24s} compliance "
              + (f"{100 * comp:6.2f}%" if comp is not None else "     ?")
              + f" (target {100 * (o.get('target') or 0):.2f}%)  budget "
              + (f"{100 * budget:6.1f}%" if budget is not None else "    ?")
              + f"  worst burn {o.get('worst_burn', 0):.1f}x{mark}")
    if summary.get("deploy"):
        d = summary["deploy"]
        inc = d.get("incumbent") or {}
        state = d.get("state")
        w(f"continuous delivery:   (controller "
          f"{'enabled' if d.get('enabled') else 'DISABLED'}, "
          f"state {state})")
        dig = inc.get("digest") or "?"
        w(f"    incumbent: step {inc.get('step')} "
          f"(digest {str(dig)[:12]})")
        cand = d.get("candidate")
        if cand:
            w(f"    candidate under canary: step {cand.get('step')} "
              f"({cand.get('pairs_done', 0)} shadow pair(s) done)")
        last = d.get("last_result")
        if last:
            reasons = ", ".join(last.get("reasons") or []) or "-"
            w(f"    last verdict: step {last.get('step')} "
              f"{last.get('verdict')} ({reasons})")
        refused = d.get("refused_steps") or {}
        if refused:
            w(f"    refused steps: "
              + ", ".join(sorted(refused, key=int)))
        c = d.get("counters") or {}
        if c:
            w(f"    counters: {c.get('promotions', 0)} promoted, "
              f"{c.get('rollbacks', 0)} rolled back, "
              f"{c.get('rejected', 0)} refused "
              f"({d.get('consecutive_rollbacks', 0)} consecutive "
              f"rollback(s) at death)")
    if summary.get("disagg"):
        d = summary["disagg"]
        alive = d.get("replicas_alive") or {}
        w("disaggregated serving:   (prefill/decode split pools)")
        for pool, ps in (d.get("per_pool") or {}).items():
            counters = "  ".join(f"{k}={v}" for k, v in ps.items())
            n = alive.get(pool)
            w(f"    {pool:8s} pool"
              + (f" ({n} replica(s) alive)" if n is not None else "")
              + (f": {counters}" if counters else ""))
        kh = d.get("kv_handoff") or {}
        if kh:
            w(f"    kv handoff: {kh.get('completed', 0)} completed "
              f"({kh.get('bytes', 0)} bytes), {kh.get('staged', 0)} staged "
              f"at death, {kh.get('fallbacks', 0)} fallback(s), "
              f"{kh.get('sheds', 0)} shed(s)")
    if summary["watchdog_alerts"]:
        w(f"watchdog:      {len(summary['watchdog_alerts'])} alert(s) "
          f"before death:")
        for a in summary["watchdog_alerts"][-5:]:
            t = time.strftime("%H:%M:%S", time.localtime(a.get("wall", 0)))
            w(f"    [{t}] {a.get('rule')}: {a.get('message')}")
    else:
        w("watchdog:      no alerts recorded")
    dropped = summary["dropped_span_events"]
    w(f"span tail:     {summary['num_spans']} events"
      + (f"  (!! ring dropped {dropped} older events — "
         f"the timeline below is a truncated window)" if dropped else
         "  (complete since start)"))
    if not summary.get("tracer_enabled", True):
        w("               (tracer was DISABLED — spans predate disabling "
          "or are empty; run with --trace-dir for full timelines)")
    for s in summary["last_spans"]:
        dur = f"{s['dur_ms']:9.3f} ms" if s["dur_ms"] is not None \
            else "   instant  "
        args = ""
        if s.get("args"):
            args = "  " + json.dumps(s["args"], default=str)[:60]
        w(f"    {dur}  {s['cat'] or '':8s} {s['name']}{args}")
    if summary["key_metrics"]:
        w("metrics at death:")
        for k, v in summary["key_metrics"].items():
            w(f"    {k:28s} {v}")
    w(f"time series:   {summary['timeseries_samples']} samples covering "
      f"{summary['timeseries_span_s']}s before death (timeseries.json)")
    w("=" * 72)
    return "\n".join(out)


def main() -> None:
    p = argparse.ArgumentParser(
        description="render a flight-record dump into an incident summary")
    p.add_argument("path", help="flight-*/ dump dir, or a dir containing "
                                "dumps (newest wins)")
    p.add_argument("--spans", type=int, default=15,
                   help="span-tail length in the report")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead")
    p.add_argument("--all", action="store_true",
                   help="treat PATH as a directory of per-rank dumps "
                        "(elastic/multi-process job) and render ONE "
                        "incident summary across all of them; also walks "
                        "one level of subdirs (a fleet's per-worker "
                        "dump namespaces)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="with --all: write the clock-aligned merge of "
                        "every dump's span tail (one pid per process, "
                        "worker tails rebased onto the supervisor clock "
                        "via the offsets persisted in each dump's "
                        "context.json) as Perfetto-loadable JSON")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="stitched goodput ledger (the elastic "
                        "supervisor's ledger_stitched.json) for the "
                        "'where the time went' section; auto-discovered "
                        "near PATH when omitted")
    args = p.parse_args()
    if args.all:
        dumps = discover_dumps(args.path)
        if not dumps:
            raise SystemExit(f"no flight-*/ dump under {args.path}")
        stitched = load_stitched_ledger(
            args.ledger or find_stitched_ledger(args.path))
        incident = summarize_incident(dumps, span_tail=args.spans,
                                      stitched=stitched)
        merged = merge_incident_trace(dumps)
        if merged is not None:
            evs = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
            incident["merged_trace"] = {
                "events": len(evs),
                "processes": len({e.get("pid") for e in evs}),
                "dropped": merged.get("droppedEvents", 0),
                "max_offset_ms": max(
                    (abs(float(t.get("offset_s") or 0.0)) * 1e3
                     for t in merged.get("sources", [])), default=0.0),
            }
            if args.trace_out:
                with open(args.trace_out, "w", encoding="utf-8") as f:
                    json.dump(merged, f)
                incident["merged_trace"]["path"] = args.trace_out
        if args.json:
            print(json.dumps(incident, indent=2, default=str))
        else:
            print(render_incident(incident))
        if incident["integrity_problems"]:
            sys.exit(1)
        return
    dump_dir = _resolve_dump(args.path)
    summary = summarize(dump_dir, span_tail=args.spans)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(render(summary))
    # A damaged dump is itself an incident: nonzero exit so scripts notice.
    if summary["integrity_problems"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
