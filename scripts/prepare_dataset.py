#!/usr/bin/env python
"""Dataset preparation CLI — reference L1 parity.

Mirrors the reference's ``scripts/prepare_dataset.py`` surface
(``prepare_glaive_dataset(num_samples, output_dir)`` + CLI,
``prepare_dataset.py:28,124-155``): fetch/ingest {question, answer} pairs,
map them through the exact Llama-2 chat contract
``<s>[INST] q [/INST] a</s>`` (``prepare_dataset.py:12-25``), and write an
on-disk dataset with a single ``text`` column.

Sources (first match wins):

* ``--input-json FILE`` — local JSON array or JSONL of
  ``{"question", "answer"}`` records: the offline path.
* ``--synthetic N``     — N deterministic synthetic code-QA pairs
  (hermetic smokes; no network, no external deps).
* default               — download ``glaiveai/glaive-code-assistant``
  (train split) from the HF hub, like the reference (needs network).

Output: HF ``save_to_disk`` directory when the ``datasets`` package is
importable (what ``scripts/train.py`` and the reference's
``load_from_disk`` consume), else a ``data.jsonl`` fallback that
``scripts/train.py`` also accepts.

Usage:
    python scripts/prepare_dataset.py --num-samples 10000 --output-dir data/glaive_code_10k
    python scripts/prepare_dataset.py --synthetic 512 --output-dir data/synth
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.data import format_conversation_for_llama2


def _synthetic_pairs(n: int) -> list:
    """Deterministic synthetic code-QA corpus for hermetic runs."""
    topics = ["reverse a linked list", "binary search", "merge two sorted arrays",
              "detect a cycle in a graph", "compute a moving average",
              "parse a CSV line", "memoize a function", "flatten a nested list"]
    langs = ["Python", "C++", "Go", "Rust", "JavaScript"]
    pairs = []
    for i in range(n):
        t, l = topics[i % len(topics)], langs[(i // len(topics)) % len(langs)]
        pairs.append({
            "question": f"How do I {t} in {l}? (variant {i})",
            "answer": f"Here is one way to {t} in {l}:\n\n"
                      f"```\n# variant {i}\ndef solution(x):\n    return x\n```",
        })
    return pairs


def _load_pairs(args) -> list:
    if args.input_json:
        with open(args.input_json) as f:
            head = f.read(256).lstrip()[:1]
            f.seek(0)
            if head == "[":
                records = json.load(f)
            else:
                records = [json.loads(line) for line in f if line.strip()]
        return [{"question": r["question"], "answer": r["answer"]} for r in records]
    if args.synthetic:
        return _synthetic_pairs(args.synthetic)
    try:
        from datasets import load_dataset
    except ImportError as e:
        raise SystemExit(
            f"`datasets` not importable ({e}); use --input-json or --synthetic"
        )
    print("downloading glaiveai/glaive-code-assistant (train split)...")
    ds = load_dataset("glaiveai/glaive-code-assistant", split="train")
    return [{"question": r["question"], "answer": r["answer"]} for r in ds]


def prepare_dataset(args) -> str:
    t0 = time.time()
    pairs = _load_pairs(args)
    if args.num_samples and args.num_samples < len(pairs):
        pairs = pairs[: args.num_samples]
    texts = [format_conversation_for_llama2(p)["text"] for p in pairs]
    rate = len(texts) / max(time.time() - t0, 1e-9)
    print(f"formatted {len(texts)} examples ({rate:,.0f} examples/s)")

    os.makedirs(args.output_dir, exist_ok=True)
    try:
        from datasets import Dataset

        Dataset.from_dict({"text": texts}).save_to_disk(args.output_dir)
        out = args.output_dir
    except ImportError:
        out = os.path.join(args.output_dir, "data.jsonl")
        with open(out, "w") as f:
            for t in texts:
                f.write(json.dumps({"text": t}) + "\n")
    total_chars = sum(len(t) for t in texts)
    print(f"saved -> {out}  ({len(texts)} rows, {total_chars / 1e6:.1f} MB of text)")

    if args.write_token_store:
        # Corpus-scale path: tokenize + (optionally pack) straight into the
        # memory-mapped row store scripts/train.py consumes with O(rows)
        # host RAM (dlti_tpu.data.streaming).
        from dlti_tpu.data import get_tokenizer
        from dlti_tpu.data.streaming import write_token_store

        tok = get_tokenizer(args.tokenizer)
        t1 = time.time()

        def docs():
            # Tokenize lazily, one document at a time — the writer chunks
            # internally, so peak host RAM stays one chunk of token rows,
            # not the tokenized corpus.
            for t in texts:
                yield tok.encode(t, add_bos=True,
                                 add_eos=True)[:args.max_seq_len]

        meta = write_token_store(docs(), args.write_token_store,
                                 seq_len=args.max_seq_len, pad_id=tok.pad_id,
                                 pack=args.pack, tokenizer=args.tokenizer)
        print(f"token store -> {args.write_token_store}  "
              f"({meta['n_rows']} rows x {args.max_seq_len}, "
              f"packed={args.pack}, {time.time() - t1:.1f}s)")
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0],
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--num-samples", "--num_samples", type=int, default=None,
                   help="subsample to N examples (default: all)")
    p.add_argument("--output-dir", "--output_dir", default="data/glaive_code_full")
    p.add_argument("--input-json", default=None,
                   help="local JSON/JSONL with question/answer records (offline)")
    p.add_argument("--synthetic", type=int, default=0,
                   help="generate N synthetic pairs instead of downloading")
    p.add_argument("--write-token-store", default=None, metavar="DIR",
                   help="also tokenize into a memory-mapped token store "
                        "(consumed directly by scripts/train.py)")
    p.add_argument("--tokenizer", default="byte",
                   help="tokenizer for --write-token-store")
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--pack", action="store_true",
                   help="pack documents when writing the token store")
    prepare_dataset(p.parse_args())


if __name__ == "__main__":
    main()
