#!/usr/bin/env python
"""Fleet engine worker entrypoint — one engine process behind the wire
protocol (``dlti_tpu.serving.worker``), spawned and supervised by
``dlti_tpu.serving.fleet.FleetSupervisor`` (``scripts/serve.py
--fleet-workers N``).

The worker builds its model the same way ``serve.py`` does — a
``--random-init`` preset initializes from ``jax.random.PRNGKey(0)``, so
every worker process (and any in-process replica built from the same
preset) holds byte-identical weights; that, plus the engine's
batch-composition-independent sampling, is what makes fleet outputs
byte-identical to the single-process engine.

All build parameters arrive as one JSON spec file (``--spec``) written by
the supervisor; after the engine is up and the socket is bound, the
chosen port is published via ``--port-file`` (durable write) for the
supervisor to pick up.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.utils.platform import honor_platform_env

honor_platform_env()


def parse_args():
    p = argparse.ArgumentParser(description="fleet engine worker")
    p.add_argument("--spec", required=True,
                   help="JSON build spec written by the fleet supervisor")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral, published via "
                        "--port-file)")
    p.add_argument("--port-file", default="",
                   help="publish the bound port here once ready to serve")
    p.add_argument("--worker-id", type=int, default=0)
    p.add_argument("--generation", type=int, default=0,
                   help="respawn generation (tags flight dumps)")
    return p.parse_args()


def build_engine(spec: dict):
    """Model + engine construction, mirroring ``serve.py``. Returns
    (engine, rebuild_fn) where rebuild_fn(host_params) makes a fresh
    engine for rolling weight reloads."""
    import jax
    import jax.numpy as jnp

    from dlti_tpu.serving import EngineConfig, InferenceEngine

    if spec.get("matmul_precision"):
        # Byte-identity across processes requires the same matmul
        # precision the supervisor-side reference engine runs under
        # (tests force "highest"; the env half of the platform dance is
        # inherited, this config knob is not).
        jax.config.update("jax_default_matmul_precision",
                          spec["matmul_precision"])

    if spec.get("model_dir"):
        from dlti_tpu.checkpoint import load_exported_model

        params, cfg = load_exported_model(spec["model_dir"])
        model_cfg = cfg.model
        lora_cfg = cfg.lora if cfg.lora.enabled else None
    else:
        from dlti_tpu.config import MODEL_PRESETS
        from dlti_tpu.models import LlamaForCausalLM

        model_cfg = MODEL_PRESETS[spec["model_preset"]]
        lora_cfg = None
        model = LlamaForCausalLM(model_cfg, None)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]

    eng_kwargs = dict(spec["engine"])
    for key in ("prefill_buckets", "adapter_targets"):
        if key in eng_kwargs and eng_kwargs[key] is not None:
            eng_kwargs[key] = tuple(eng_kwargs[key])
    ec = EngineConfig(**eng_kwargs)

    def rebuild(host_params):
        return InferenceEngine(model_cfg, host_params, ec, lora_cfg,
                               donate_params=True)

    engine = InferenceEngine(model_cfg, params, ec, lora_cfg,
                             donate_params=True)
    return engine, rebuild


def main() -> None:
    args = parse_args()
    with open(args.spec, encoding="utf-8") as f:
        spec = json.load(f)
    # Per-worker identity for flight-dump tagging (flightrecorder labels
    # dumps -g{generation}-r{process_id}); the supervisor sets these in
    # the child env, the flags win if both are present.
    os.environ["DLTI_PROCESS_ID"] = str(args.worker_id)
    os.environ["DLTI_GENERATION"] = str(args.generation)

    for name, adir in (spec.get("adapters") or {}).items():
        from dlti_tpu.serving.adapters import register_adapter

        register_adapter(name, adir)

    # Span federation: the worker keeps a local span ring and ships its
    # tail to the supervisor in FT_STEP/FT_HEALTH replies; the label
    # names this process's row in the merged Perfetto timeline.
    if spec.get("trace", True):
        from dlti_tpu.telemetry import configure_tracer

        tracer = configure_tracer(enabled=True)
        tracer.process_label = f"worker{args.worker_id} gen{args.generation}"

    engine, rebuild = build_engine(spec)
    if spec.get("slow_log_k"):
        engine.telemetry.critical_path.slow.k = max(
            1, int(spec["slow_log_k"]))
    if spec.get("warmup", True):
        engine.warmup_decode_ladder()

    # Per-worker metrics registry: the health-frame snapshot the
    # supervisor federates into the gateway-level /metrics.
    import types

    from dlti_tpu.serving.server import build_registry
    from dlti_tpu.serving.worker import EngineWorker

    registry = build_registry(types.SimpleNamespace(engine=engine))

    if spec.get("flight_dir"):
        from dlti_tpu.telemetry import install_recorder
        from dlti_tpu.telemetry.flightrecorder import FlightRecorder

        # Per-process dump namespace: the supervisor and every worker
        # write to their own subdir; postmortem.py --all walks one level
        # of subdirs and merges them into a single incident timeline.
        recorder = FlightRecorder(os.path.join(
            spec["flight_dir"], f"worker{args.worker_id}"))
        recorder.add_metrics_source(registry.stats_dict)
        recorder.note(role="fleet-worker", worker=args.worker_id,
                      generation=args.generation)
        install_recorder(recorder)

    def _rebuild_warm(tree):
        eng = rebuild(tree)
        if spec.get("warmup", True):
            eng.warmup_decode_ladder()
        return eng

    worker = EngineWorker(engine, host=args.host, port=args.port,
                          worker_id=args.worker_id, registry=registry,
                          reload_fn=_rebuild_warm)

    if args.port_file:
        from dlti_tpu.utils.durable_io import write_json_atomic

        write_json_atomic(args.port_file,
                          {"port": worker.port, "pid": os.getpid(),
                           "worker_id": args.worker_id,
                           "generation": args.generation},
                          path_class="fleet_runtime")
    print(f"engine worker {args.worker_id} (gen {args.generation}) "
          f"serving on {worker.host}:{worker.port}", flush=True)
    try:
        worker.serve_forever()
    finally:
        worker.close()


if __name__ == "__main__":
    main()
