#!/usr/bin/env python
"""Experiment-matrix CLI — the reference notebook (L4), as a script.

The reference drives baseline + ZeRO-{1,2,3} x {1,2,3,4} GPUs from
``training/train.ipynb`` ``%%bash`` cells; this runs the same matrix as
fresh subprocesses, appends every run to the shared metrics CSV, and ends
with the comparison analysis (the ``scripts/compare_training.py`` step).

Examples:

    # hermetic CPU-simulated matrix (tiny model, 3 steps per cell)
    python scripts/run_experiments.py --simulate-devices 8 \
        --strategies baseline,zero1,zero2,zero3 --device-counts 1,2,4 \
        --model llama_tiny --tokenizer byte --dataset-path data/synth \
        --max-steps 3

    # real-chip run of the flagship matrix
    python scripts/run_experiments.py --strategies baseline,zero3 \
        --device-counts 1 --model llama2_7b --dataset-path data/glaive_code_full

    # emit SLURM sbatch scripts instead of running (README.md:18 parity)
    python scripts/run_experiments.py --emit-slurm slurm/ --hosts-per-pod 4 ...
"""

import argparse
import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.orchestration import emit_slurm, plan_matrix, run_matrix


def main() -> None:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--strategies", default="baseline,zero1,zero2,zero3")
    p.add_argument("--device-counts", default="1,2,4")
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--sequence", type=int, default=1)
    p.add_argument("--model", default="llama2_7b")
    p.add_argument("--tokenizer", default="meta-llama/Llama-2-7b-hf")
    p.add_argument("--dataset-path", default="./data/glaive_code_full")
    p.add_argument("--max-steps", type=int, default=0)
    p.add_argument("--num-train-epochs", type=int, default=1)
    p.add_argument("--per-device-batch-size", type=int, default=1)
    p.add_argument("--gradient-accumulation-steps", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--lora-r", type=int, default=16)
    p.add_argument("--metrics-csv", default="results/training_metrics.csv")
    p.add_argument("--plot-out", default="results/plots/training_comparison.png",
                   help="where the post-matrix comparison plot is written")
    p.add_argument("--output-root", default="checkpoints")
    p.add_argument("--log-dir", default="logs")
    p.add_argument("--simulate-devices", type=int, default=0,
                   help="N>0: run each cell on an N-device virtual CPU mesh")
    p.add_argument("--dry-run", action="store_true",
                   help="print the commands without running")
    p.add_argument("--no-analyze", action="store_true")
    p.add_argument("--emit-slurm", default=None, metavar="DIR",
                   help="write sbatch scripts to DIR instead of running")
    p.add_argument("--hosts-per-pod", type=int, default=1)
    p.add_argument("--partition", default=None)
    p.add_argument("--time-limit", default=None)
    args = p.parse_args()

    specs = plan_matrix(
        [s.strip() for s in args.strategies.split(",") if s.strip()],
        [int(n) for n in args.device_counts.split(",")],
        tensor=args.tensor, sequence=args.sequence)
    train_args = {
        "model": args.model,
        "tokenizer": args.tokenizer,
        "dataset_path": args.dataset_path,
        "max_steps": args.max_steps,
        "num_train_epochs": args.num_train_epochs,
        "per_device_batch_size": args.per_device_batch_size,
        "gradient_accumulation_steps": args.gradient_accumulation_steps,
        "max_seq_len": args.max_seq_len,
        "lora_r": args.lora_r,
    }

    if args.emit_slurm:
        paths = emit_slurm(specs, train_args, out_dir=args.emit_slurm,
                           hosts_per_pod=args.hosts_per_pod,
                           partition=args.partition,
                           time_limit=args.time_limit)
        for path in paths:
            print(path)
        return

    results = run_matrix(
        specs, train_args, metrics_csv=args.metrics_csv,
        simulate_devices=args.simulate_devices,
        output_root=args.output_root, analyze=not args.no_analyze,
        plot_path=args.plot_out,
        dry_run=args.dry_run, log_dir=args.log_dir)
    failures = [r for r in results if r["returncode"] not in (0, None)]
    if failures:
        print(f"{len(failures)}/{len(results)} runs failed: "
              + ", ".join(r["name"] for r in failures))
        sys.exit(1)


if __name__ == "__main__":
    main()
