#!/usr/bin/env python
"""Static HBM capacity planner — "will it fit?" answered BEFORE compiling.

The paper-plan half of the memory ledger
(``dlti_tpu/telemetry/memledger.py``): the ledger measures where device
memory actually went at runtime; this script predicts the same owner
buckets from the model/engine configs alone, so a 7B serving deployment
(or a fine-tune) can be sized on paper — and the two are cross-checked
against each other in ``tests/test_memledger.py`` on a tiny CPU model.

Training plan (per chip, no sharding):
    params      = num_params x sizeof(param_dtype)
    optimizer   = 2 x trainable x 4        (AdamW m+v, always fp32)
    grad_buffers = trainable x 4           (transient; peak-relevant)
Serving plan:
    params      = num_params x sizeof(param_dtype)
    kv_pool     = 2 x layers x kv_heads x head_dim x sizeof(kv_dtype)
                  x block_size x num_blocks
    kv/token    = the same without the pool factors -> max resident
                  tokens, and max concurrent seqs at max_model_len

Usage:
    python scripts/memory_plan.py --model llama2_7b --budget-gb 16
    python scripts/memory_plan.py --model llama2_7b --serving \\
        --num-blocks 2048 --kv-dtype int8 --budget-gb 16
    python scripts/memory_plan.py ... --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

# Source checkout wins over any installed copy.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.config import MODEL_PRESETS, ModelConfig  # noqa: E402

# Storage bytes per element (matches dlti_tpu.utils.dtypes resolution).
DTYPE_BYTES = {
    "float32": 4, "fp32": 4, "f32": 4,
    "bfloat16": 2, "bf16": 2, "float16": 2, "fp16": 2,
    "int8": 1, "fp8": 1,
}


def _dtype_bytes(name: str) -> int:
    try:
        return DTYPE_BYTES[name.lower()]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; one of "
                         f"{sorted(DTYPE_BYTES)}") from None


def lora_trainable_params(cfg: ModelConfig, r: int = 16,
                          target_modules: tuple = ("q_proj", "k_proj",
                                                   "v_proj", "o_proj"),
                          ) -> int:
    """Adapter parameter count for the reference LoRA graft: per layer and
    per targeted projection, two factors of shape (in, r) and (r, out)."""
    h = cfg.hidden_size
    hd = cfg.resolved_head_dim
    dims = {
        "q_proj": (h, cfg.num_heads * hd),
        "k_proj": (h, cfg.num_kv_heads * hd),
        "v_proj": (h, cfg.num_kv_heads * hd),
        "o_proj": (cfg.num_heads * hd, h),
    }
    per_layer = sum(r * (i + o) for m, (i, o) in dims.items()
                    if m in target_modules)
    return cfg.num_layers * per_layer


def _proj_dims(cfg: ModelConfig) -> dict:
    """(in, out) of every LoRA-targetable projection — the same dims the
    engine's AdapterPool walks off the live param tree."""
    h = cfg.hidden_size
    hd = cfg.resolved_head_dim
    inter = cfg.intermediate_size
    return {
        "q_proj": (h, cfg.num_heads * hd),
        "k_proj": (h, cfg.num_kv_heads * hd),
        "v_proj": (h, cfg.num_kv_heads * hd),
        "o_proj": (cfg.num_heads * hd, h),
        "gate_proj": (h, inter),
        "up_proj": (h, inter),
        "down_proj": (inter, h),
    }


def adapter_pool_bytes(cfg: ModelConfig, num_slots: int, rank: int = 16,
                       targets: tuple = ("q_proj", "k_proj",
                                         "v_proj", "o_proj")) -> int:
    """HBM the stacked multi-LoRA adapter pool pins: per layer and target,
    f32 A (P, in, r) + B (P, r, out) + scale (P,) with P = num_slots + 1
    (row 0 is the all-zero base row). Must equal
    ``dlti_tpu.serving.adapters.plan_pool_bytes`` — cross-checked against
    it AND the measured ``lora_adapters`` ledger owner in tier-1."""
    if num_slots <= 0:
        return 0
    dims = _proj_dims(cfg)
    unknown = [t for t in targets if t not in dims]
    if unknown:
        raise ValueError(f"unknown adapter targets {unknown}; "
                         f"one of {sorted(dims)}")
    per_row = sum(dims[t][0] * rank + rank * dims[t][1] + 1
                  for t in targets)
    return (num_slots + 1) * cfg.num_layers * per_row * 4


def kv_bytes_per_token(cfg: ModelConfig, kv_dtype: str = "bfloat16") -> int:
    """K + V bytes one token holds resident across all layers."""
    return (2 * cfg.num_layers * cfg.num_kv_heads * cfg.resolved_head_dim
            * _dtype_bytes(kv_dtype))


def plan_training(cfg: ModelConfig, param_dtype: Optional[str] = None,
                  trainable_params: Optional[int] = None,
                  budget_bytes: int = 0) -> dict:
    """Owner-bucket prediction for one training process (no sharding —
    divide by the data/tensor-parallel factor externally)."""
    pbytes = _dtype_bytes(param_dtype or cfg.param_dtype)
    n = cfg.num_params()
    trainable = n if trainable_params is None else trainable_params
    owners = {
        "params": n * pbytes,
        # AdamW first/second moments, fp32 regardless of param dtype.
        "optimizer_state": 2 * trainable * 4,
        # Transient but peak-relevant: one fp32 grad per trainable param.
        "grad_buffers": trainable * 4,
    }
    total = sum(owners.values())
    out = {
        "mode": "training",
        "num_params": n,
        "trainable_params": trainable,
        "owners": owners,
        "total_bytes": total,
    }
    if budget_bytes:
        out["budget_bytes"] = budget_bytes
        out["headroom_bytes"] = budget_bytes - total
        out["fits"] = total <= budget_bytes
    return out


def plan_serving(cfg: ModelConfig, param_dtype: Optional[str] = None,
                 kv_dtype: str = "bfloat16", num_blocks: int = 256,
                 block_size: int = 16, max_model_len: int = 0,
                 budget_bytes: int = 0, adapter_slots: int = 0,
                 adapter_rank: int = 16,
                 adapter_targets: tuple = ("q_proj", "k_proj",
                                           "v_proj", "o_proj")) -> dict:
    """Owner-bucket prediction for one engine replica: the KV pool is
    pre-allocated at init (engine.py), so its full size is resident from
    the first request — and so is the multi-LoRA adapter pool when
    ``adapter_slots`` > 0 (hot-loads scatter into it; it never grows)."""
    pbytes = _dtype_bytes(param_dtype or cfg.param_dtype)
    n = cfg.num_params()
    per_tok = kv_bytes_per_token(cfg, kv_dtype)
    owners = {
        "params": n * pbytes,
        "kv_block_pool": per_tok * block_size * num_blocks,
    }
    if adapter_slots > 0:
        owners["lora_adapters"] = adapter_pool_bytes(
            cfg, adapter_slots, adapter_rank, adapter_targets)
    total = sum(owners.values())
    max_len = max_model_len or cfg.max_seq_len
    out = {
        "mode": "serving",
        "num_params": n,
        "owners": owners,
        "total_bytes": total,
        "kv_bytes_per_token": per_tok,
        # Block 0 is the engine's reserved trash block.
        "max_resident_tokens": (num_blocks - 1) * block_size,
        "max_seqs_at_max_len": (num_blocks - 1) * block_size // max_len,
    }
    if budget_bytes:
        out["budget_bytes"] = budget_bytes
        out["headroom_bytes"] = budget_bytes - total
        out["fits"] = total <= budget_bytes
        # How large could the pool grow inside the budget?
        kv_budget = budget_bytes - owners["params"]
        per_block = per_tok * block_size
        out["max_blocks_in_budget"] = max(0, kv_budget // per_block)
    return out


def render(p: dict) -> str:
    gib = 1024.0 ** 3
    out = [f"memory plan ({p['mode']}, {p['num_params'] / 1e6:.1f}M params)"]
    total = p["total_bytes"] or 1
    for k, v in sorted(p["owners"].items(), key=lambda kv: -kv[1]):
        out.append(f"    {k:20s} {v / gib:9.3f} GiB  {100 * v / total:5.1f}%")
    out.append(f"    {'total':20s} {total / gib:9.3f} GiB")
    if "budget_bytes" in p:
        verdict = "FITS" if p["fits"] else "DOES NOT FIT"
        out.append(f"    budget {p['budget_bytes'] / gib:.2f} GiB -> "
                   f"{verdict}, headroom {p['headroom_bytes'] / gib:.3f} GiB")
    if p["mode"] == "serving":
        out.append(f"    kv/token {p['kv_bytes_per_token']} B; max resident "
                   f"tokens {p['max_resident_tokens']}; "
                   f"max seqs @ max_len {p['max_seqs_at_max_len']}")
        if "max_blocks_in_budget" in p:
            out.append(f"    pool could grow to {p['max_blocks_in_budget']} "
                       f"blocks inside the budget")
    return "\n".join(out)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="static HBM capacity plan from model/engine configs")
    ap.add_argument("--model", default="llama2_7b",
                    choices=sorted(MODEL_PRESETS))
    ap.add_argument("--serving", action="store_true",
                    help="plan a serving replica instead of a trainer")
    ap.add_argument("--param-dtype", default=None,
                    help="override the preset's param storage dtype")
    ap.add_argument("--kv-dtype", default="bfloat16")
    ap.add_argument("--num-blocks", type=int, default=256)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-model-len", type=int, default=0)
    ap.add_argument("--lora-r", type=int, default=0,
                    help="LoRA rank: trainable = adapters only "
                         "(0 = full fine-tune)")
    ap.add_argument("--adapter-slots", type=int, default=0,
                    help="multi-LoRA serving pool slots (engine "
                         "--adapter-slots); adds the lora_adapters owner "
                         "(0 = off)")
    ap.add_argument("--adapter-rank", type=int, default=16,
                    help="pool rank ceiling (engine --adapter-rank)")
    ap.add_argument("--adapter-targets",
                    default="q_proj,k_proj,v_proj,o_proj",
                    help="comma-separated targeted projections")
    ap.add_argument("--budget-gb", type=float, default=0.0,
                    help="HBM budget to check the plan against")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    cfg = MODEL_PRESETS[args.model]
    budget = int(args.budget_gb * 1024 ** 3)
    if args.serving:
        p = plan_serving(cfg, param_dtype=args.param_dtype,
                         kv_dtype=args.kv_dtype, num_blocks=args.num_blocks,
                         block_size=args.block_size,
                         max_model_len=args.max_model_len,
                         budget_bytes=budget,
                         adapter_slots=args.adapter_slots,
                         adapter_rank=args.adapter_rank,
                         adapter_targets=tuple(
                             t.strip() for t in
                             args.adapter_targets.split(",") if t.strip()))
    else:
        trainable = (lora_trainable_params(cfg, r=args.lora_r)
                     if args.lora_r else None)
        p = plan_training(cfg, param_dtype=args.param_dtype,
                          trainable_params=trainable, budget_bytes=budget)
    if args.json:
        print(json.dumps(p, indent=2))
    else:
        print(render(p))


if __name__ == "__main__":
    main()
