#!/usr/bin/env python
"""Offline consolidated export: training checkpoint -> servable model.

The sharded-training-state -> portable-single-artifact capability
(``stage3_gather_16bit_weights_on_model_save`` parity, SURVEY.md §5.4)
WITHOUT a live device: the checkpoint is restored host-side from disk
(shapes come from ``jax.eval_shape`` over the same state constructor
``scripts/train.py`` uses, so int8 ``{q, scale}`` leaves line up), LoRA
is merged, int8 dequantized, and the result written as a normal export
that ``scripts/serve.py --model-dir`` loads.

Exists for links where fetching a 7B tree from the device is slow or
flaky (the checkpoint already on disk is the source of truth), and for
exporting on machines with no accelerator at all.

Usage:
    python scripts/export_from_checkpoint.py --checkpoint-dir runs/7b \
        --model llama2_7b --lora-r 16 --quantize-base int8 \
        --out exports/merged_7b
"""

from __future__ import annotations

import argparse
import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root


def main() -> None:
    p = argparse.ArgumentParser(
        description="checkpoint -> merged servable export (host-side)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--step", type=int, default=0, help="0 = latest")
    p.add_argument("--model", default="llama2_7b")
    p.add_argument("--lora-r", type=int, default=16)
    p.add_argument("--quantize-base", default="", choices=["", "int8"])
    p.add_argument("--seq-len", type=int, default=512,
                   help="example shape used at train init (shapes only)")
    p.add_argument("--out", required=True)
    p.add_argument("--keep-lora", action="store_true",
                   help="export unmerged (adapter factors kept as leaves)")
    p.add_argument("--fp16", action="store_true",
                   help="checkpoint came from an --fp16 run (its state "
                        "carries the dynamic loss scaler subtree)")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from dlti_tpu.checkpoint import (
        export_merged_model, latest_verified_step, restore_train_state,
    )
    from dlti_tpu.config import Config, LoRAConfig, OptimizerConfig, preset
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.training import build_optimizer, create_train_state

    cfg: Config = preset("baseline", model=args.model)
    cfg = cfg.replace(
        lora=LoRAConfig(enabled=args.lora_r > 0, r=max(args.lora_r, 1),
                        alpha=2 * max(args.lora_r, 1)))

    def make_state():
        model = LlamaForCausalLM(cfg.model, cfg.lora if cfg.lora.enabled else None)
        tx = build_optimizer(OptimizerConfig())
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, (1, args.seq_len),
            lora_enabled=cfg.lora.enabled,
            fp16_initial_scale=2.0 ** 16 if args.fp16 else None)
        if args.quantize_base:
            from dlti_tpu.models.quantization import quantize_params_int8

            state = state.replace(
                params=quantize_params_int8(state.params))
        return state

    # eval_shape materializes nothing; the store places each restored
    # leaf on the template's sharding, so pin them all to host CPU.
    host = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])
    template = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=host)
        if hasattr(s, "shape") else s,
        jax.eval_shape(make_state))
    # latest *verified*: a corrupt/incomplete newest checkpoint is
    # quarantined and the export falls back to the newest good one.
    step = args.step or latest_verified_step(args.checkpoint_dir)
    if step is None:
        raise SystemExit(f"no verified checkpoints under {args.checkpoint_dir}")
    print(f"restoring step {step} from {args.checkpoint_dir} (host-side)")
    state = restore_train_state(args.checkpoint_dir, step, template)
    out = export_merged_model(args.out, state.params, cfg,
                              merge_lora=not args.keep_lora)
    # The export's content identity on stdout: the params manifest
    # SHA-256 that /v1/reload re-verification and the deploy controller
    # pin — so release tooling can record what it just produced and
    # later assert the fleet is serving exactly those bytes.
    from dlti_tpu.checkpoint import manifest_digest

    digest = manifest_digest(os.path.join(out, "model"))
    print(f"export -> {out}")
    print(f"manifest sha256: {digest}")


if __name__ == "__main__":
    main()
