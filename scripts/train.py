#!/usr/bin/env python
"""Training CLI — one entry point for the reference's four trainer scripts.

The reference ships one script per strategy (``training/train_baseline.py``,
``train_deepspeed_zero{1,2,3}.py``) with drifting argparse defaults
(SURVEY.md §5.6). Here a single CLI selects the strategy with ``--preset``
and the mesh with ``--num-devices/--tensor/--sequence/--expert/--pipe``
(`--data` sets the batch-row extent under ``--pipe``); everything else is
the shared typed config tree.

Examples:

    # reference baseline equivalent (1 chip, LoRA r=16, accum 16)
    python scripts/train.py --preset baseline --dataset-path data/synth \
        --model llama2_7b --tokenizer meta-llama/Llama-2-7b-hf

    # ZeRO-3 over 8 chips with TP=2 (the `deepspeed --num_gpus=8` analog)
    python scripts/train.py --preset zero3 --num-devices 4 --tensor 2 ...

    # hermetic CPU smoke (virtual 8-device mesh)
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/train.py --preset zero1 --num-devices 8 --model llama_tiny \
        --tokenizer byte --dataset-path data/synth --max-steps 3

Reference flag mapping (``train_baseline.py:27-89``): ``--model-name`` ->
``--model`` (a preset, since weights are trained from scratch or restored
from our checkpoints), ``--per-device-batch-size`` and grad-accum/lr/lora-r
keep the reference defaults (1, 16, 2e-4, 16).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.utils.platform import honor_platform_env

honor_platform_env()


def parse_args():
    p = argparse.ArgumentParser(description="TPU-native LLM trainer",
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--preset", default="baseline",
                   help="strategy: baseline | zero1 | zero2 | zero3")
    p.add_argument("--model", default="llama2_7b",
                   help="model preset name (see dlti_tpu.config.MODEL_PRESETS)")
    p.add_argument("--dataset-path", "--dataset_path", default="./data/glaive_code_full",
                   help="HF save_to_disk dir, JSONL with a `text` field, or plain-text file")
    p.add_argument("--output-dir", "--output_dir", default="./checkpoints/run")
    p.add_argument("--tokenizer", default="meta-llama/Llama-2-7b-hf",
                   help="HF tokenizer name/path, or 'byte' for the hermetic byte tokenizer")
    # Reference training defaults (train_baseline.py:27-89).
    p.add_argument("--num-train-epochs", type=int, default=1)
    p.add_argument("--max-steps", type=int, default=0, help="0 = full epochs")
    p.add_argument("--per-device-batch-size", type=int, default=1)
    p.add_argument("--gradient-accumulation-steps", type=int, default=16)
    p.add_argument("--learning-rate", type=float, default=2e-4)
    p.add_argument("--warmup-steps", type=int, default=100)
    p.add_argument("--lora-r", type=int, default=16, help="0 disables LoRA (full fine-tune)")
    p.add_argument("--max-seq-len", type=int, default=512)
    p.add_argument("--pack", action="store_true",
                   help="pack sequences to fill seq_len (perf option; reference pads)")
    p.add_argument("--prefetch-depth", type=int, default=2,
                   help="background batch-prefetch depth: gather/pack and "
                        "the host→device transfer run off the step thread, "
                        "double-buffered this deep (bit-identical loss "
                        "trajectory; 0 = legacy inline fetch)")
    # Mesh axes (the torchrun/deepspeed --num_gpus analog).
    p.add_argument("--num-devices", type=int, default=0,
                   help="DP/FSDP extent; 0 = all visible devices / "
                        "(tensor*sequence*expert)")
    p.add_argument("--tensor", type=int, default=1, help="tensor-parallel extent")
    p.add_argument("--sequence", type=int, default=1,
                   help="sequence-parallel (ring attention) extent")
    p.add_argument("--expert", type=int, default=1,
                   help="expert-parallel extent (MoE models: experts "
                        "shard over this axis)")
    p.add_argument("--pipe", type=int, default=1,
                   help="pipeline-parallel stages (GPipe schedule; "
                        "microbatches = --gradient-accumulation-steps). "
                        "Composes with every other mesh axis: under "
                        "--pipe, --data sets the batch-row extent "
                        "(ZeRO presets shard over it)")
    p.add_argument("--data", type=int, default=None,
                   help="batch-row (DP) extent under --pipe; with a "
                        "zero3 preset this is the FSDP extent; default: "
                        "the preset's own extent. Rejected without "
                        "--pipe (use --num-devices there)")
    p.add_argument("--offload-optimizer", action="store_true",
                   help="ZeRO-3 host-offload parity (ds_config_zero3.json:19-23)")
    p.add_argument("--offload-params", action="store_true",
                   help="ZeRO-3 param host-offload parity (ds_config_zero3.json:24-27)")
    p.add_argument("--fp16", action="store_true",
                   help="fp16 + dynamic loss scaling parity mode (TPU default is "
                        "bf16, which needs no scaler — ds_config fp16 block)")
    p.add_argument("--quantize-base", default="", choices=["", "int8"],
                   help="store the frozen base params weight-only quantized "
                        "during LoRA training (QLoRA-style); halves base "
                        "HBM and buys activation-saving headroom")
    p.add_argument("--remat-policy", default=None,
                   choices=["none", "nothing_saveable", "dots_saveable",
                            "dots_with_no_batch_dims_saveable",
                            "save_attn_out"],
                   help="activation-saving policy for jax.checkpoint "
                        "('none' disables remat entirely — fits at 7B bs4 "
                        "once the base is int8; default: preset's)")
    p.add_argument("--remat-stride", type=int, default=0,
                   help="keep every Nth block's activations (selective "
                        "remat; 0 = preset)")
    p.add_argument("--loss-chunk", type=int, default=0,
                   help="sequence-chunked cross-entropy: compute LM head + "
                        "CE this many positions at a time so full fp32 "
                        "logits never sit in HBM (0 = off; not for "
                        "--sequence > 1 or MoE)")
    p.add_argument("--steps-per-sync", type=int, default=1,
                   help="optimizer steps per compiled program call (scanned "
                        "window; same trajectory as 1, metrics stay "
                        "per-step, eval/saves land at window boundaries; "
                        "not with --offload-* or multi-host)")
    # Checkpointing (reference: save_steps=100, keep 3 — zero1:243-245).
    p.add_argument("--save-strategy", default="steps", choices=["steps", "epoch", "no"])
    p.add_argument("--save-steps", type=int, default=100)
    p.add_argument("--save-total-limit", type=int, default=3)
    p.add_argument("--no-resume", action="store_true",
                   help="skip the verified scan-latest-and-resume pass")
    p.add_argument("--fault-inject-step", default="",
                   help="deterministic trainer chaos hook 'STEP[:MODE]' "
                        "(MODE: raise | kill | save-raise | save-kill | "
                        "nan-grad | poison-batch | param-flip[:RANK]) — "
                        "crash/SIGKILL the trainer, or inject a numeric "
                        "fault (NaN grads, a deterministically-poisoned "
                        "data window, a silent param bit-flip) to drill "
                        "the sentinel's skip/rollback/quarantine/SDC "
                        "paths; also via env DLTI_TRAIN_FAULT_INJECT")
    # Numeric-fault sentinel (dlti_tpu.training.sentinel).
    p.add_argument("--no-sentinel", action="store_true",
                   help="disable the numeric-fault sentinel (per-step "
                        "nonfinite/spike detection + automatic rollback; "
                        "the in-step nonfinite update gate stays — it is "
                        "a correctness fix, not an option)")
    p.add_argument("--sentinel-rollback-after", type=int, default=3,
                   help="consecutive anomalous steps before automatic "
                        "rollback to the last verified checkpoint (0 = "
                        "detect only, never roll back)")
    p.add_argument("--sentinel-window", type=int, default=32,
                   help="rolling-median spike window (steps)")
    p.add_argument("--sentinel-min-samples", type=int, default=8,
                   help="normal steps required before spike detection "
                        "arms (cold start)")
    p.add_argument("--sentinel-loss-spike-factor", type=float, default=2.0,
                   help="loss spike threshold: latest > factor x rolling "
                        "median")
    p.add_argument("--sentinel-quarantine-after", type=int, default=2,
                   help="rollbacks implicating a data window before it is "
                        "quarantined permanently (below that it replays)")
    p.add_argument("--sdc-check-interval", type=int, default=0,
                   help="cross-rank param-digest SDC probe cadence in "
                        "optimizer steps (0 = off; multi-process runs "
                        "only) — a mismatching rank is flagged as the "
                        "suspect host, dumps a flight record, and exits "
                        "87 for the elastic supervisor to evict")
    p.add_argument("--export-dir", default=None,
                   help="write a consolidated merged-LoRA export here after training")
    p.add_argument("--init-from-hf", default=None, metavar="DIR",
                   help="initialize base weights from an HF Llama checkpoint dir "
                        "(config.json + safetensors); overrides --model's arch")
    p.add_argument("--export-hf", default=None, metavar="DIR",
                   help="write the merged model as an HF-layout checkpoint after training")
    p.add_argument("--export-peft", default=None, metavar="DIR",
                   help="write the LoRA factors as a PEFT adapter after training")
    p.add_argument("--eval-dataset", default=None, metavar="PATH",
                   help="held-out dataset (same formats as --dataset-path); "
                        "evaluated every --eval-steps optimizer steps")
    p.add_argument("--eval-steps", type=int, default=0,
                   help="eval cadence in steps (0 = never; requires "
                        "--eval-dataset)")
    p.add_argument("--metrics-csv", default="results/training_metrics.csv")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--logging-steps", type=int, default=10)
    p.add_argument("--profile-dir", default="",
                   help="capture a jax.profiler trace window here (XProf)")
    p.add_argument("--profile-start-step", type=int, default=10)
    p.add_argument("--profile-num-steps", type=int, default=3)
    # Unified telemetry (dlti_tpu.telemetry) — host-side, always-available
    # complement to the jax.profiler device traces above.
    p.add_argument("--trace-dir", default="",
                   help="export a host-side span trace (per-step phases: "
                        "batch fetch, host→device, dispatch, sync, eval, "
                        "save) as Chrome-trace JSON here; open in Perfetto")
    p.add_argument("--trace-capacity", type=int, default=65536,
                   help="span ring-buffer capacity (most recent events kept)")
    p.add_argument("--step-log", default="",
                   help="per-step JSONL telemetry stream (rank-0): step, "
                        "loss, grad_norm, lr, tok/s/chip, MFU, HBM peak — "
                        "a superset of the reference CSV columns")
    p.add_argument("--heartbeat-interval", type=int, default=0,
                   help="multi-host heartbeat cadence in steps (rank 0 "
                        "logs straggler lag; 0 = off)")
    # Self-monitoring (dlti_tpu.telemetry.{watchdog,flightrecorder}).
    p.add_argument("--watchdog", action="store_true",
                   help="enable the anomaly watchdog: hung-step deadline "
                        "(k x rolling-median step time), throughput "
                        "collapse, heartbeat staleness, checkpoint retry "
                        "storms — alerts via "
                        "dlti_watchdog_alerts_total{rule=} + JSONL log")
    p.add_argument("--watchdog-action", default="log",
                   choices=["log", "dump", "abort"],
                   help="alert escalation: log only, also dump a flight "
                        "record, or dump + abort the run (CI chaos)")
    p.add_argument("--watchdog-hung-step-min", type=float, default=30.0,
                   help="hung-step deadline floor in seconds (the rule "
                        "fires past max(this, factor x median step time))")
    p.add_argument("--flight-dir", default="",
                   help="enable the flight recorder: fatal exceptions, "
                        "preemption stops, chaos faults (even N:kill), "
                        "and watchdog escalations dump a flight-*/ black "
                        "box here; render with scripts/postmortem.py")
    p.add_argument("--no-goodput-ledger", action="store_true",
                   help="disable the goodput ledger (telemetry.ledger): "
                        "no per-bucket wall-clock accounting, goodput "
                        "fraction, per-phase steplog fields, or stitched "
                        "elastic ledger — every site drops to one "
                        "attribute read")
    p.add_argument("--slo-goodput-floor", type=float, default=0.0,
                   help="training SLO (telemetry.slo): goodput fraction "
                        "the run must hold; time below the floor burns "
                        "the error budget, burn-rate alerts fire the "
                        "watchdog's slo_burn rule and land in slo.json "
                        "flight dumps (0 = SLO engine off)")
    p.add_argument("--slo-goodput-target", type=float, default=0.99,
                   help="fraction of wall-clock that must sit at or "
                        "above --slo-goodput-floor")
    p.add_argument("--slo-window", type=float, default=3600.0,
                   help="SLO compliance / error-budget window seconds")
    p.add_argument("--slo-burn-tiers", default="14:60:5,6:300:30",
                   help="burn-rate alert tiers 'factor:long_s:short_s,"
                        "...' (SRE multi-window multi-burn-rate)")
    p.add_argument("--no-memory-ledger", action="store_true",
                   help="disable the HBM memory ledger "
                        "(telemetry.memledger): no per-owner attribution, "
                        "hbm_* steplog fields, or memory.json in flight "
                        "dumps")
    p.add_argument("--hbm-budget-bytes", type=int, default=0,
                   help="HBM capacity for headroom accounting (0 = "
                        "auto-detect from device memory_stats(); stays "
                        "unknown on CPU, keeping the hbm_pressure rule "
                        "and headroom fields off)")
    return p.parse_args()


def load_texts(path: str) -> list:
    """Dataset dir (HF save_to_disk), JSONL with `text`, or plain text lines."""
    if os.path.isdir(path):
        jsonl = os.path.join(path, "data.jsonl")
        if os.path.isfile(jsonl):
            path = jsonl
        else:
            from datasets import load_from_disk

            return list(load_from_disk(path)["text"])
    with open(path) as f:
        first = f.readline()
        f.seek(0)
        if first.lstrip().startswith("{"):
            return [json.loads(line)["text"] for line in f if line.strip()]
        return [line.rstrip("\n") for line in f if line.strip()]


def _apply_packed_window(cfg, max_doc_len: int):
    """Exact banded attention for packed batches (see
    ModelConfig.packed_attention_window)."""
    if max_doc_len and max_doc_len < cfg.data.max_seq_len:
        cfg = cfg.replace(model=dataclasses.replace(
            cfg.model, packed_attention_window=max_doc_len))
        print(f"packed attention window: {max_doc_len} "
              f"(corpus max doc length)")
    return cfg


def build_config(args):
    import jax

    from dlti_tpu.config import (
        CheckpointConfig, DataConfig, FlightRecorderConfig, LoRAConfig,
        OptimizerConfig, SentinelConfig, SLOConfig, TelemetryConfig,
        TrainConfig, WatchdogConfig, ZeROStage, preset,
    )

    cfg = preset(args.preset, model=args.model)
    par = cfg.parallel
    if args.pipe > 1:
        # GPipe over the 'pipe' axis, composing with every other mesh
        # axis (r05): ZeRO presets shard over the --data extent (zero3:
        # fsdp), TP/SP/EP ride GSPMD inside the stages. Every flag the
        # user passed is forwarded so
        # Trainer._validate_pipeline_config rejects genuinely illegal
        # combinations loudly instead of them being silently dropped.
        # Batch-row extent: an EXPLICIT --data always wins (even --data 1
        # for a pure pipe mesh — a mesh flag is never silently dropped);
        # default inherits the preset's own extent (zero3_8dev encodes
        # fsdp=8, zero1_4dev data=4).
        preset_rows = par.fsdp if int(par.zero_stage) == 3 else par.data
        rows = args.data if args.data is not None else max(preset_rows, 1)
        if rows < 1:
            raise SystemExit(f"--data {rows} must be >= 1")
        if int(par.zero_stage) == 3 and rows == 1:
            raise SystemExit(
                "--preset zero3 with --pipe needs a batch-row extent for "
                "the FSDP axis: pass --data N or use a zero3_Ndev preset "
                "(fsdp=1 would silently disable ZeRO-3 param sharding)")
        data_ext, fsdp_ext = rows, 1
        if int(par.zero_stage) == 3:
            data_ext, fsdp_ext = 1, rows
        mesh_n = (args.pipe * args.tensor * args.sequence * args.expert
                  * rows)
        if args.num_devices and args.num_devices != mesh_n:
            raise SystemExit(
                f"--num-devices {args.num_devices} conflicts with --pipe "
                f"{args.pipe} (the pipe mesh uses exactly "
                f"pipe*tensor*sequence*expert*data = {mesh_n} devices; "
                f"drop --num-devices or fix --data)")
        par = par.__class__(zero_stage=par.zero_stage,
                            pipe=args.pipe, tensor=args.tensor,
                            sequence=args.sequence, expert=args.expert,
                            data=data_ext, fsdp=fsdp_ext,
                            offload_optimizer=args.offload_optimizer,
                            offload_params=args.offload_params)
    else:
        if args.data is not None:
            # Loud-reject rule: a mesh flag must never be silently
            # dropped. Without --pipe the DP/FSDP extent is
            # --num-devices.
            raise SystemExit(
                f"--data {args.data} only applies under --pipe; without "
                f"it use --num-devices to set the DP/FSDP extent")
        n = args.num_devices or max(
            jax.device_count() // (args.tensor * args.sequence
                                   * args.expert), 1
        )
        if int(par.zero_stage) == 3:
            par = par.__class__(zero_stage=par.zero_stage, fsdp=n,
                                tensor=args.tensor, sequence=args.sequence,
                                expert=args.expert,
                                offload_optimizer=args.offload_optimizer,
                                offload_params=args.offload_params)
        else:
            par = par.__class__(zero_stage=par.zero_stage, data=n,
                                tensor=args.tensor, sequence=args.sequence,
                                expert=args.expert,
                                offload_optimizer=args.offload_optimizer,
                                offload_params=args.offload_params)

    dp = par.data * par.fsdp
    from dlti_tpu.utils.experiment import create_experiment_name

    model_cfg = cfg.model
    if args.fp16:
        # fp16 parity mode: compute and store in fp16 (the scaler handles
        # overflow); without --fp16 the TPU default bf16 stays.
        model_cfg = dataclasses.replace(model_cfg, dtype="float16",
                                        param_dtype="float16")
    if args.remat_policy == "none":
        model_cfg = dataclasses.replace(model_cfg, remat=False)
    elif args.remat_policy:
        model_cfg = dataclasses.replace(model_cfg,
                                        remat_policy=args.remat_policy)
    if args.remat_stride:
        model_cfg = dataclasses.replace(model_cfg,
                                        remat_stride=args.remat_stride)

    return cfg.replace(
        model=model_cfg,
        parallel=par,
        lora=LoRAConfig(enabled=args.lora_r > 0, r=max(args.lora_r, 1),
                        alpha=2 * max(args.lora_r, 1)),
        optimizer=OptimizerConfig(learning_rate=args.learning_rate,
                                  warmup_steps=args.warmup_steps),
        data=DataConfig(dataset_path=args.dataset_path, tokenizer=args.tokenizer,
                        max_seq_len=args.max_seq_len, pack_sequences=args.pack,
                        prefetch_depth=args.prefetch_depth),
        checkpoint=CheckpointConfig(output_dir=args.output_dir,
                                    save_strategy=args.save_strategy,
                                    save_steps=args.save_steps,
                                    save_total_limit=args.save_total_limit,
                                    resume=not args.no_resume),
        train=TrainConfig(num_epochs=args.num_train_epochs,
                          max_steps=args.max_steps,
                          micro_batch_size=args.per_device_batch_size * dp,
                          grad_accum_steps=args.gradient_accumulation_steps,
                          logging_steps=args.logging_steps, seed=args.seed,
                          metrics_csv=args.metrics_csv, fp16=args.fp16,
                          quantize_frozen_base=args.quantize_base,
                          loss_chunk=args.loss_chunk,
                          steps_per_sync=args.steps_per_sync,
                          fault_inject_step=args.fault_inject_step,
                          eval_steps=args.eval_steps,
                          profile_dir=args.profile_dir,
                          profile_start_step=args.profile_start_step,
                          profile_num_steps=args.profile_num_steps,
                          sentinel=SentinelConfig(
                              enabled=not args.no_sentinel,
                              rollback_after=args.sentinel_rollback_after,
                              window=args.sentinel_window,
                              min_samples=args.sentinel_min_samples,
                              loss_spike_factor=args.sentinel_loss_spike_factor,
                              quarantine_after=args.sentinel_quarantine_after,
                              sdc_check_interval=args.sdc_check_interval)),
        telemetry=TelemetryConfig(
            trace_dir=args.trace_dir,
            trace_capacity=args.trace_capacity,
            step_log_path=args.step_log,
            heartbeat_interval_steps=args.heartbeat_interval,
            goodput_ledger=not args.no_goodput_ledger,
            memory_ledger=not args.no_memory_ledger,
            hbm_budget_bytes=args.hbm_budget_bytes,
            slo=SLOConfig(
                enabled=args.slo_goodput_floor > 0,
                window_s=args.slo_window,
                burn_tiers=args.slo_burn_tiers,
                goodput_floor=args.slo_goodput_floor,
                goodput_target=args.slo_goodput_target),
            watchdog=WatchdogConfig(
                enabled=args.watchdog,
                action=args.watchdog_action,
                hung_step_min_s=args.watchdog_hung_step_min,
                heartbeat_stale_s=(600.0 if args.heartbeat_interval else 0.0),
                alert_log_path=(os.path.join(args.flight_dir,
                                             "watchdog_alerts.jsonl")
                                if args.flight_dir else "")),
            flight_recorder=FlightRecorderConfig(dir=args.flight_dir)),
        experiment_name=create_experiment_name(
            par.num_devices, int(par.zero_stage)),
    )


def main() -> None:
    args = parse_args()

    # Multi-host rendezvous when spawned by scripts/launch.py (the
    # LOCAL_RANK/WORLD_SIZE contract analog); no-op single-process.
    from dlti_tpu.launcher import maybe_initialize_from_env

    maybe_initialize_from_env()

    cfg = build_config(args)

    # Elastic launch (scripts/launch.py --elastic): when this generation
    # runs at less than the full slot count, shrink the mesh batch axes
    # to the surviving devices and recompute grad-accum so the GLOBAL
    # batch schedule (rows per optimizer step, steps/epoch, rng folds) is
    # exactly the full-size run's — a resumed shrunk generation replays
    # the same batches the dead world would have.
    from dlti_tpu.training.elastic import maybe_reshape_from_env

    cfg = maybe_reshape_from_env(cfg)

    base_params = None
    if args.init_from_hf:
        from dlti_tpu.models import load_hf_checkpoint

        # config.json supplies the architecture; the preset keeps the
        # performance fields (bf16 dtypes, remat, attention impl, seq len) —
        # an fp32 checkpoint must not silently flip training to fp32.
        perf_fields = dict(
            dtype=cfg.model.dtype, param_dtype=cfg.model.param_dtype,
            remat=cfg.model.remat, remat_policy=cfg.model.remat_policy,
            attention_impl=cfg.model.attention_impl,
            flash_block_q=cfg.model.flash_block_q,
            flash_block_kv=cfg.model.flash_block_kv,
        )
        base_params, hf_model_cfg = load_hf_checkpoint(
            args.init_from_hf, **perf_fields)
        if hf_model_cfg != cfg.model:
            print(f"model arch from {args.init_from_hf}/config.json "
                  f"(overrides --model={args.model})")
            cfg = cfg.replace(model=hf_model_cfg)

    from dlti_tpu.data import get_tokenizer, make_batches
    from dlti_tpu.training import Trainer

    print(f"experiment: {cfg.experiment_name}")
    print(f"mesh: data={cfg.parallel.data} fsdp={cfg.parallel.fsdp} "
          f"tensor={cfg.parallel.tensor} sequence={cfg.parallel.sequence} "
          f"pipe={cfg.parallel.pipe}")

    if os.path.isfile(os.path.join(args.dataset_path, "meta.json")):
        # Memory-mapped token store (scripts/prepare_dataset.py
        # --write-token-store): corpus-scale input, O(rows) host RAM.
        from dlti_tpu.data.streaming import StreamingTokenDataset

        # Fail fast on config mismatches: the rows are baked at prepare
        # time, so the run config must match them (a tokenizer mismatch
        # raises inside the dataset; a different seq_len silently changes
        # the workload).
        try:
            dataset = StreamingTokenDataset(
                args.dataset_path,
                micro_batch_size=cfg.train.micro_batch_size,
                grad_accum_steps=cfg.train.grad_accum_steps,
                shuffle_seed=cfg.data.shuffle_seed,
                expect_tokenizer=cfg.data.tokenizer,
            )
        except ValueError as e:
            raise SystemExit(str(e))
        if dataset.seq_len != cfg.data.max_seq_len:
            raise SystemExit(
                f"token store {args.dataset_path} was written with "
                f"seq_len={dataset.seq_len}, but --max-seq-len is "
                f"{cfg.data.max_seq_len}; re-prepare or pass the matching "
                f"--max-seq-len")
        print(f"dataset: memory-mapped token store {args.dataset_path} "
              f"({dataset._ids.shape[0]} rows x {dataset.seq_len}, "
              f"packed={dataset.packed})")
        if dataset.packed and cfg.parallel.pipe > 1:
            raise SystemExit(
                "this token store is packed, and packed batches are not "
                "supported under --pipe (the pipelined stage body takes "
                "no segment mask); re-prepare without --pack")
        if dataset.packed:
            cfg = _apply_packed_window(cfg, dataset.max_doc_len)
    else:
        texts = load_texts(args.dataset_path)
        print(f"dataset: {len(texts)} examples from {args.dataset_path}")
        tok = get_tokenizer(cfg.data.tokenizer)
        dataset = make_batches(
            texts, tok,
            seq_len=cfg.data.max_seq_len,
            micro_batch_size=cfg.train.micro_batch_size,
            grad_accum_steps=cfg.train.grad_accum_steps,
            shuffle_seed=cfg.data.shuffle_seed,
            pack=cfg.data.pack_sequences,
        )
        if cfg.data.pack_sequences and dataset.sequences:
            cfg = _apply_packed_window(cfg, max(
                min(len(s), cfg.data.max_seq_len) for s in dataset.sequences))
    print(f"steps/epoch: {dataset.steps_per_epoch()}")

    eval_dataset = None
    if args.eval_dataset:
        if not cfg.train.eval_steps:
            raise SystemExit("--eval-dataset needs --eval-steps > 0")
        if os.path.isfile(os.path.join(args.eval_dataset, "meta.json")):
            # Same formats as --dataset-path: a token store evals directly.
            from dlti_tpu.data import StreamingTokenDataset

            try:
                eval_dataset = StreamingTokenDataset(
                    args.eval_dataset,
                    micro_batch_size=cfg.train.micro_batch_size,
                    grad_accum_steps=1,
                    shuffle_seed=None,  # fixed order: eval loss is comparable
                    expect_tokenizer=cfg.data.tokenizer,
                )
            except ValueError as e:
                raise SystemExit(str(e))
            if eval_dataset.seq_len != cfg.data.max_seq_len:
                raise SystemExit(
                    f"eval token store {args.eval_dataset} was written with "
                    f"seq_len={eval_dataset.seq_len}, but --max-seq-len is "
                    f"{cfg.data.max_seq_len}")
            print(f"eval dataset: token store {args.eval_dataset} "
                  f"({eval_dataset._ids.shape[0]} rows)")
            if eval_dataset.packed and cfg.parallel.pipe > 1:
                raise SystemExit(
                    "the eval token store is packed, and packed batches "
                    "are not supported under --pipe; re-prepare the eval "
                    "split without --pack")
            if (eval_dataset.packed and cfg.model.packed_attention_window
                    and eval_dataset.max_doc_len
                    > cfg.model.packed_attention_window):
                # The banded window is exact only if it covers the longest
                # document either split contains; widen it to stay exact
                # for eval (>= seq_len disables the band entirely).
                widened = (0 if eval_dataset.max_doc_len
                           >= cfg.data.max_seq_len
                           else eval_dataset.max_doc_len)
                cfg = cfg.replace(model=dataclasses.replace(
                    cfg.model, packed_attention_window=widened))
                print(f"packed attention window widened to {widened or 'off'}"
                      f" (eval corpus max doc length)")
        else:
            eval_texts = load_texts(args.eval_dataset)
            print(f"eval dataset: {len(eval_texts)} examples from "
                  f"{args.eval_dataset}")
            eval_dataset = make_batches(
                eval_texts, get_tokenizer(cfg.data.tokenizer),
                seq_len=cfg.data.max_seq_len,
                micro_batch_size=cfg.train.micro_batch_size,
                grad_accum_steps=1,
                shuffle_seed=None,  # fixed order: eval loss is comparable
            )
        if eval_dataset.steps_per_epoch() == 0:
            raise SystemExit(
                f"eval dataset yields zero batches: it has fewer rows than "
                f"one global batch ({cfg.train.micro_batch_size}); shrink "
                f"--per-device-batch-size or grow the eval split")

    trainer = Trainer(cfg, base_params=base_params)
    state, record = trainer.train(dataset=dataset, eval_dataset=eval_dataset)

    if args.export_dir:
        from dlti_tpu.checkpoint import export_merged_model

        export_merged_model(args.export_dir, state.params, cfg)
        print(f"merged export -> {args.export_dir}")
    if args.export_peft:
        import jax

        from dlti_tpu.models import save_peft_adapter

        save_peft_adapter(args.export_peft, jax.device_get(state.params), cfg.lora)
        print(f"PEFT adapter -> {args.export_peft}")
    if args.export_hf:
        import jax

        from dlti_tpu.models import merge_lora_params, save_hf_checkpoint

        merged = merge_lora_params(jax.device_get(state.params), alpha=cfg.lora.alpha)
        save_hf_checkpoint(args.export_hf, merged, cfg.model)
        print(f"HF checkpoint -> {args.export_hf}")


if __name__ == "__main__":
    main()
