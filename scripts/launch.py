#!/usr/bin/env python
"""Process launcher CLI — the torchrun / `deepspeed` / srun-glue analog.

Examples:

    # 4 local worker processes (the `torchrun --nproc_per_node=4` analog)
    python scripts/launch.py --num-processes 4 -- \
        python scripts/train.py --preset zero2 ...

    # inside an sbatch (one srun task per host; see dlti_tpu.orchestration.emit_slurm)
    srun python scripts/launch.py --coordinator-from-slurm -- \
        python scripts/train.py --preset zero3 ...

Workers receive DLTI_COORDINATOR / DLTI_NUM_PROCESSES / DLTI_PROCESS_ID and
entry points pick them up via dlti_tpu.launcher.maybe_initialize_from_env().
"""

import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.launcher import main

if __name__ == "__main__":
    sys.exit(main())
