#!/usr/bin/env python
"""Serving CLI — launch the OpenAI-compatible TPU inference server.

The serving leg the reference claims (vLLM + TP, ``README.md:10,16``) but
never ships (SURVEY.md §0): paged KV cache, continuous batching, streaming
SSE, ``/v1/completions`` + ``/v1/chat/completions``.

Usage:
    # serve a consolidated export written by scripts/train.py --export-dir
    python scripts/serve.py --model-dir exports/run1 \
        --tokenizer meta-llama/Llama-2-7b-hf --port 8000

    # hermetic smoke: random-weight tiny model + byte tokenizer
    python scripts/serve.py --random-init llama_tiny --tokenizer byte

    # disaggregated: 2 prefill + 2 decode replicas, paged-KV handoff
    python scripts/serve.py --random-init llama_tiny --tokenizer byte \
        --disagg --prefill-replicas 2 --decode-replicas 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.utils.platform import honor_platform_env

honor_platform_env()


def parse_args():
    p = argparse.ArgumentParser(description="TPU-native LLM server",
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--model-dir", default=None,
                   help="consolidated export dir (scripts/train.py --export-dir)")
    p.add_argument("--random-init", default=None, metavar="PRESET",
                   help="serve a random-weight model preset (smoke/bench)")
    p.add_argument("--tokenizer", default="meta-llama/Llama-2-7b-hf")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--max-seqs", type=int, default=8, help="decode batch slots")
    p.add_argument("--num-blocks", type=int, default=2048, help="KV pool blocks")
    p.add_argument("--block-size", type=int, default=16, help="tokens per KV block")
    p.add_argument("--max-model-len", type=int, default=2048)
    p.add_argument("--max-tokens-default", type=int, default=256)
    p.add_argument("--enable-prefix-caching", action="store_true",
                   help="reuse KV blocks across requests sharing a prompt "
                        "prefix (content-addressed, LRU-evicted)")
    # -- prefix-cache tiering (dlti_tpu.serving.prefix_tiers) -----------
    p.add_argument("--prefix-host-blocks", type=int, default=0,
                   help="host-RAM prefix tier budget in KV blocks: evicted "
                        "HBM prefix blocks demote here instead of being "
                        "discarded, and restore with one host->device "
                        "scatter instead of a re-prefill (0 = tier off; "
                        "implies --enable-prefix-caching)")
    p.add_argument("--prefix-disk-dir", default="",
                   help="disk prefix tier directory: host-tier overflow "
                        "demotes to digest-verified block dirs here "
                        "(checkpoint-store manifest/SHA-256 format; corrupt "
                        "blocks quarantine to _quarantine/ and read as "
                        "misses)")
    p.add_argument("--prefix-disk-blocks", type=int, default=0,
                   help="disk prefix tier budget in block dirs (0 = disk "
                        "tier off; needs --prefix-disk-dir)")
    p.add_argument("--tensor", type=int, default=1,
                   help="tensor-parallel extent: shard weights + KV pools "
                        "over this many chips (ICI collectives via GSPMD)")
    p.add_argument("--replicas", type=int, default=1,
                   help="data-parallel engine replicas (each tensor-wide); "
                        "a replica whose step faults is excluded and its "
                        "requests fail over to survivors")
    # -- multi-process fleet (dlti_tpu.serving.fleet) -------------------
    p.add_argument("--fleet-workers", type=int, default=0,
                   help="serve from N engine WORKER PROCESSES behind the "
                        "fleet supervisor (TCP wire protocol, per-process "
                        "failure domains): a SIGKILL'd worker is "
                        "respawned and canary-reinstated while its "
                        "in-flight work fails over / migrates; outputs "
                        "are byte-identical to the in-process engine "
                        "(0 = off; overrides --replicas)")
    p.add_argument("--fleet-runtime-dir", default="",
                   help="fleet scratch dir (worker spec, port files, "
                        "per-worker logs); default: a per-PID dir under "
                        "the system temp dir")
    p.add_argument("--fleet-respawn-backoff", type=float, default=0.5,
                   help="initial respawn backoff after a worker death "
                        "(doubles per consecutive failure, capped at 30s)")
    p.add_argument("--fleet-restart-budget", type=int, default=8,
                   help="respawns allowed per worker before it is "
                        "permanently evicted")
    # -- prefill/decode disaggregation (dlti_tpu.serving.disagg) --------
    p.add_argument("--disagg", action="store_true",
                   help="prefill/decode disaggregation: prompts prefill on "
                        "a dedicated pool, then their paged-KV blocks "
                        "migrate to a decode pool — long prefills stop "
                        "inflating neighbours' decode TPOT (overrides "
                        "--replicas; pool sizes below)")
    p.add_argument("--prefill-replicas", type=int, default=1,
                   help="prefill-pool replicas (each tensor-wide; needs "
                        "--disagg)")
    p.add_argument("--decode-replicas", type=int, default=1,
                   help="decode-pool replicas (each tensor-wide; needs "
                        "--disagg)")
    p.add_argument("--handoff-queue-depth", type=int, default=8,
                   help="finished prefills staged per decode replica "
                        "awaiting a free slot; full queues leave prefill "
                        "slots occupied (admission backpressure)")
    p.add_argument("--handoff-deadline-s", type=float, default=0.0,
                   help="staged longer than this re-prefills on the decode "
                        "replica instead of waiting for adoption (0 = "
                        "wait indefinitely)")
    # -- admission gateway (dlti_tpu.serving.gateway) -------------------
    p.add_argument("--gateway", action="store_true",
                   help="enable the admission gateway: bounded queue with "
                        "429 overflow, per-tenant rate limits, "
                        "interactive>batch priority, deadline shed, "
                        "graceful SIGTERM drain")
    p.add_argument("--max-queued-requests", type=int, default=256,
                   help="gateway queue bound (requests); overflow -> 429 "
                        "+ Retry-After")
    p.add_argument("--max-queued-tokens", type=int, default=0,
                   help="gateway queue bound (total queued prompt tokens); "
                        "0 = request bound only")
    p.add_argument("--rate-limit-rps", type=float, default=0.0,
                   help="per-tenant sustained admission rate (req/s); "
                        "0 = off")
    p.add_argument("--rate-limit-burst", type=float, default=0.0,
                   help="per-tenant token-bucket burst capacity; 0 derives "
                        "max(1, 2*rps)")
    p.add_argument("--tenant-weights", default="",
                   help="weighted fair dequeue, e.g. 'teamA:4,teamB:1' "
                        "(unlisted tenants weigh 1)")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="seconds SIGTERM waits for in-flight requests "
                        "before exiting anyway")
    p.add_argument("--max-retries", type=int, default=2,
                   help="failover resubmissions per request after a "
                        "replica step fault")
    p.add_argument("--fault-inject-step", default="",
                   help="chaos hook 'REPLICA:STEP[:MODE]': kill that "
                        "replica on its STEP-th step — MODE 'raise' "
                        "(default) raises in place of a device fault; "
                        "'nan-logits' poisons the replica's params so "
                        "the engine's numeric output guard trips the "
                        "same quarantine; 'preempt' simulates a planned "
                        "preemption notice (drain via live KV migration, "
                        "then quarantine) (also env "
                        "DLTI_GATEWAY_FAULT_INJECT)")
    p.add_argument("--self-heal", action="store_true",
                   help="replica lifecycle healing: a faulted replica is "
                        "quarantined, rebuilt from known-good weights, "
                        "and reinstated after a passing canary probe "
                        "(default: a faulted replica stays dead)")
    p.add_argument("--probation", type=float, default=2.0,
                   help="seconds before a quarantined replica's first "
                        "reinstate probe (doubles per failed probe, "
                        "capped at 60s)")
    p.add_argument("--flap-window", type=float, default=300.0,
                   help="flap-breaker window: more than --flap-max-cycles "
                        "quarantines inside this many seconds evicts the "
                        "replica permanently")
    p.add_argument("--flap-max-cycles", type=int, default=3,
                   help="quarantine/reinstate cycles tolerated inside "
                        "--flap-window before permanent eviction")
    p.add_argument("--reload-checkpoint", default="",
                   help="kick off a rolling weight reload at startup "
                        "from this checkpoint-store params export (same "
                        "artifact POST /v1/reload takes); mostly useful "
                        "with --self-heal drills")
    p.add_argument("--no-numeric-guard", action="store_true",
                   help="disable the nonfinite decode-output guard "
                        "(NumericFault -> replica quarantine; leaving it "
                        "on is how a numerically-dead replica never "
                        "streams garbage to users)")
    p.add_argument("--guard-token-storm", type=int, default=0,
                   help="quarantine a replica after N consecutive decode "
                        "steps where every active slot sampled the same "
                        "token (degenerate-output storm; 0 = off)")
    p.add_argument("--no-memory-ledger", action="store_true",
                   help="disable the HBM memory ledger "
                        "(telemetry.memledger): no per-owner attribution, "
                        "/debug/memory, hbm_* gauges, or memory.json in "
                        "flight dumps")
    p.add_argument("--hbm-budget-bytes", type=int, default=0,
                   help="HBM capacity for headroom accounting (0 = "
                        "auto-detect from device memory_stats(); stays "
                        "unknown on CPU, keeping headroom features off)")
    p.add_argument("--admit-min-headroom-frac", type=float, default=0.0,
                   help="defer admitting new requests while ledger "
                        "headroom is below this fraction of capacity "
                        "(0 = off; deferred requests stay queued — "
                        "latency, never a client error)")
    p.add_argument("--affinity", action="store_true",
                   help="cache-affinity routing: sticky rendezvous-hash a "
                        "session key (X-Session header, else hashed prompt "
                        "prefix) to a replica so repeat sessions land on "
                        "warm prefix caches; spills least-loaded past the "
                        "backlog threshold (needs --gateway)")
    p.add_argument("--affinity-spill-threshold", type=int, default=4,
                   help="spill to least-loaded when the sticky replica's "
                        "backlog exceeds its decode slots by more than "
                        "this many requests")
    p.add_argument("--affinity-prefix-tokens", type=int, default=32,
                   help="prompt tokens hashed into the affinity key when "
                        "no X-Session header is present")
    # -- multi-LoRA serving (dlti_tpu.serving.adapters) -----------------
    p.add_argument("--adapter-slots", type=int, default=0,
                   help="HBM adapter-pool slots: one decode batch serves "
                        "up to this many distinct LoRA adapters over ONE "
                        "shared base (S-LoRA-style gathered einsum); "
                        "0 = multi-LoRA off, engine traces identically to "
                        "an adapter-free build")
    p.add_argument("--adapter-rank", type=int, default=16,
                   help="pool rank ceiling R: registered adapters of rank "
                        "<= R zero-pad into the stacked pool (float-exact)")
    p.add_argument("--adapter", action="append", default=[],
                   metavar="NAME=DIR",
                   help="register adapter NAME from checkpoint-store DIR "
                        "(scripts/train.py --export-adapter-dir / "
                        "save_adapter) at startup; repeatable. More can "
                        "hot-load later via POST /v1/adapters")
    p.add_argument("--adapter-map", default="",
                   help="tenant->adapter routing, e.g. "
                        "'teamA:ad1,teamB:ad2': requests without an "
                        "explicit X-Adapter header get their tenant's "
                        "adapter (needs --gateway; X-Adapter always works)")
    p.add_argument("--steps-per-sync", type=int, default=1,
                   help="decode iterations per compiled program (multi-step "
                        "scheduling; amortizes host round-trips)")
    p.add_argument("--kv-cache-dtype", default="bfloat16",
                   choices=["bfloat16", "float16", "float32", "int8"],
                   help="KV pool dtype; int8 stores per-row-scaled "
                        "payloads at half the bf16 HBM (roughly double "
                        "the decode slots on a fixed chip)")
    p.add_argument("--quantization", default="none", choices=["none", "int8"],
                   help="weight-only quantization (int8 + per-channel scales; "
                        "~halves weight HBM)")
    p.add_argument("--no-decode-state-cache", action="store_true",
                   help="disable the device-resident decode-state cache "
                        "(per-slot dirty tracking; clean decode steps "
                        "upload no host state) and re-upload every mirror "
                        "each step — debugging/A-B only, outputs are "
                        "byte-identical either way")
    p.add_argument("--speculative", default="none", choices=["none", "ngram"],
                   help="n-gram prompt-lookup speculative decoding (exact "
                        "greedy outputs, multiple tokens per model call)")
    p.add_argument("--num-draft-tokens", type=int, default=4)
    p.add_argument("--max-prefill-tokens", type=int, default=0,
                   help="chunked prefill: cap prompt tokens prefilled per "
                        "engine step so decode never stalls a full prompt "
                        "length (latency mode; 0 = unbounded throughput "
                        "mode)")
    p.add_argument("--ngram-size", type=int, default=2,
                   help="trailing n-gram length matched for prompt lookup")
    p.add_argument("--spec-min-acceptance", type=float, default=0.25,
                   help="adaptive speculative gate: pause proposing when "
                        "mean extra tokens per greedy slot-round fall below "
                        "this (0 = always speculate)")
    p.add_argument("--spec-probe-window", type=int, default=64,
                   help="greedy slot-rounds measured before each gate "
                        "decision")
    p.add_argument("--spec-cooldown", type=int, default=32,
                   help="engine rounds the gate pauses a slot's proposing "
                        "for after a failed probe window")
    p.add_argument("--no-spec-adaptive", action="store_true",
                   help="pin the draft length at --num-draft-tokens "
                        "instead of picking it per round from live "
                        "per-slot acceptance (the pow2 draft-length "
                        "ladder; outputs are byte-identical either way)")
    p.add_argument("--ragged-prefill", action="store_true",
                   help="pack prefill chunks from many admissions into "
                        "shared ragged program calls (group width = "
                        "widest member, padding bounded) instead of one "
                        "call per length bucket — fewer dispatches under "
                        "multi-admission waves, byte-identical outputs")
    p.add_argument("--trace-dir", default="",
                   help="enable the host-side span tracer (per-request "
                        "lifecycle + engine step phases) and export a "
                        "Chrome-trace JSON here on shutdown; a live "
                        "snapshot is served at GET /debug/trace. Open "
                        "either in Perfetto (ui.perfetto.dev)")
    p.add_argument("--trace-capacity", type=int, default=65536,
                   help="span ring-buffer capacity (most recent events "
                        "kept; a long-lived server never grows past it)")
    # -- self-monitoring (dlti_tpu.telemetry.{watchdog,flightrecorder}) --
    # A /debug/vars time-series ring + /dashboard page are always on.
    p.add_argument("--watchdog", action="store_true",
                   help="enable the anomaly watchdog: throughput "
                        "collapse, gateway queue/shed buildup rules over "
                        "the /debug/vars ring, alerting via "
                        "dlti_watchdog_alerts_total + JSONL event log")
    p.add_argument("--watchdog-action", default="log",
                   choices=["log", "dump", "abort"],
                   help="alert escalation: log only, also dump a flight "
                        "record, or dump + abort the process (CI chaos)")
    p.add_argument("--watchdog-queue-depth", type=int, default=64,
                   help="queue_buildup rule threshold (gateway queue "
                        "depth sustained 3 samples; 0 = rule off)")
    p.add_argument("--watchdog-shed-rate", type=float, default=1.0,
                   help="shed_buildup rule threshold (gateway "
                        "sheds+rejections per second; 0 = rule off)")
    # -- SLO engine (dlti_tpu.telemetry.slo) ---------------------------
    p.add_argument("--slo", action="store_true",
                   help="enable the SLO engine: objectives over the "
                        "request SLIs, rolling error budgets, "
                        "multi-window burn-rate alerting (watchdog "
                        "slo_burn rule), GET /debug/slo, dlti_slo_* "
                        "gauges, slo.json in flight dumps")
    p.add_argument("--slo-window", type=float, default=3600.0,
                   help="SLO compliance / error-budget window seconds")
    p.add_argument("--slo-burn-tiers", default="14:60:5,6:300:30",
                   help="burn-rate alert tiers 'factor:long_s:short_s,"
                        "...' — fires when the budget burns >= factor x "
                        "over BOTH windows of a tier")
    p.add_argument("--slo-ttft-s", type=float, default=0.0,
                   help="TTFT objective threshold seconds (snapped to "
                        "the nearest histogram bucket bound; 0 = off)")
    p.add_argument("--slo-ttft-target", type=float, default=0.99,
                   help="fraction of requests that must meet the TTFT "
                        "threshold")
    p.add_argument("--slo-tpot-s", type=float, default=0.0,
                   help="per-token decode latency objective threshold "
                        "seconds (0 = off)")
    p.add_argument("--slo-tpot-target", type=float, default=0.99,
                   help="fraction of requests that must meet the TPOT "
                        "threshold")
    p.add_argument("--slo-queue-s", type=float, default=0.0,
                   help="engine queue-delay objective threshold seconds "
                        "(0 = off)")
    p.add_argument("--slo-queue-target", type=float, default=0.99,
                   help="fraction of requests that must meet the "
                        "queue-delay threshold")
    p.add_argument("--slo-availability-target", type=float, default=0.0,
                   help="fraction of gateway arrivals that must be "
                        "served (not shed/rejected), per priority class "
                        "and overall; needs --gateway; 0 = off")
    p.add_argument("--flight-dir", default="",
                   help="enable the flight recorder: on engine fault, "
                        "replica death, SIGTERM, or watchdog escalation, "
                        "dump a flight-*/ black box (span tail, metrics, "
                        "time-series tail) here; render with "
                        "scripts/postmortem.py")
    p.add_argument("--slow-log-k", type=int, default=32,
                   help="worst-latency requests retained with full "
                        "critical-path timelines (queue, prefill, tier "
                        "restore, failover, decode) for GET /debug/slow")
    p.add_argument("--deploy-watch", default="", metavar="DIR",
                   help="continuous delivery (serving.deploy): watch this "
                        "training checkpoint dir for newly COMMITted "
                        "verified steps, export each candidate, canary it "
                        "on shadow traffic beside the fleet, and promote "
                        "or roll back autonomously; needs a replicated "
                        "fleet (--replicas/--self-heal/--fleet-workers)")
    p.add_argument("--deploy-export-dir", default="",
                   help="where candidate params exports land "
                        "(default: <watch>/_deploy_exports)")
    p.add_argument("--deploy-poll-interval", type=float, default=5.0,
                   help="checkpoint-dir poll cadence, seconds")
    p.add_argument("--canary-shadow-frac", type=float, default=0.25,
                   help="fraction of live requests mirrored onto the "
                        "canary engine as shadow traffic (shadow results "
                        "never reach clients and never book into client "
                        "SLIs)")
    p.add_argument("--canary-min-requests", type=int, default=8,
                   help="completed shadow/live request pairs required "
                        "before the canary verdict")
    p.add_argument("--canary-max-wait", type=float, default=120.0,
                   help="max seconds to wait for --canary-min-requests "
                        "before judging with whatever shadow traffic "
                        "arrived")
    p.add_argument("--promote-max-logprob-drift", type=float, default=0.25,
                   help="max |mean greedy logprob delta| per pinned probe "
                        "prompt vs the incumbent before the candidate is "
                        "rejected")
    p.add_argument("--promote-backoff", type=float, default=30.0,
                   help="initial backoff after a rollback before the next "
                        "candidate is canaried (doubles per consecutive "
                        "rollback)")
    return p.parse_args()


def main() -> None:
    args = parse_args()
    if not args.model_dir and not args.random_init:
        raise SystemExit("need --model-dir or --random-init PRESET")

    import jax
    import jax.numpy as jnp

    from dlti_tpu.data import get_tokenizer
    from dlti_tpu.serving import (
        EngineConfig, InferenceEngine, SamplingParams, ServerConfig, serve,
    )

    tok = get_tokenizer(args.tokenizer)

    tracer = None
    if args.trace_dir:
        from dlti_tpu.telemetry import configure_tracer

        # Enable BEFORE the engine is built so its lifecycle hooks see an
        # enabled tracer from the first request.
        tracer = configure_tracer(enabled=True,
                                  capacity=args.trace_capacity)

    if args.model_dir:
        from dlti_tpu.checkpoint import load_exported_model

        params, cfg = load_exported_model(args.model_dir)
        model_cfg = cfg.model
        lora_cfg = cfg.lora if cfg.lora.enabled else None
        print(f"loaded export {args.model_dir} "
              f"(layers={model_cfg.num_layers}, hidden={model_cfg.hidden_size})")
    else:
        from dlti_tpu.config import MODEL_PRESETS
        from dlti_tpu.models import LlamaForCausalLM

        model_cfg = MODEL_PRESETS[args.random_init]
        lora_cfg = None
        model = LlamaForCausalLM(model_cfg, None)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        print(f"random-initialized preset {args.random_init}")

    tiered = args.prefix_host_blocks > 0 or (
        args.prefix_disk_blocks > 0 and args.prefix_disk_dir)
    ec = EngineConfig(
        max_seqs=args.max_seqs, block_size=args.block_size,
        num_blocks=args.num_blocks, max_model_len=args.max_model_len,
        eos_token_id=tok.eos_id,
        enable_prefix_caching=args.enable_prefix_caching or tiered,
        prefix_host_blocks=args.prefix_host_blocks,
        prefix_disk_dir=args.prefix_disk_dir,
        prefix_disk_blocks=args.prefix_disk_blocks,
        steps_per_sync=args.steps_per_sync,
        cache_dtype=args.kv_cache_dtype,
        quantization=args.quantization,
        speculative=args.speculative,
        num_draft_tokens=args.num_draft_tokens,
        ngram_size=args.ngram_size,
        spec_min_acceptance=args.spec_min_acceptance,
        spec_probe_window=args.spec_probe_window,
        spec_cooldown=args.spec_cooldown,
        spec_adaptive=not args.no_spec_adaptive,
        max_prefill_tokens_per_step=args.max_prefill_tokens,
        ragged_prefill=args.ragged_prefill,
        decode_state_cache=not args.no_decode_state_cache,
        guard_nonfinite=not args.no_numeric_guard,
        guard_token_storm=args.guard_token_storm,
        memory_ledger=not args.no_memory_ledger,
        hbm_budget_bytes=args.hbm_budget_bytes,
        admit_min_headroom_frac=args.admit_min_headroom_frac,
        adapter_slots=args.adapter_slots,
        adapter_rank=args.adapter_rank,
    )
    if args.adapter:
        # Register BEFORE the engines are built: verification (manifest
        # digests) fails fast on a corrupt directory at startup, and the
        # catalog is process-global so every replica resolves the names.
        from dlti_tpu.serving.adapters import register_adapter

        if args.adapter_slots <= 0:
            raise SystemExit("--adapter needs --adapter-slots > 0")
        for spec in args.adapter:
            name, sep, adir = spec.partition("=")
            if not sep or not name.strip() or not adir.strip():
                raise SystemExit(f"--adapter expects NAME=DIR, got {spec!r}")
            register_adapter(name.strip(), adir.strip())
            print(f"registered adapter {name.strip()!r} from {adir.strip()}")
    from dlti_tpu.config import ReplicaLifecycleConfig

    lc_cfg = ReplicaLifecycleConfig(
        enabled=args.self_heal,
        probation_initial_s=args.probation,
        flap_window_s=args.flap_window,
        flap_max_cycles=args.flap_max_cycles)
    if args.fleet_workers > 0:
        if args.disagg:
            raise SystemExit("--fleet-workers and --disagg are mutually "
                             "exclusive (disagg pools stay in-process)")
        import dataclasses
        import tempfile

        from dlti_tpu.config import FleetConfig
        from dlti_tpu.serving import FleetSupervisor, make_subprocess_spawner

        runtime_dir = args.fleet_runtime_dir or os.path.join(
            tempfile.gettempdir(), f"dlti_fleet_{os.getpid()}")
        # Everything a worker needs to build a byte-identical engine: the
        # same model source, engine config, adapters, and the parent's
        # matmul precision (the env half of the platform setup is
        # inherited through the child env).
        spec = {
            "model_dir": args.model_dir,
            "model_preset": args.random_init,
            "engine": dataclasses.asdict(ec),
            "matmul_precision": jax.config.jax_default_matmul_precision,
            "adapters": {name.strip(): adir.strip()
                         for name, _, adir in
                         (s.partition("=") for s in args.adapter)},
            "warmup": True,
            "slow_log_k": args.slow_log_k,
            "flight_dir": args.flight_dir,
        }
        # Fleet healing is always on: respawn-on-death is the point of
        # per-process failure domains (--self-heal only tunes probation).
        engine = FleetSupervisor(
            ec, workers=args.fleet_workers,
            spawner=make_subprocess_spawner(spec, runtime_dir,
                                            host="127.0.0.1"),
            fleet_cfg=FleetConfig(
                workers=args.fleet_workers,
                respawn_backoff_s=args.fleet_respawn_backoff,
                restart_budget=args.fleet_restart_budget),
            lifecycle_cfg=dataclasses.replace(lc_cfg, enabled=True),
            max_retries=args.max_retries,
            affinity_spill_threshold=args.affinity_spill_threshold,
            canary_vocab=model_cfg.vocab_size)
        print(f"fleet supervisor: {args.fleet_workers} worker "
              f"process(es) ready (runtime dir {runtime_dir})")
    elif args.disagg:
        from dlti_tpu.serving import DisaggController

        engine = DisaggController(
            model_cfg, params, ec, lora_cfg,
            prefill_replicas=args.prefill_replicas,
            decode_replicas=args.decode_replicas,
            tensor=args.tensor,
            max_retries=args.max_retries,
            # Pool-scoped here: "POOL:REPLICA:STEP[:MODE]".
            fault_inject_step=args.fault_inject_step,
            handoff_queue_depth=args.handoff_queue_depth,
            handoff_deadline_s=args.handoff_deadline_s,
            affinity_spill_threshold=args.affinity_spill_threshold,
            lifecycle_cfg=lc_cfg)
    elif args.replicas > 1 or args.self_heal or args.reload_checkpoint:
        # A sole replica still gets the lifecycle layer when healing or
        # a rolling reload is requested — quarantine/probe/reinstate and
        # weight swaps work fleet-of-one (migration has no survivors, so
        # drains wait for in-flight work instead).
        from dlti_tpu.serving import ReplicatedEngine

        engine = ReplicatedEngine(
            model_cfg, params, ec, lora_cfg,
            replicas=args.replicas, tensor=args.tensor,
            max_retries=args.max_retries,
            fault_inject_step=args.fault_inject_step,
            affinity_spill_threshold=args.affinity_spill_threshold,
            lifecycle_cfg=lc_cfg)
    else:
        mesh = None
        if args.tensor > 1:
            from dlti_tpu.config import ParallelConfig
            from dlti_tpu.parallel import build_mesh

            mesh = build_mesh(ParallelConfig(tensor=args.tensor))
        engine = InferenceEngine(model_cfg, params, ec, lora_cfg, mesh=mesh,
                                 donate_params=True)
    # The engine owns (a possibly quantized copy of) the weights now; this
    # frame's reference would otherwise pin the original tree in HBM for
    # the server's lifetime — 13.5 GB of dead bf16 under --quantization.
    del params
    gw_cfg = None
    if args.gateway:
        from dlti_tpu.config import GatewayConfig

        gw_cfg = GatewayConfig(
            enabled=True,
            max_queued_requests=args.max_queued_requests,
            max_queued_tokens=args.max_queued_tokens,
            rate_limit_rps=args.rate_limit_rps,
            rate_limit_burst=args.rate_limit_burst,
            tenant_weights=args.tenant_weights,
            drain_grace_s=args.drain_grace,
            max_retries=args.max_retries,
            fault_inject_step=args.fault_inject_step,
            affinity=args.affinity,
            affinity_spill_threshold=args.affinity_spill_threshold,
            affinity_prefix_tokens=args.affinity_prefix_tokens,
            adapter_map=args.adapter_map)
    from dlti_tpu.config import (
        FlightRecorderConfig, SLOConfig, TelemetryConfig, WatchdogConfig,
    )

    tel_cfg = TelemetryConfig(
        trace_dir=args.trace_dir,
        trace_capacity=args.trace_capacity,
        slo=SLOConfig(
            enabled=args.slo,
            window_s=args.slo_window,
            burn_tiers=args.slo_burn_tiers,
            ttft_threshold_s=args.slo_ttft_s,
            ttft_target=args.slo_ttft_target,
            tpot_threshold_s=args.slo_tpot_s,
            tpot_target=args.slo_tpot_target,
            queue_threshold_s=args.slo_queue_s,
            queue_target=args.slo_queue_target,
            availability_target=args.slo_availability_target),
        watchdog=WatchdogConfig(
            enabled=args.watchdog,
            action=args.watchdog_action,
            queue_depth_limit=args.watchdog_queue_depth,
            shed_rate_limit=args.watchdog_shed_rate,
            alert_log_path=(os.path.join(args.flight_dir,
                                         "watchdog_alerts.jsonl")
                            if args.flight_dir else "")),
        flight_recorder=FlightRecorderConfig(dir=args.flight_dir))
    sc = ServerConfig(host=args.host, port=args.port,
                      default_params=SamplingParams(max_tokens=args.max_tokens_default),
                      gateway=gw_cfg, telemetry=tel_cfg)
    # Critical-path slow log sizing (telemetry.ledger): the engines share
    # one RequestTelemetry, so one SlowLog serves the whole fleet.
    engine.telemetry.critical_path.slow.k = max(1, args.slow_log_k)
    print("pre-compiling decode programs (single-step + multi-step ladder)...")
    t0 = time.time()
    engine.warmup_decode_ladder()
    print(f"decode programs ready in {time.time() - t0:.0f}s")
    if args.disagg:
        # Concurrent pool stepping: long prefills overlap decode dispatch
        # instead of serializing with it in the stepper thread.
        engine.start()
        print(f"disaggregated pools: {args.prefill_replicas} prefill + "
              f"{args.decode_replicas} decode replicas "
              f"(handoff queue depth {args.handoff_queue_depth})")
    if args.reload_checkpoint:
        # Startup-kicked rolling upgrade (the drill path: boot on old
        # weights, roll to new ones under load): same verified-load
        # contract as POST /v1/reload.
        reload_fn = getattr(engine, "request_reload", None)
        if reload_fn is None:
            raise SystemExit("--reload-checkpoint needs a replicated "
                             "fleet (--replicas > 1 or --disagg)")
        from dlti_tpu.checkpoint.store import load_pytree

        rdir = args.reload_checkpoint
        reload_fn(lambda: load_pytree(rdir, verify=True))
        print(f"rolling weight reload queued from {rdir}")
    deploy = None
    if args.deploy_watch:
        # Continuous delivery: the controller watches the training run's
        # checkpoint dir, exports each new verified step, canaries it on
        # a shadow replica built BESIDE the fleet (client capacity never
        # shrinks), and promotes through the same rolling-reload path as
        # POST /v1/reload — or rolls back, quarantines, and refuses.
        if getattr(engine, "request_reload", None) is None:
            raise SystemExit("--deploy-watch needs a replicated fleet "
                             "(--replicas > 1, --self-heal, or "
                             "--fleet-workers)")
        import dataclasses as _dc

        from dlti_tpu.checkpoint.store import load_pytree as _load_pytree
        from dlti_tpu.config import DeployConfig
        from dlti_tpu.serving.deploy import DeploymentController

        dcfg = DeployConfig(
            enabled=True,
            watch_dir=args.deploy_watch,
            export_dir=args.deploy_export_dir,
            poll_interval_s=args.deploy_poll_interval,
            canary_shadow_frac=args.canary_shadow_frac,
            canary_min_requests=args.canary_min_requests,
            canary_max_wait_s=args.canary_max_wait,
            promote_max_logprob_drift=args.promote_max_logprob_drift,
            promote_backoff_s=args.promote_backoff,
            slo_ttft_threshold_s=args.slo_ttft_s,
            slo_tpot_threshold_s=args.slo_tpot_s)
        # The canary engine is a deliberately small shadow replica: a few
        # slots and a modest KV pool judge gates fine, and the tiered
        # prefix cache / adapters / memory ledger stay off so the shadow
        # can never contend with the fleet for those singletons.
        canary_ec = _dc.replace(
            ec, max_seqs=min(ec.max_seqs, 4),
            num_blocks=min(ec.num_blocks, 512),
            enable_prefix_caching=False, prefix_host_blocks=0,
            prefix_disk_dir="", prefix_disk_blocks=0,
            memory_ledger=False, adapter_slots=0)

        def _canary_factory(export_dir):
            cparams = _load_pytree(export_dir, verify=True)
            return InferenceEngine(model_cfg, cparams, canary_ec, None,
                                   donate_params=True)

        incumbent = args.model_dir if (args.model_dir and os.path.isfile(
            os.path.join(args.model_dir, "MANIFEST.json"))) else ""
        deploy = DeploymentController(
            engine, dcfg, canary_factory=_canary_factory,
            incumbent_dir=incumbent)
        print(f"deploy controller: watching {args.deploy_watch} "
              f"(shadow frac {args.canary_shadow_frac}, min pairs "
              f"{args.canary_min_requests}; control: /v1/deploy)")
    print(f"serving on http://{args.host}:{args.port}  "
          f"(pool: {args.num_blocks} blocks x {args.block_size} tokens)")
    print(f"live dashboard: http://{args.host}:{args.port}/dashboard  "
          f"(JSON: /debug/vars; profiler: POST /debug/profile)")
    try:
        serve(engine, tok, sc, deploy=deploy)
    finally:
        if args.fleet_workers > 0:
            engine.close()  # FT_SHUTDOWN + terminate/kill ladder
        if args.disagg:
            engine.stop()
        if tracer is not None:
            path = tracer.export(os.path.join(
                args.trace_dir, f"trace_serve_{os.getpid()}.json"))
            print(f"telemetry trace -> {path} (open in ui.perfetto.dev)")


if __name__ == "__main__":
    main()
