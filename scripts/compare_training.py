#!/usr/bin/env python
"""Training comparison CLI — reference L3 parity (``scripts/compare_training.py``).

Reads the metrics CSV written by training runs (same schema as the
reference's ``results/training_metrics.csv``), prints the comparison table
and key findings, and renders the 2x2 comparison figure.

Usage:
    python scripts/compare_training.py
    python scripts/compare_training.py --csv results/training_metrics.csv --no-plots
"""

from __future__ import annotations

import argparse
import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.analysis import compare


def main() -> None:
    p = argparse.ArgumentParser(description="compare training runs",
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--csv", default="results/training_metrics.csv")
    p.add_argument("--plot-out", default="results/plots/training_comparison.png")
    p.add_argument("--no-plots", action="store_true")
    args = p.parse_args()

    if not os.path.isfile(args.csv):
        raise SystemExit(
            f"{args.csv} not found — run scripts/train.py first (it appends "
            f"one row per run)"
        )
    compare(args.csv, plot_path=None if args.no_plots else args.plot_out)


if __name__ == "__main__":
    main()
