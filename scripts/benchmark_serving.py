#!/usr/bin/env python
"""Serving load-test CLI — the Locust/AsyncIO benchmark leg.

The reference pins ``locust``/``aiohttp`` and claims a benchmarking layer
(``README.md:11,17``; ``requirements.txt:35-36``) with no code (SURVEY.md
§0). This drives :mod:`dlti_tpu.benchmarks.loadgen` against any
OpenAI-compatible endpoint and reports throughput + latency percentiles
(+TTFT/TPOT in streaming mode).

Usage:
    python scripts/benchmark_serving.py --port 8000 --num-requests 128 \
        --concurrency 16 --max-tokens 64
    python scripts/benchmark_serving.py --qps 10 --no-stream --json-out results/serving.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

from dlti_tpu.benchmarks import LoadGenConfig, run_load_test


def main() -> None:
    p = argparse.ArgumentParser(description="async load generator",
                                formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num-requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--qps", type=float, default=None,
                   help="open-loop Poisson arrival rate (default: closed loop)")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--prompt", default="Write a function that reverses a linked list.")
    p.add_argument("--prompt-file", default=None,
                   help="file with one prompt per line: requests draw from "
                        "this pool (seeded), exercising varied prefill "
                        "lengths instead of one fixed prompt")
    p.add_argument("--chat", action="store_true", help="use /v1/chat/completions")
    p.add_argument("--tenants", type=int, default=0,
                   help="spread requests over N synthetic tenants via the "
                        "X-Tenant header (exercises the admission "
                        "gateway's per-tenant limits and fair dequeue)")
    p.add_argument("--priority-mix", default="",
                   help="priority class mix, e.g. 'interactive:0.8,"
                        "batch:0.2'; the report then includes per-class "
                        "TTFT/TPOT percentiles and shed counts")
    p.add_argument("--deadline", type=float, default=0.0,
                   help="per-request queued-deadline seconds (body "
                        "deadline_s; a gateway sheds expired queued "
                        "requests with 503)")
    p.add_argument("--sessions", type=int, default=0,
                   help="recurring-session (chat-shaped) mode: N concurrent "
                        "sessions each replaying a shared system prompt + "
                        "growing history with an X-Session header; the "
                        "report splits cold vs warm TTFT percentiles and "
                        "scrapes the server's prefix-cache hit rate "
                        "(num-requests is ignored: sessions x turns)")
    p.add_argument("--turns", type=int, default=4,
                   help="requests per session in --sessions mode")
    p.add_argument("--reuse-frac", type=float, default=1.0,
                   help="fraction of non-first turns that revisit their "
                        "session; the rest issue unrelated cold one-offs")
    p.add_argument("--long-prompt-frac", type=float, default=0.0,
                   help="mixed-interference mode: this fraction of "
                        "requests carries a synthetic ~long-prompt-tokens "
                        "prompt; the report splits short-request decode "
                        "TPOT p99 by concurrent-long-prefill overlap (the "
                        "disaggregation stressor)")
    p.add_argument("--long-prompt-tokens", type=int, default=512,
                   help="synthetic long-prompt length in tokens (exact "
                        "under the byte tokenizer)")
    p.add_argument("--adapters", type=int, default=0,
                   help="multi-LoRA mode: tag requests with an X-Adapter "
                        "header drawn from N names 'adapter-0'..'adapter-"
                        "N-1' (register them server-side first); the "
                        "report adds per-adapter TTFT/TPOT percentiles "
                        "and the scraped adapter-pool hit rate")
    p.add_argument("--adapter-mix", default="zipf",
                   choices=["zipf", "uniform"],
                   help="adapter draw: zipf (1/(i+1) skew — hot adapters "
                        "stay pool-resident, the tail exercises eviction) "
                        "or uniform")
    p.add_argument("--trace", default="",
                   help="replay a dlti-trace/1 JSONL workload trace "
                        "(benchmarks.traces): each event fires at its "
                        "recorded arrival offset with its own tenant / "
                        "priority / session / adapter / lengths / "
                        "deadline; num-requests, qps, tenants and "
                        "priority-mix are ignored")
    p.add_argument("--record-trace", default="",
                   help="write every request this run submits back out "
                        "as a dlti-trace/1 JSONL file, making the run a "
                        "replayable fixture (works in any drive mode, "
                        "replay included)")
    p.add_argument("--scrape-server-metrics", action="store_true",
                   help="attach the server's on-engine histogram "
                        "summaries (/metrics) to the report")
    p.add_argument("--no-stream", action="store_true",
                   help="non-streaming (usage-accurate token counts, no TTFT)")
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json-out", default=None, help="also write the report as JSON")
    args = p.parse_args()

    prompts = ()
    if args.prompt_file:
        with open(args.prompt_file) as f:
            prompts = tuple(line.rstrip("\r\n") for line in f if line.strip())
    cfg = LoadGenConfig(
        host=args.host, port=args.port, num_requests=args.num_requests,
        concurrency=args.concurrency, qps=args.qps, stream=not args.no_stream,
        max_tokens=args.max_tokens, temperature=args.temperature,
        prompt=args.prompt, prompts=prompts, chat=args.chat,
        timeout_s=args.timeout, seed=args.seed,
        tenants=args.tenants, priority_mix=args.priority_mix,
        deadline_s=args.deadline,
        scrape_server_metrics=args.scrape_server_metrics,
        sessions=args.sessions, turns=args.turns,
        reuse_frac=args.reuse_frac,
        long_prompt_frac=args.long_prompt_frac,
        long_prompt_tokens=args.long_prompt_tokens,
        adapters=args.adapters, adapter_mix=args.adapter_mix,
        trace=args.trace, record_trace=args.record_trace,
    )
    report = run_load_test(cfg)
    d = report.to_dict()
    print(json.dumps(d, indent=2))
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(d, f, indent=2)
        print(f"report -> {args.json_out}")


if __name__ == "__main__":
    main()
