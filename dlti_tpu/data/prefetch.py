"""Bounded background batch prefetcher — the training half of the
host-latency-hiding layer.

The r03 MFU ladder (results/mfu_investigation_r03.json) amortized *dispatch*
with ``steps_per_sync``, but the host work between compiled windows — batch
gather/pack/stack in ``TokenBatchDataset._gather`` plus the host→device
transfer — still sat on the critical path: the device idles while Python
stacks numpy rows. This module runs that work on a background thread,
double-buffered (depth ``Config.data.prefetch_depth``, default 2), and
optionally issues ``jax.device_put`` with the step's input sharding ahead of
need, so by the time the step thread asks for batch N+1 it is already
device-resident. The canonical design is tf.data's bounded prefetch queue
(Murray et al., VLDB 2021); this is the in-tree, schedule-preserving
equivalent.

Guarantees, in priority order:

1. **Identical batch order.** One worker thread consumes the source
   iterator sequentially into a FIFO queue — the step thread sees exactly
   the sequence it would have seen calling ``next()`` itself, so the loss
   trajectory is bit-identical with prefetch on or off (equivalence-tested
   in ``tests/test_host_overlap.py``).
2. **Bounded memory.** At most ``depth`` batches (plus the one in flight)
   are ever materialized ahead of the consumer.
3. **Preemption-safe shutdown.** :meth:`close` unblocks a worker stuck on
   a full queue, joins it, and is idempotent — the Trainer calls it on
   SIGTERM/``request_stop`` paths and at epoch end, so no daemon thread
   outlives the loop holding dataset references.
4. **Exception transparency.** A source-iterator failure re-raises on the
   consumer thread at the ``next()`` that would have produced the batch.

Telemetry: a queue-depth gauge and a per-fetch stall-time histogram
(names pinned in ``tests/test_bench_contract.py``), ``train/prefetch``
spans from the worker thread, and a raw ``stats`` dict for benchmarks.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, Tuple

from dlti_tpu.telemetry.registry import Gauge, Histogram

# Host-path latencies: stalls are ideally ~0 (buffer hit) and otherwise the
# gather/pack cost — microseconds to tens of milliseconds.
PREFETCH_STALL_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# Exposition-name contract (scraped/pinned like the dlti_<stat> names).
PREFETCH_METRIC_NAMES = (
    "dlti_train_prefetch_queue_depth",
    "dlti_train_prefetch_stall_seconds",
)

_OK, _ERR, _END = 0, 1, 2


class HostPrefetcher:
    """Iterate ``source`` on a background thread through a bounded queue.

    Yields ``(host_batch, placed_batch)`` pairs: ``host_batch`` is the
    source item untouched (the Trainer's recorder and window-stacking
    paths need host numpy), ``placed_batch`` is ``place_fn(host_batch)``
    when a placement function is given (typically ``jax.device_put`` with
    the step's input sharding — an *async* dispatch, so the transfer
    overlaps the in-flight step) and the same object otherwise.
    """

    def __init__(
        self,
        source: Iterable,
        depth: int = 2,
        place_fn: Optional[Callable] = None,
        tracer=None,
        span_name: str = "train/prefetch",
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = source
        self._place = place_fn
        self._span_name = span_name
        if tracer is None:
            from dlti_tpu.telemetry.tracer import get_tracer

            tracer = get_tracer()
        self._tracer = tracer
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._done = False
        self.queue_depth = Gauge(
            PREFETCH_METRIC_NAMES[0],
            help="batches buffered ahead of the training step thread")
        self.stall_time = Histogram(
            PREFETCH_METRIC_NAMES[1], PREFETCH_STALL_BUCKETS,
            help="time the step thread blocked waiting for the next batch",
            stats_key="train_prefetch_stall_seconds")
        # Raw counters for benchmarks (benchmarks_dev/host_overlap.py).
        self.stats = {"fetches": 0, "stalls": 0, "stall_time_s": 0.0}
        self._thread = threading.Thread(
            target=self._worker, name="dlti-prefetch", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def _put(self, item) -> bool:
        """Queue ``item``, yielding to :meth:`close` every 50 ms."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self) -> None:
        try:
            it = iter(self._source)
            while not self._stop.is_set():
                with self._tracer.span(self._span_name, cat="train"):
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    placed = self._place(batch) if self._place is not None \
                        else batch
                if not self._put((_OK, (batch, placed))):
                    return  # closed while blocked on a full queue
                self.queue_depth.set(self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — re-raised on consumer
            self._put((_ERR, e))
            return
        self._put((_END, None))

    # ------------------------------------------------------------------
    def __iter__(self) -> Iterator[Tuple]:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        tag, payload = self._q.get()
        stall = time.perf_counter() - t0
        self.queue_depth.set(self._q.qsize())
        if tag == _END:
            self._done = True
            self._thread.join(timeout=5.0)
            raise StopIteration
        if tag == _ERR:
            self._done = True
            raise payload
        # Stall accounting covers real batches only (the end-of-epoch
        # sentinel wait is not an input stall).
        self.stall_time.observe(stall)
        self.stats["fetches"] += 1
        self.stats["stall_time_s"] += stall
        if stall > 1e-4:  # below this the buffer effectively had it ready
            self.stats["stalls"] += 1
        return payload

    def buffered_batches(self) -> list:
        """The *placed* batches currently buffered ahead of the step
        thread — the memory ledger's ``prefetch_buffers`` owner handle
        (device bytes only exist where place_fn issued a device_put; a
        host-only buffer contributes nothing and that is correct).
        Racy-by-design read of the queue's internal deque: the ledger
        snapshot tolerates a batch popping mid-walk (deleted arrays are
        skipped), and no lock is worth taking on the step thread's hot
        producer/consumer path."""
        try:
            return [payload[1] for tag, payload in list(self._q.queue)
                    if tag == _OK]
        except Exception:
            return []

    def close(self) -> None:
        """Stop the worker and drop buffered batches. Idempotent; safe to
        call with the worker blocked on a full queue (preemption path)."""
        self._done = True
        self._stop.set()
        # Drain so a worker blocked in put() can observe the stop event.
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        self.queue_depth.set(0)
