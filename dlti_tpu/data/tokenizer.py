"""Tokenizer layer.

The reference uses HF ``AutoTokenizer`` (Rust ``tokenizers`` backend,
``train_baseline.py:115-117``) with pad=eos fallback. We wrap the same
data-plane (tokenization is host-side on GPU and TPU alike) and add a
hermetic :class:`ByteTokenizer` so tests and offline environments never need
the HF hub.
"""

from __future__ import annotations

from typing import List, Optional, Protocol


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    eos_id: int
    bos_id: int

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer with BOS/EOS/PAD specials — hermetic, vocab 259.

    id 0 = pad, 1 = bos, 2 = eos, byte b -> b + 3.
    """

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = 259

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: List[int]) -> str:
        # Ignore specials and out-of-vocab ids (a serving model's vocab may
        # exceed 259; decode must never raise on sampled ids).
        data = bytes(i - 3 for i in ids if 3 <= i < 259)
        return data.decode("utf-8", errors="replace")


class IdTokenizer:
    """Hermetic id-passthrough tokenizer: every id renders as ``<id> `` and
    text encodes by parsing that form (non-numeric words hash into the
    vocab). Exists for serving benchmarks against random-weight models,
    whose sampled ids exceed any real tokenizer's printable range — the
    byte tokenizer renders those as empty strings, which suppresses every
    SSE delta and zeroes streaming TTFT/TPOT measurements.
    """

    def __init__(self, vocab_size: int = 32000) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = vocab_size

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = []
        for w in text.split():
            if w.startswith("<") and w.endswith(">") and w[1:-1].isdigit():
                ids.append(int(w[1:-1]) % self.vocab_size)
            else:
                import zlib

                # crc32, not hash(): stable across processes (PYTHONHASHSEED).
                ids.append(3 + (zlib.crc32(w.encode()) % (self.vocab_size - 3)))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: List[int]) -> str:
        return " ".join(f"<{i}>" for i in ids)


class HFTokenizer:
    """Adapter over a HF fast tokenizer (pad=eos fallback like
    ``train_baseline.py:116-117``)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        if self._tok.pad_token is None:
            self._tok.pad_token = self._tok.eos_token
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id
        self.eos_id = self._tok.eos_token_id
        self.bos_id = (
            self._tok.bos_token_id if self._tok.bos_token_id is not None else self.eos_id
        )

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def get_tokenizer(name: str) -> Tokenizer:
    """"byte" / "id[:vocab]" -> hermetic tokenizers; else -> HF hub/path.

    "id:4096" bounds the IdTokenizer to a 4096-vocab model so hashed or
    parsed prompt ids never exceed the served model's embedding table.
    """
    if name == "byte":
        return ByteTokenizer()
    if name == "id" or name.startswith("id:"):
        suffix = name.split(":", 1)[1] if ":" in name else ""
        if suffix and not suffix.isdigit():
            raise ValueError(
                f"bad id-tokenizer spec {name!r}: expected 'id' or "
                f"'id:<vocab_size>' (e.g. 'id:4096')")
        return IdTokenizer(int(suffix) if suffix else 32000)
    return HFTokenizer(name)
