"""Tokenizer layer.

The reference uses HF ``AutoTokenizer`` (Rust ``tokenizers`` backend,
``train_baseline.py:115-117``) with pad=eos fallback. We wrap the same
data-plane (tokenization is host-side on GPU and TPU alike) and add a
hermetic :class:`ByteTokenizer` so tests and offline environments never need
the HF hub.
"""

from __future__ import annotations

from typing import List, Optional, Protocol


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    eos_id: int
    bos_id: int

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer with BOS/EOS/PAD specials — hermetic, vocab 259.

    id 0 = pad, 1 = bos, 2 = eos, byte b -> b + 3.
    """

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = 259

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: List[int]) -> str:
        # Ignore specials and out-of-vocab ids (a serving model's vocab may
        # exceed 259; decode must never raise on sampled ids).
        data = bytes(i - 3 for i in ids if 3 <= i < 259)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Adapter over a HF fast tokenizer (pad=eos fallback like
    ``train_baseline.py:116-117``)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        if self._tok.pad_token is None:
            self._tok.pad_token = self._tok.eos_token
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id
        self.eos_id = self._tok.eos_token_id
        self.bos_id = (
            self._tok.bos_token_id if self._tok.bos_token_id is not None else self.eos_id
        )

    def encode(self, text: str, add_bos: bool = False, add_eos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def get_tokenizer(name: str) -> Tokenizer:
    """"byte" -> hermetic ByteTokenizer; anything else -> HF hub/path."""
    if name == "byte":
        return ByteTokenizer()
    return HFTokenizer(name)
