"""Data pipeline: chat formatting, tokenization, batching.

Reference layer L1 (``scripts/prepare_dataset.py``) rebuilt with a per-host
sharded, packing-capable input pipeline designed to never starve the chips.
"""

from dlti_tpu.data.formats import format_conversation_for_llama2  # noqa: F401
from dlti_tpu.data.tokenizer import ByteTokenizer, get_tokenizer  # noqa: F401
from dlti_tpu.data.pipeline import (  # noqa: F401
    TokenBatchDataset,
    make_batches,
    tokenize_and_truncate,
)
from dlti_tpu.data.streaming import (  # noqa: F401
    StreamingTokenDataset,
    write_token_store,
)
from dlti_tpu.data.prefetch import (  # noqa: F401
    HostPrefetcher,
    PREFETCH_METRIC_NAMES,
)
