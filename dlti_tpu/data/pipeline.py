"""Input pipeline: tokenize → truncate/pack → per-host sharded batches.

Reference contract: tokenize with truncation to ``max_length=512``, no
padding at map time (``train_baseline.py:152-165``), dynamic padding in the
collator with labels = input_ids (``train_baseline.py:195-198``). Here the
collator is replaced by static-shape batches (XLA needs static shapes):
right-padding to ``max_seq_len`` with a loss mask, or optional sequence
*packing* (multiple documents per row + segment ids) which the reference
lacks and which removes pad waste — the single biggest input-side perf lever
on TPU.

Multi-host: each host materializes only its slice of every global batch
(``shard_by_host``), indexed by ``jax.process_index()`` — the analog of
the per-rank ``DistributedSampler`` HF Trainer gives the reference
implicitly — while the *schedule* (which rows feed which optimizer step)
stays a pure function of (corpus, seed, global batch shape), independent
of world size, so an elastic mesh reshape preserves it exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence

import numpy as np

from dlti_tpu.data.tokenizer import Tokenizer


def tokenize_and_truncate(
    texts: Sequence[str],
    tokenizer: Tokenizer,
    max_seq_len: int = 512,
    add_eos: bool = True,
) -> List[List[int]]:
    """Tokenize each text, truncating to ``max_seq_len`` (reference:
    ``truncation=True, max_length=512`` — ``train_baseline.py:155``)."""
    out = []
    for t in texts:
        ids = tokenizer.encode(t, add_bos=True, add_eos=add_eos)
        out.append(ids[:max_seq_len])
    return out


def pad_to_batch(
    seqs: List[List[int]], seq_len: int, pad_id: int
) -> tuple:
    """Right-pad to (len(seqs), seq_len); loss_mask 1 on real tokens."""
    n = len(seqs)
    ids = np.full((n, seq_len), pad_id, dtype=np.int32)
    mask = np.zeros((n, seq_len), dtype=np.int32)
    for i, s in enumerate(seqs):
        L = min(len(s), seq_len)
        ids[i, :L] = s[:L]
        mask[i, :L] = 1
    return ids, mask


def pack_sequences(
    seqs: List[List[int]], seq_len: int, pad_id: int, open_rows: int = 64
) -> tuple:
    """Greedy windowed first-fit packing: (ids, loss_mask, segment_ids).

    segment_ids are 1-based per document, 0 on padding — consumed by the
    attention segment mask so packed documents cannot attend across
    boundaries.

    Only the last ``open_rows`` rows are candidates for placement, keeping
    packing O(docs * open_rows) instead of O(docs * rows) — at corpus scale
    (the reference dataset is 136k docs, train.ipynb:50) unbounded first-fit
    is billions of Python iterations. When the native runtime is built the
    assignment loop runs in C++ (``native/packer.cc``) with a vectorized
    numpy scatter; the pure-Python path below is the fallback and oracle.
    """
    from dlti_tpu.utils.native import load_native_runtime

    # Zero-length docs pack to nothing; dropping them up front keeps the
    # native and Python paths identical (and the Python path from indexing
    # an empty row's segment list).
    seqs = [s for s in seqs if s]

    native = load_native_runtime()
    if native is not None and hasattr(native, "dlti_pack_assign") and seqs:
        return _pack_sequences_native(native, seqs, seq_len, pad_id, open_rows)

    rows: List[List[int]] = []
    row_segs: List[List[int]] = []
    open_idx: List[int] = []  # indices of still-open rows, oldest first
    for s in seqs:
        s = s[:seq_len]
        placed = False
        for oi, i in enumerate(open_idx):
            if len(rows[i]) + len(s) <= seq_len:
                seg_id = row_segs[i][-1] + 1
                rows[i].extend(s)
                row_segs[i].extend([seg_id] * len(s))
                if len(rows[i]) == seq_len:
                    open_idx.pop(oi)
                placed = True
                break
        if not placed:
            rows.append(list(s))
            row_segs.append([1] * len(s))
            open_idx.append(len(rows) - 1)
            if len(open_idx) > open_rows:
                open_idx.pop(0)
    n = len(rows)
    ids = np.full((n, seq_len), pad_id, dtype=np.int32)
    segs = np.zeros((n, seq_len), dtype=np.int32)
    for i, (row, seg) in enumerate(zip(rows, row_segs)):
        ids[i, : len(row)] = row
        segs[i, : len(seg)] = seg
    mask = (segs > 0).astype(np.int32)
    return ids, mask, segs


def _pack_sequences_native(native, seqs, seq_len: int, pad_id: int,
                           open_rows: int) -> tuple:
    """C++ assignment + vectorized token scatter (same outputs as the
    Python path, bit for bit)."""
    import ctypes

    n = len(seqs)
    lens = np.array([min(len(s), seq_len) for s in seqs], np.int64)
    out_row = np.empty(n, np.int32)
    out_col = np.empty(n, np.int32)
    out_seg = np.empty(n, np.int32)
    n_rows = native.dlti_pack_assign(
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        np.int32(n), np.int32(seq_len), np.int32(open_rows),
        out_row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_col.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_seg.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )

    total = int(lens.sum())
    # Measured at 50k docs: fromiter over one flat generator beats
    # per-doc np.asarray + np.concatenate ~2x (50k tiny array
    # constructions dominate the latter).
    tokens = np.fromiter(
        (t for s in seqs for t in (s if len(s) <= seq_len else s[:seq_len])),
        np.int32, count=total) if total else np.empty(0, np.int32)
    # Flat destination index of every token: row*seq_len + col + offset.
    starts = out_row.astype(np.int64) * seq_len + out_col
    flat_pos = np.repeat(starts, lens) + _ranges(lens)

    ids = np.full(n_rows * seq_len, pad_id, np.int32)
    segs = np.zeros(n_rows * seq_len, np.int32)
    ids[flat_pos] = tokens
    segs[flat_pos] = np.repeat(out_seg, lens)
    ids = ids.reshape(n_rows, seq_len)
    segs = segs.reshape(n_rows, seq_len)
    return ids, (segs > 0).astype(np.int32), segs


def _ranges(lens: np.ndarray) -> np.ndarray:
    """[0..l0), [0..l1), ... concatenated (vectorized arange per doc)."""
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, np.int64)
    idx = np.arange(total, dtype=np.int64)
    doc_start = np.repeat(np.cumsum(lens) - lens, lens)
    return idx - doc_start


def packed_loss_mask(segment_ids: np.ndarray) -> np.ndarray:
    """Loss mask for packed rows: target position p is valid iff it belongs
    to a document (seg > 0) and its predicting position p-1 is in the *same*
    document — the boundary token of doc k must not be trained to predict
    doc k+1's first token."""
    mask = np.zeros_like(segment_ids)
    mask[:, 1:] = (segment_ids[:, 1:] > 0) & (
        segment_ids[:, 1:] == segment_ids[:, :-1]
    )
    return mask.astype(np.int32)


def packed_positions(segment_ids: np.ndarray) -> np.ndarray:
    """Per-document positions (RoPE restarts at 0 for each packed doc).

    Vectorized: position = index - index_of_current_doc_start.
    """
    n, L = segment_ids.shape
    idx = np.broadcast_to(np.arange(L, dtype=np.int32), (n, L))
    is_start = np.ones((n, L), dtype=bool)
    is_start[:, 1:] = (segment_ids[:, 1:] != segment_ids[:, :-1]) | (
        segment_ids[:, 1:] == 0
    )
    start_idx = np.where(is_start, idx, 0)
    start_idx = np.maximum.accumulate(start_idx, axis=1)
    return (idx - start_idx).astype(np.int32)


class HostShardedSchedule:
    """World-size-invariant global schedule + seeded epoch shuffle +
    ``skip_steps`` resume, with per-host materialization.

    Shared by :class:`TokenBatchDataset` and
    :class:`~dlti_tpu.data.streaming.StreamingTokenDataset` so the row
    *schedule* (epoch permutation, per-step chunking, resume skip) cannot
    desynchronize between the in-memory and disk-backed paths. Note the
    shared piece is the schedule over rows, not row construction: in packed
    mode the two paths build rows from different document orders
    (TokenBatchDataset pre-shuffles the corpus before packing; the store
    writer packs in arrival order), so a packed checkpoint resumes
    byte-identically only against the same dataset kind it was trained
    with. Unpacked rows are identical either way.

    The schedule is a pure function of (corpus, seed, global batch shape)
    and NOT of the world size: one seeded *global* permutation, chunked
    ``samples_per_step`` rows per optimizer step; host p then materializes
    only its 1/process_count batch-column slice of each chunk. That
    invariance is what lets elastic training reshape the mesh to a
    surviving world and resume the exact batch schedule (with
    :func:`~dlti_tpu.training.elastic.rescale_batch_schedule` trading
    batch rows for grad-accum steps) — under the pre-r06 contiguous
    range-split, a shrunk world would have silently fed different rows
    per step.

    Subclasses call :meth:`_init_procs` early (fail fast, before any
    expensive row construction), then :meth:`_init_host_shard` with their
    row count, and implement
    ``_gather(row_indices) -> {field: (n, seq_len) array}``.
    """

    def _init_procs(self, shard_by_host: bool) -> None:
        import jax

        self._procs = jax.process_count() if shard_by_host else 1
        self._proc_id = jax.process_index() if shard_by_host else 0
        if self.micro_batch_size % self._procs != 0:
            raise ValueError(
                f"global micro_batch_size {self.micro_batch_size} must be "
                f"divisible by process_count {self._procs}"
            )

    def _init_host_shard(self, n_rows: int, shard_by_host: bool) -> None:
        if not hasattr(self, "_procs"):
            self._init_procs(shard_by_host)
        self._n_rows = n_rows

    @property
    def samples_per_step(self) -> int:
        """Global samples consumed per optimizer step."""
        return self.micro_batch_size * self.grad_accum_steps

    def steps_per_epoch(self) -> int:
        # Global chunking: every host agrees by construction (a ragged
        # split would deadlock collectives on the last step), at any
        # world size.
        if getattr(self, "drop_remainder", True):
            return self._n_rows // self.samples_per_step
        return -(-self._n_rows // self.samples_per_step)

    def _pad_partial(self, fields: dict, present: np.ndarray) -> dict:
        """Pad a partial final step to the static step shape: pad rows are
        all ``pad_id`` tokens with an all-zero loss mask (and zero
        segment ids / positions), so they contribute nothing to the loss
        or gradients while keeping every compiled shape identical. Pad
        positions are fixed in GLOBAL batch coordinates, so the padded
        step is world-size invariant too."""
        out = {}
        n = present.shape[0]
        for k, v in fields.items():
            fill = self.pad_id if k == "input_ids" else 0
            full = np.full((n,) + v.shape[1:], fill, v.dtype)
            full[present] = v
            out[k] = full
        return out

    def epoch(self, epoch_idx: int = 0, skip_steps: int = 0) -> Iterator[dict]:
        order = np.arange(self._n_rows)
        if self.shuffle_seed is not None:
            # One GLOBAL permutation, identical on every host.
            rng = np.random.default_rng(self.shuffle_seed + epoch_idx)
            rng.shuffle(order)
        S = self.samples_per_step
        bs = self.micro_batch_size
        bs_local = bs // self._procs
        shape = (self.grad_accum_steps, bs_local, self.seq_len)
        drop = getattr(self, "drop_remainder", True)
        # This host's positions within a step's global chunk: local batch
        # element (a, b) is global chunk row a*bs + proc_id*bs_local + b —
        # the slice make_global_batch reassembles along the batch dim.
        g_idx = (np.arange(self.grad_accum_steps)[:, None] * bs
                 + self._proc_id * bs_local
                 + np.arange(bs_local)[None, :]).ravel()
        for step_i, start in enumerate(range(0, self._n_rows, S)):
            chunk = order[start:start + S]
            if len(chunk) < S and drop:
                break  # legacy behavior: the ragged tail is dropped
            if step_i < skip_steps:
                continue
            present = g_idx < len(chunk)
            fields = self._gather(chunk[g_idx[present]])
            if not present.all():
                fields = self._pad_partial(fields, present)
            yield {k: v.reshape(shape) for k, v in fields.items()}


@dataclasses.dataclass
class TokenBatchDataset(HostShardedSchedule):
    """In-memory tokenized dataset yielding train-step-shaped batches.

    Yields dicts with ``input_ids`` / ``loss_mask`` (and, when packing,
    ``segment_ids`` / ``positions``) shaped (accum, micro_bs, seq_len) —
    exactly what :func:`dlti_tpu.training.make_train_step` consumes.

    ``micro_batch_size`` is the *global* (all-hosts, all-devices) microbatch;
    each host materializes 1/process_count of it when ``shard_by_host``.

    ``drop_remainder=False`` keeps the final partial step of each epoch by
    padding it to the full static step shape with all-pad rows (loss mask
    zero — no loss/grad contribution); the default drops it, matching the
    reference's drop_last semantics.
    """

    sequences: List[List[int]]
    seq_len: int
    pad_id: int
    micro_batch_size: int
    grad_accum_steps: int = 1
    shuffle_seed: Optional[int] = 0
    shard_by_host: bool = True
    drop_remainder: bool = True
    pack: bool = False

    def __post_init__(self) -> None:
        self._init_procs(self.shard_by_host)  # validate before packing
        if self.pack:
            # Pack once over the (seed-shuffled) corpus; epochs reshuffle rows.
            order = np.arange(len(self.sequences))
            if self.shuffle_seed is not None:
                np.random.default_rng(self.shuffle_seed).shuffle(order)
            ids, mask, segs = pack_sequences(
                [self.sequences[j] for j in order], self.seq_len, self.pad_id
            )
            self._packed = (ids, packed_loss_mask(segs), segs, packed_positions(segs))
            n_rows = ids.shape[0]
        else:
            self._packed = None
            n_rows = len(self.sequences)
        self._init_host_shard(n_rows, self.shard_by_host)

    def _row(self, j: int) -> tuple:
        if self._packed is not None:
            ids, mask, segs, pos = self._packed
            return ids[j], mask[j], segs[j], pos[j]
        s = self.sequences[j]
        ids, mask = pad_to_batch([s], self.seq_len, self.pad_id)
        return ids[0], mask[0], None, None

    def _gather(self, row_indices: np.ndarray) -> dict:
        rows = [self._row(j) for j in row_indices]
        fields = {
            "input_ids": np.stack([r[0] for r in rows]),
            "loss_mask": np.stack([r[1] for r in rows]),
        }
        if self._packed is not None:
            fields["segment_ids"] = np.stack([r[2] for r in rows])
            fields["positions"] = np.stack([r[3] for r in rows])
        return fields


def make_batches(
    texts: Sequence[str],
    tokenizer: Tokenizer,
    seq_len: int = 512,
    micro_batch_size: int = 1,
    grad_accum_steps: int = 1,
    shuffle_seed: Optional[int] = 0,
    shard_by_host: bool = True,
    pack: bool = False,
) -> TokenBatchDataset:
    seqs = tokenize_and_truncate(texts, tokenizer, seq_len)
    return TokenBatchDataset(
        sequences=seqs,
        seq_len=seq_len,
        pad_id=tokenizer.pad_id,
        micro_batch_size=micro_batch_size,
        grad_accum_steps=grad_accum_steps,
        shuffle_seed=shuffle_seed,
        shard_by_host=shard_by_host,
        pack=pack,
    )
