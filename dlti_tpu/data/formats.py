"""Chat formatting contracts.

:func:`format_conversation_for_llama2` reproduces the reference's exact
Llama-2 format contract (``scripts/prepare_dataset.py:12-25``):

    {"question": q, "answer": a} -> {"text": "<s>[INST] q [/INST] a</s>"}

The golden tests pin these strings byte-for-byte — a checkpoint fine-tuned
here sees the same token stream the reference model saw.
"""

from __future__ import annotations


def format_conversation_for_llama2(example: dict) -> dict:
    """Map one {question, answer} record to Llama-2 chat text."""
    question = example["question"].strip()
    answer = example["answer"].strip()
    return {"text": f"<s>[INST] {question} [/INST] {answer}</s>"}


def format_llama2_system(question: str, answer: str, system: str | None = None) -> str:
    """Extended form with an optional system prompt (Llama-2 spec)."""
    if system:
        return f"<s>[INST] <<SYS>>\n{system}\n<</SYS>>\n\n{question} [/INST] {answer}</s>"
    return f"<s>[INST] {question} [/INST] {answer}</s>"
