"""Disk-backed (memory-mapped) token store for corpus-scale training.

The reference memory-maps its dataset through the HF ``datasets`` Arrow
backend (``/root/reference/scripts/prepare_dataset.py:92`` ``save_to_disk``
+ ``load_from_disk`` in every trainer) — the corpus never has to fit in
host RAM. :class:`~dlti_tpu.data.pipeline.TokenBatchDataset` holds the
tokenized corpus in memory, which is fine at the reference's 136k docs but
not the honest equivalent at corpus scale. This module is that equivalent:

* :func:`write_token_store` streams documents (an *iterator* of token
  lists — nothing is accumulated) into flat binary row files, packing in
  bounded chunks along the way, so the writer's working set is one chunk
  regardless of corpus size.
* :class:`StreamingTokenDataset` ``np.memmap``-s the row files and yields
  batches through the same schedule machinery as :class:`TokenBatchDataset`
  (shared :class:`~dlti_tpu.data.pipeline.HostShardedSchedule`: per-host
  sharding, seeded epoch shuffle, ``skip_steps`` resume) while holding only
  O(rows) index memory (8 bytes per row for the epoch permutation), never
  the tokens. Unpacked batches are byte-identical to the in-memory
  dataset's; packed rows are built in arrival order (the in-memory packer
  pre-shuffles first), so packed checkpoints resume against the same
  dataset kind they were trained with.

Store layout (``<dir>/``):
    meta.json     {"n_rows", "seq_len", "pad_id", "packed", "version"}
    ids.bin       int32 (n_rows, seq_len) row tokens (padded)
    lengths.bin   int32 (n_rows,)         real-token count   [unpacked]
    segs.bin      int32 (n_rows, seq_len) segment ids        [packed]
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Iterable, List, Optional

import numpy as np

from dlti_tpu.data.pipeline import (
    HostShardedSchedule,
    pack_sequences,
    packed_loss_mask,
    packed_positions,
    pad_to_batch,
)

_VERSION = 1


def write_token_store(
    token_docs: Iterable[List[int]],
    directory: str,
    *,
    seq_len: int,
    pad_id: int,
    pack: bool = False,
    chunk_docs: int = 8192,
    tokenizer: Optional[str] = None,
) -> dict:
    """Stream ``token_docs`` into a memory-mappable row store.

    Documents are consumed strictly one chunk (``chunk_docs``) at a time;
    packed mode packs each chunk independently (the C++ packer when built),
    so packing efficiency is within one open-row window of the in-memory
    packer at a fraction of its footprint. Returns the meta dict.
    """
    os.makedirs(directory, exist_ok=True)
    ids_path = os.path.join(directory, "ids.bin")
    aux_path = os.path.join(directory, "segs.bin" if pack else "lengths.bin")
    n_rows = 0
    max_doc_len = 0
    with open(ids_path, "wb") as f_ids, open(aux_path, "wb") as f_aux:
        chunk: List[List[int]] = []

        def flush():
            nonlocal n_rows
            if not chunk:
                return
            if pack:
                ids, _, segs = pack_sequences(chunk, seq_len, pad_id)
                f_aux.write(np.ascontiguousarray(segs, np.int32).tobytes())
            else:
                # Same padding/truncation code path as the in-memory
                # dataset — the parity contract is structural, not copied.
                ids, mask = pad_to_batch(chunk, seq_len, pad_id)
                f_aux.write(mask.sum(1, dtype=np.int32).tobytes())
            f_ids.write(np.ascontiguousarray(ids, np.int32).tobytes())
            n_rows += ids.shape[0]
            chunk.clear()

        for doc in token_docs:
            # pack_sequences drops empty docs; unpacked mode must keep them
            # as all-pad rows for row-count parity with TokenBatchDataset.
            if pack and not doc:
                continue
            chunk.append(list(doc))
            max_doc_len = max(max_doc_len, min(len(doc), seq_len))
            if len(chunk) >= chunk_docs:
                flush()
        flush()

    meta = {"n_rows": n_rows, "seq_len": seq_len, "pad_id": pad_id,
            "packed": pack, "version": _VERSION,
            # Bound on any (truncated) document's tokens: lets training
            # run packed attention with an exact window of this size
            # (ModelConfig.packed_attention_window).
            "max_doc_len": max_doc_len}
    if tokenizer is not None:
        # Recorded so consumers can fail fast on a tokenizer mismatch
        # (ids from the wrong vocab gather-clamp silently under jit).
        meta["tokenizer"] = tokenizer
    with open(os.path.join(directory, "meta.json"), "w") as f:
        json.dump(meta, f)
    return meta


@dataclasses.dataclass
class StreamingTokenDataset(HostShardedSchedule):
    """Memory-mapped drop-in for :class:`TokenBatchDataset`.

    Same batch shapes ((accum, micro_bs, seq_len) dicts), same per-host
    sharding (equal shard per process), same seeded epoch shuffle and
    ``skip_steps`` resume contract — but rows are read from disk on
    demand; host RAM holds only the epoch permutation.

    ``expect_tokenizer``: when the store's meta records the tokenizer it
    was written with, a mismatch raises here instead of gather-clamping
    wrong-vocab ids silently under jit.
    """

    directory: str
    micro_batch_size: int
    grad_accum_steps: int = 1
    shuffle_seed: Optional[int] = 0
    shard_by_host: bool = True
    expect_tokenizer: Optional[str] = None
    # Same contract as TokenBatchDataset: False pads the final partial
    # step (all-pad rows, zero loss mask) instead of dropping it.
    drop_remainder: bool = True

    def __post_init__(self) -> None:
        with open(os.path.join(self.directory, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("version") != _VERSION:
            raise ValueError(f"unknown token-store version {meta.get('version')}")
        self.tokenizer_name = meta.get("tokenizer")
        self.max_doc_len = int(meta.get("max_doc_len", 0))
        if (self.expect_tokenizer is not None
                and self.tokenizer_name is not None
                and self.tokenizer_name != self.expect_tokenizer):
            raise ValueError(
                f"token store at {self.directory!r} was written with "
                f"tokenizer {self.tokenizer_name!r} but the run expects "
                f"{self.expect_tokenizer!r}; ids from the wrong vocab "
                f"would be clamped silently"
            )
        self.seq_len = int(meta["seq_len"])
        self.pad_id = int(meta["pad_id"])
        self.packed = bool(meta["packed"])
        n_rows = int(meta["n_rows"])
        if n_rows == 0:
            raise ValueError(
                f"token store at {self.directory!r} is empty (n_rows=0) — "
                "was write_token_store given an empty document iterator?"
            )

        self._ids = np.memmap(os.path.join(self.directory, "ids.bin"),
                              np.int32, "r", shape=(n_rows, self.seq_len))
        if self.packed:
            self._segs = np.memmap(os.path.join(self.directory, "segs.bin"),
                                   np.int32, "r", shape=(n_rows, self.seq_len))
            self._lens = None
        else:
            self._segs = None
            self._lens = np.memmap(os.path.join(self.directory, "lengths.bin"),
                                   np.int32, "r", shape=(n_rows,))

        self._init_host_shard(n_rows, self.shard_by_host)

    def _gather(self, row_indices: np.ndarray) -> dict:
        rows = np.sort(row_indices)  # monotone reads off the memmap
        unsort = np.argsort(np.argsort(row_indices))
        ids = np.asarray(self._ids[rows])[unsort]
        fields = {"input_ids": ids}
        if self.packed:
            segs = np.asarray(self._segs[rows])[unsort]
            fields["loss_mask"] = packed_loss_mask(segs)
            fields["segment_ids"] = segs
            fields["positions"] = packed_positions(segs)
        else:
            lens = np.asarray(self._lens[rows])[unsort]
            fields["loss_mask"] = (np.arange(self.seq_len)[None, :]
                                   < lens[:, None]).astype(np.int32)
        return fields
