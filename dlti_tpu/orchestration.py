"""Experiment-matrix orchestration — the reference L4 layer, in code.

The reference drives its experiment matrix from a notebook
(``training/train.ipynb``: baseline + ZeRO-{1,2,3} x {1,2,3,4} GPUs via
``%%bash`` + ``deepspeed --num_gpus=N``, cells 5-33) and *claims* SLURM
orchestration (``README.md:18``) without shipping any SLURM code
(SURVEY.md §0, §1 L4). This module replaces both:

* :func:`plan_matrix` — strategy x device-count grid -> ordered specs
  (baseline runs single-device only, like the reference's
  ``train_baseline.py``).
* :func:`build_command` — one spec -> the ``scripts/train.py`` argv (the
  ``deepspeed --num_gpus=N train_deepspeed_zeroS.py`` analog).
* :func:`run_matrix` — executes each cell in a fresh subprocess (the
  notebook's process-per-cell semantics: a crashed run is recorded and the
  matrix continues — the reference's own 2-GPU NCCL crash is preserved
  in-notebook, ``train.ipynb:794-838``), then runs the comparison analysis
  over the shared metrics CSV.
* :func:`emit_slurm` — writes one ``sbatch`` script per experiment plus a
  ``submit_all.sh``, closing the README's SLURM claim with real artifacts.

Each subprocess gets its own JAX backend, so a CPU-simulated mesh
(``--simulate-devices N``) or the real TPU work identically.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from dlti_tpu.utils.experiment import create_experiment_name

STRATEGY_STAGE = {"baseline": 0, "zero1": 1, "zero2": 2, "zero3": 3}


@dataclasses.dataclass(frozen=True)
class ExperimentSpec:
    """One cell of the matrix: a strategy at a device count."""

    strategy: str          # baseline | zero1 | zero2 | zero3
    num_devices: int
    tensor: int = 1
    sequence: int = 1

    @property
    def name(self) -> str:
        return create_experiment_name(self.num_devices,
                                      STRATEGY_STAGE[self.strategy])


def plan_matrix(strategies: Sequence[str],
                device_counts: Sequence[int],
                tensor: int = 1,
                sequence: int = 1) -> List[ExperimentSpec]:
    """Strategy x device grid, reference semantics.

    The baseline strategy is inherently single-device
    (``train_baseline.py:104-108`` warns and uses one GPU), so it appears
    once regardless of ``device_counts``; ZeRO strategies fan out over all
    counts (the notebook's ``--num_gpus={1,2,3,4}`` loop).
    """
    specs: List[ExperimentSpec] = []
    for strat in strategies:
        if strat not in STRATEGY_STAGE:
            raise ValueError(
                f"unknown strategy {strat!r}; choose from {sorted(STRATEGY_STAGE)}")
        if strat == "baseline":
            specs.append(ExperimentSpec("baseline", 1))
            continue
        for n in device_counts:
            specs.append(ExperimentSpec(strat, n, tensor=tensor,
                                        sequence=sequence))
    return specs


def build_command(spec: ExperimentSpec,
                  train_args: Dict[str, object],
                  python: str = sys.executable,
                  train_script: Optional[str] = None) -> List[str]:
    """Spec -> argv for one training run.

    ``train_args`` are passed through as ``--key value`` flags (underscores
    become dashes); booleans become bare flags when true.
    """
    if train_script is None:
        train_script = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "train.py")
    cmd = [python, train_script,
           "--preset", spec.strategy,
           "--num-devices", str(spec.num_devices)]
    if spec.tensor > 1:
        cmd += ["--tensor", str(spec.tensor)]
    if spec.sequence > 1:
        cmd += ["--sequence", str(spec.sequence)]
    for key, val in train_args.items():
        flag = "--" + key.replace("_", "-")
        if isinstance(val, bool):
            if val:
                cmd.append(flag)
        elif val is not None:
            cmd += [flag, str(val)]
    return cmd


def _subprocess_env(spec: ExperimentSpec,
                    simulate_devices: int = 0) -> Dict[str, str]:
    env = dict(os.environ)
    if simulate_devices:
        from dlti_tpu.utils.platform import host_platform_env

        n = max(simulate_devices,
                spec.num_devices * spec.tensor * spec.sequence)
        host_platform_env(n, env)
    return env


def run_matrix(specs: Sequence[ExperimentSpec],
               train_args: Dict[str, object],
               metrics_csv: str = "results/training_metrics.csv",
               simulate_devices: int = 0,
               output_root: str = "checkpoints",
               analyze: bool = True,
               plot_path: Optional[str] = "results/plots/training_comparison.png",
               dry_run: bool = False,
               log_dir: Optional[str] = "logs",
               train_script: Optional[str] = None) -> List[dict]:
    """Run every cell; record outcomes; never abort the matrix on one failure.

    Returns one record per spec: ``{name, returncode, seconds, command}``.
    Per-run stdout/stderr go to ``{log_dir}/{name}.out`` / ``.err`` — the
    layout the reference's ``.gitignore:36-37`` implies its SLURM jobs used.
    """
    results: List[dict] = []
    for spec in specs:
        args = dict(train_args)
        args.setdefault("metrics_csv", metrics_csv)
        args["output_dir"] = os.path.join(output_root, spec.name)
        cmd = build_command(spec, args, train_script=train_script)
        if dry_run:
            print(shlex.join(cmd))
            results.append({"name": spec.name, "returncode": None,
                            "seconds": 0.0, "command": cmd})
            continue
        env = _subprocess_env(spec, simulate_devices)
        print(f"=== {spec.name}: {shlex.join(cmd)}", flush=True)
        t0 = time.perf_counter()
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            with open(os.path.join(log_dir, f"{spec.name}.out"), "wb") as out, \
                 open(os.path.join(log_dir, f"{spec.name}.err"), "wb") as err:
                proc = subprocess.run(cmd, env=env, stdout=out, stderr=err)
        else:
            proc = subprocess.run(cmd, env=env)
        dt = time.perf_counter() - t0
        status = "ok" if proc.returncode == 0 else f"FAILED rc={proc.returncode}"
        print(f"=== {spec.name}: {status} in {dt:.1f}s", flush=True)
        results.append({"name": spec.name, "returncode": proc.returncode,
                        "seconds": dt, "command": cmd})

    if analyze and not dry_run and os.path.isfile(metrics_csv):
        from dlti_tpu.analysis import compare

        compare(metrics_csv, plot_path)
    return results


SBATCH_TEMPLATE = """#!/bin/bash
#SBATCH --job-name={name}
#SBATCH --nodes={nodes}
#SBATCH --ntasks-per-node=1
#SBATCH --output=logs/{name}.out
#SBATCH --error=logs/{name}.err
{extra_directives}
# One task per host; every host runs the same program and discovers its
# process id / coordinator from the launcher env (scripts/launch.py —
# jax.distributed.initialize). This replaces the reference's claimed-but-
# absent SLURM layer (README.md:18) and its torchrun/deepspeed launchers.
srun {python} {launch} --coordinator-from-slurm -- {train_cmd}
"""


def emit_slurm(specs: Sequence[ExperimentSpec],
               train_args: Dict[str, object],
               out_dir: str = "slurm",
               hosts_per_pod: int = 1,
               partition: Optional[str] = None,
               time_limit: Optional[str] = None) -> List[str]:
    """Write one sbatch per spec + submit_all.sh; return the script paths."""
    os.makedirs(out_dir, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    launch = os.path.join(repo, "scripts", "launch.py")
    paths: List[str] = []
    extra = ""
    if partition:
        extra += f"#SBATCH --partition={partition}\n"
    if time_limit:
        extra += f"#SBATCH --time={time_limit}\n"
    for spec in specs:
        args = dict(train_args)
        args["output_dir"] = os.path.join("checkpoints", spec.name)
        cmd = build_command(spec, args, python="python")
        # Keep the interpreter in the exec'd command: launch.py execvpe's
        # argv[0], and train.py itself carries no exec bit.
        body = SBATCH_TEMPLATE.format(
            name=spec.name, nodes=hosts_per_pod, extra_directives=extra,
            python="python", launch=launch,
            train_cmd=shlex.join(cmd))
        path = os.path.join(out_dir, f"{spec.name}.sbatch")
        with open(path, "w") as f:
            f.write(body)
        paths.append(path)
    submit = os.path.join(out_dir, "submit_all.sh")
    with open(submit, "w") as f:
        f.write("#!/bin/bash\nset -e\n")
        for p in paths:
            f.write(f"sbatch {os.path.basename(p)}\n")
    os.chmod(submit, 0o755)
    return paths
