"""LoRA as a first-class Flax module.

The reference grafts LoRA via PEFT's ``get_peft_model`` with r=16, alpha=32,
dropout=0.05 on q/k/v/o projections, bias "none"
(``training/train_baseline.py:131-140``, ``train_deepspeed_zero3.py:176-185``).
Here LoRA is a native module: :class:`LoRADense` computes

    y = x @ W_base  +  scaling * dropout(x) @ A @ B

with ``A ~ N(0, 1/r)``-style init (kaiming-uniform like PEFT), ``B = 0`` so
training starts at the base model's function, and ``scaling = alpha / r``.

Base kernels live in ``param_dtype`` (bf16, frozen); LoRA factors are fp32
master weights (they are the only trainable/optimized params — the "0.2484%
trainable" property recorded at ``training/train.ipynb:307``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.core import FrozenDict


class LoRADense(nn.Module):
    """Dense layer with an optional LoRA adapter branch."""

    features: int
    use_bias: bool = False
    lora_r: int = 0  # 0 disables the adapter branch
    lora_alpha: int = 32
    lora_dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.bfloat16
    lora_param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True,
                 adapter_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel", self.kernel_init, (in_features, self.features), self.param_dtype
        )
        if isinstance(kernel, dict):
            # Weight-only int8 serving: the stored leaf is {"q", "scale"};
            # dequantize at the consumer so only the executing layer holds
            # a compute-dtype copy (dlti_tpu.models.quantization).
            from dlti_tpu.models.quantization import maybe_dequantize

            kernel = maybe_dequantize(kernel, self.dtype, anchor=x)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype),
                    preferred_element_type=self.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)

        if self.lora_r > 0:
            # PEFT-style init: A kaiming-uniform, B zeros.
            lora_a = self.param(
                "lora_a",
                nn.initializers.variance_scaling(1.0 / 3.0, "fan_in", "uniform"),
                (in_features, self.lora_r),
                self.lora_param_dtype,
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.lora_r, self.features),
                self.lora_param_dtype,
            )
            h = x
            if self.lora_dropout > 0.0 and not deterministic:
                h = nn.Dropout(rate=self.lora_dropout)(h, deterministic=False)
            # Low-rank branch in compute dtype; r is tiny so this is cheap.
            scaling = self.lora_alpha / self.lora_r
            delta = jnp.dot(
                jnp.dot(h.astype(self.dtype), lora_a.astype(self.dtype),
                        preferred_element_type=self.dtype),
                lora_b.astype(self.dtype),
                preferred_element_type=self.dtype,
            )
            y = y + scaling * delta

        if adapter_ids is not None and self.has_variable("adapters", "a"):
            # Batched multi-LoRA serving (dlti_tpu.serving.adapters): the
            # stacked per-slot A/B pool rides in as an "adapters" variable
            # collection; each batch row gathers ITS adapter's factors by
            # id, so one compiled step serves heterogeneous adapters
            # (S-LoRA/Punica BGMV). Row 0 is all-zero — base requests add
            # exactly +0.0 and stay byte-identical to an adapter-free
            # engine. The branch is Python-static: training and
            # adapter-off serving never trace it.
            pa = self.get_variable("adapters", "a")  # (P, in, r)
            pb = self.get_variable("adapters", "b")  # (P, r, out)
            ps = self.get_variable("adapters", "s")  # (P,)
            a = jnp.take(pa, adapter_ids, axis=0).astype(self.dtype)
            b = jnp.take(pb, adapter_ids, axis=0).astype(self.dtype)
            s = jnp.take(ps, adapter_ids, axis=0).astype(self.dtype)
            h = jnp.einsum("bsi,bir->bsr", x.astype(self.dtype), a,
                           preferred_element_type=self.dtype)
            delta = jnp.einsum("bsr,bro->bso", h, b,
                               preferred_element_type=self.dtype)
            y = y + s[:, None, None] * delta
        return y


# ----------------------------------------------------------------------
# Param-tree utilities
# ----------------------------------------------------------------------

def _is_lora_path(path: tuple) -> bool:
    return any(str(p) in ("lora_a", "lora_b") for p in path)


def lora_param_mask(params) -> Any:
    """Pytree of bools: True for trainable (LoRA) leaves, False for frozen.

    Drives ``optax.masked`` so optimizer state exists only for the ~0.25%
    trainable params — the property that makes ZeRO-1/2 optimizer-state
    sharding compose with LoRA (SURVEY.md §7 hard part #1).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    if not any(_is_lora_path([getattr(k, "key", k) for k in path]) for path, _ in flat):
        # Full fine-tune (no LoRA grafted): everything trainable.
        return jax.tree_util.tree_map(lambda _: True, params)
    return jax.tree_util.tree_map_with_path(
        lambda path, _: _is_lora_path([getattr(k, "key", k) for k in path]), params
    )


def merge_lora_params(params, scaling: Optional[float] = None, alpha: int = 32):
    """Fold LoRA factors into base kernels: W' = W + scaling * A @ B.

    The TPU-native equivalent of PEFT's ``merge_and_unload`` — produces the
    consolidated checkpoint the serving leg loads (the capability the
    reference gets from ``stage3_gather_16bit_weights_on_model_save``,
    ``configs/ds_config_zero3.json:36``, plus PEFT merge).
    Returns a params tree with ``lora_a``/``lora_b`` removed.
    """
    if isinstance(params, FrozenDict):
        params = params.unfreeze()

    from dlti_tpu.models.quantization import is_quant_node, maybe_dequantize

    def _merge(tree):
        if is_quant_node(tree):
            # int8-frozen-base training: expand back to bf16 so the merged
            # export is a standard compute-dtype tree (serving re-quantizes
            # on load; int8->bf16->int8 round-trips to the same grid).
            return maybe_dequantize(tree, jnp.bfloat16)
        if not isinstance(tree, dict):
            return tree
        out = {}
        has_lora = "lora_a" in tree and "lora_b" in tree and "kernel" in tree
        for k, v in tree.items():
            if has_lora and k in ("lora_a", "lora_b"):
                continue
            if has_lora and k == "kernel":
                if is_quant_node(v):
                    v = maybe_dequantize(v, jnp.bfloat16)
                a = tree["lora_a"].astype(jnp.float32)
                b = tree["lora_b"].astype(jnp.float32)
                r = a.shape[-1]
                s = scaling if scaling is not None else alpha / r
                out[k] = (v.astype(jnp.float32) + s * (a @ b)).astype(v.dtype)
            else:
                out[k] = _merge(v)
        return out

    return _merge(params)


def count_params(params) -> tuple:
    """(trainable, total) param counts, reference-style report
    (``train.ipynb:307``: 16,777,216 / 6,755,192,832 = 0.2484%)."""
    mask = lora_param_mask(params)
    sizes = jax.tree_util.tree_map(lambda x: int(x.size), params)
    total = sum(jax.tree_util.tree_leaves(sizes))
    trainable = sum(
        s for s, m in zip(jax.tree_util.tree_leaves(sizes), jax.tree_util.tree_leaves(mask)) if m
    )
    return trainable, total
