"""Model zoo: Llama-family transformer in Flax + LoRA grafting."""

from dlti_tpu.models.llama import LlamaForCausalLM, LlamaModel  # noqa: F401
from dlti_tpu.models.lora import (  # noqa: F401
    LoRADense,
    lora_param_mask,
    merge_lora_params,
    count_params,
)
