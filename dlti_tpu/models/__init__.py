"""Model zoo: Llama-family transformer in Flax + LoRA grafting."""

from dlti_tpu.models.llama import LlamaForCausalLM, LlamaModel  # noqa: F401
from dlti_tpu.models.lora import (  # noqa: F401
    LoRADense,
    lora_param_mask,
    merge_lora_params,
    count_params,
)
from dlti_tpu.models.hf_interop import (  # noqa: F401
    config_from_hf,
    config_to_hf,
    graft_base_params,
    load_hf_checkpoint,
    load_peft_adapter,
    params_from_hf_state_dict,
    hf_state_dict_from_params,
    save_hf_checkpoint,
    save_peft_adapter,
)
