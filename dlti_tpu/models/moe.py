"""Mixture-of-Experts MLP with expert parallelism.

Mixtral-style top-k routed SwiGLU experts, expressed the TPU way: instead
of per-token Python dispatch (host control flow XLA can't compile), tokens
are packed into fixed-capacity per-expert buffers with one-hot dispatch /
combine einsums (the GShard/Switch formulation). All shapes are static;
the only data-dependent effect is token dropping when an expert
overflows its capacity — controlled by ``moe_capacity_factor``.

Expert parallelism rides a dedicated ``expert`` mesh axis: the stacked
expert weights ``(E, ...)`` shard on dim 0, the dispatched activations
``(E, C, h)`` shard on their expert dim, and GSPMD inserts the
all-to-all between the token-sharded and expert-sharded layouts.

The router's load-balance auxiliary loss (Switch §2.2 / Mixtral) is
recorded via ``self.sow("intermediates", "router_aux_loss", ...)``; the
train step collects it when ``ModelConfig.num_experts > 0``.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dlti_tpu.config import ModelConfig
from dlti_tpu.models.llama import _dtype


class MoEMLP(nn.Module):
    """Top-k routed expert SwiGLU MLP (drop-in for LlamaMLP)."""

    cfg: ModelConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True,
                 token_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """``token_mask`` (b, s): 1 for real tokens, 0 for padding. Padding
        tokens are excluded from routing — they'd otherwise consume expert
        capacity (displacing real tokens of later sequences in the batch)
        and bias the load-balance statistics."""
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        pdtype = _dtype(cfg.param_dtype)
        b, s, h = x.shape
        E = cfg.num_experts
        k = cfg.num_experts_per_tok
        m = cfg.intermediate_size
        T = b * s
        valid = (jnp.ones((T,), jnp.float32) if token_mask is None
                 else token_mask.reshape(T).astype(jnp.float32))

        # Router in fp32 for stable softmax/top-k.
        router_kernel = self.param(
            "router", nn.initializers.lecun_normal(), (h, E), jnp.float32)
        xt = x.reshape(T, h)
        logits = jnp.dot(xt.astype(jnp.float32), router_kernel)          # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_w, topk_idx = jax.lax.top_k(probs, k)                        # (T, k)
        topk_w = topk_w / jnp.maximum(
            jnp.sum(topk_w, axis=-1, keepdims=True), 1e-9)  # Mixtral renorm
        topk_w = topk_w * valid[:, None]

        # Fixed expert capacity (static shape): each expert accepts at most
        # C of the T*k routed slots; overflow tokens are dropped for that
        # expert (their combine weight is zeroed).
        C = max(int(cfg.moe_capacity_factor * T * k / E), 1)

        # Position of each (token, slot) within its expert's buffer,
        # counted over slots-major order so slot 0 (highest router weight)
        # wins buffer space first.
        flat_e = topk_idx.T.reshape(-1)                                   # (k*T,)
        flat_valid = jnp.tile(valid, k).astype(jnp.int32)
        # Padding tokens take no buffer rank and never dispatch.
        onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32) * flat_valid[:, None]
        pos = jnp.cumsum(onehot, axis=0) * onehot - onehot                # rank in expert
        pos = jnp.sum(pos, axis=-1)                                       # (k*T,)
        keep = (pos < C) & (flat_valid > 0)

        slot_w = topk_w.T.reshape(-1) * keep                              # (k*T,)
        # dispatch[t, e, c]: token t occupies slot c of expert e.
        disp = (jax.nn.one_hot(flat_e, E, dtype=jnp.float32)[:, :, None]
                * jax.nn.one_hot(jnp.where(keep, pos, C), C + 1,
                                 dtype=jnp.float32)[:, None, :C])          # (kT,E,C)
        combine = disp * slot_w[:, None, None]
        # Fold the k slots back onto tokens.
        disp = disp.reshape(k, T, E, C).sum(0)
        combine = combine.reshape(k, T, E, C).sum(0)

        expert_in = jnp.einsum("tec,th->ech", disp.astype(dtype),
                               xt.astype(dtype))                          # (E,C,h)
        expert_in = self._expert_constraint(expert_in)

        w1 = self.param("w1", nn.initializers.lecun_normal(), (E, h, m), pdtype)
        w3 = self.param("w3", nn.initializers.lecun_normal(), (E, h, m), pdtype)
        w2 = self.param("w2", nn.initializers.lecun_normal(), (E, m, h), pdtype)
        if isinstance(w1, dict):  # int8 serving (per-expert-channel scales)
            from dlti_tpu.models.quantization import maybe_dequantize

            w1, w2, w3 = (maybe_dequantize(w, dtype, anchor=expert_in)
                          for w in (w1, w2, w3))

        hidden = (nn.silu(jnp.einsum("ech,ehm->ecm", expert_in, w1.astype(dtype)))
                  * jnp.einsum("ech,ehm->ecm", expert_in, w3.astype(dtype)))
        out_e = jnp.einsum("ecm,emh->ech", hidden, w2.astype(dtype))
        out_e = self._expert_constraint(out_e)

        y = jnp.einsum("tec,ech->th", combine.astype(dtype), out_e)       # (T,h)

        # Load-balance aux loss (Switch Transformers eq. 4, Mixtral's k
        # normalization): E * sum_e f_e * P_e with f_e = fraction of routed
        # *assignments* landing on expert e, P_e = mean router prob.
        # Equals 1 at perfect balance, its minimum.
        n_valid = jnp.maximum(jnp.sum(valid), 1.0)
        frac = (jnp.sum(
            jax.nn.one_hot(topk_idx, E, dtype=jnp.float32).sum(1)
            * valid[:, None], axis=0) / (n_valid * k))
        mean_prob = jnp.sum(probs * valid[:, None], axis=0) / n_valid     # (E,)
        aux = E * jnp.sum(frac * mean_prob)
        self.sow("intermediates", "router_aux_loss", aux)

        return y.reshape(b, s, h)

    def _expert_constraint(self, v: jnp.ndarray) -> jnp.ndarray:
        """Pin the expert dim to the 'expert' mesh axis (GSPMD then places
        the all-to-all between token- and expert-sharded layouts).

        Inside a shard_map with manual axes (the PP x EP case: 'pipe' is
        manual, 'expert' auto), a constraint built on the CONCRETE mesh
        is rejected ("axes in vma should be Manual") — the current
        *abstract* mesh carries the right Manual/Auto axis types, so use
        it whenever it is active."""
        if (self.mesh is not None and "expert" in self.mesh.shape
                and self.mesh.shape["expert"] > 1):
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = self.mesh
            try:
                am = jax.sharding.get_abstract_mesh()
                if am is not None and not am.empty and "expert" in am.shape:
                    mesh = am
            except Exception:
                pass
            return jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P("expert", None, None)))
        return v


def collect_aux_loss(intermediates: dict) -> jnp.ndarray:
    """Sum every sown ``router_aux_loss`` scalar (one per MoE layer)."""
    total = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(intermediates):
        total = total + jnp.sum(leaf)
    return total
