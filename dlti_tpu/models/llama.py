"""Llama-family transformer, TPU-first, in Flax linen.

The reference uses HF ``LlamaForCausalLM`` loaded from the hub
(``training/train_baseline.py:122-126``); this is a from-scratch
implementation of the same architecture family (RMSNorm, RoPE, GQA-capable
attention, SwiGLU MLP, untied LM head) designed for XLA:

* bf16 matmuls with fp32 reductions (MXU-friendly, no loss scaling —
  replaces the reference's fp16 dynamic loss scaler,
  ``configs/ds_config_zero1.json:25-32``)
* ``jax.checkpoint`` per block when ``remat=True`` (replaces CUDA gradient
  checkpointing, ``training/train_baseline.py:181``)
* LoRA grafted natively via :class:`~dlti_tpu.models.lora.LoRADense` on the
  projections named by ``LoRAConfig.target_modules`` (reference PEFT graft,
  ``training/train_baseline.py:131-140``)
* a functional KV cache threaded through ``__call__`` for the serving engine
  (the reference's claimed-but-absent vLLM leg, ``README.md:10``).

All shapes are static; decode uses fixed-capacity caches + dynamic-slice
updates so the whole engine stays inside one compiled program.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from dlti_tpu.config import LoRAConfig, ModelConfig
from dlti_tpu.models.lora import LoRADense
from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.ops.rope import (
    apply_rope, assert_rope_table_covers, rope_frequencies,
)


from dlti_tpu.utils.dtypes import resolve_dtype as _dtype  # shared table


class RMSNorm(nn.Module):
    """Llama RMSNorm; stats in fp32 regardless of compute dtype.

    ``offset`` selects Gemma's ``(1 + weight)`` parameterization (weights
    stored zero-centered, HF state dicts carry ``w`` with the +1 applied at
    run time); init follows suit (zeros instead of ones).
    """

    eps: float = 1e-5
    param_dtype: Any = jnp.float32
    offset: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        orig_dtype = x.dtype
        init = nn.initializers.zeros if self.offset else nn.initializers.ones
        scale = self.param("scale", init, (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        normed = x32 * jax.lax.rsqrt(var + self.eps)
        s = scale.astype(jnp.float32)
        if self.offset:
            s = 1.0 + s
        return (normed * s).astype(orig_dtype)


def _lora_kwargs(cfg: ModelConfig, lora: Optional[LoRAConfig], name: str) -> dict:
    """LoRA hyperparams for projection ``name``, or r=0 when untargeted."""
    if lora is not None and lora.enabled and name in lora.target_modules:
        return dict(lora_r=lora.r, lora_alpha=lora.alpha, lora_dropout=lora.dropout)
    return dict(lora_r=0)


class LlamaAttention(nn.Module):
    cfg: ModelConfig
    lora: Optional[LoRAConfig] = None
    # Device mesh, threaded in by the parallel layer. When its 'sequence'
    # axis is >1, training attention runs the ring schedule
    # (dlti_tpu.parallel.ring_attention) — the reference has no SP at all
    # (SURVEY.md §5.7); here it is first-class.
    mesh: Optional[Any] = None

    def _effective_window(self, segment_ids) -> Optional[int]:
        """Sliding window combined with the packed doc-length bound.

        For packed batches a window of ``packed_attention_window`` is
        *exact*: intra-document attention can never reach further back
        than the document's own length, and the segment mask handles the
        rest — so the flash kernel's banded sweep (or the ring's chunk
        skip) applies without changing any logit.
        """
        cfg = self.cfg
        window = cfg.sliding_window
        if segment_ids is not None and cfg.packed_attention_window:
            window = (min(window, cfg.packed_attention_window)
                      if window else cfg.packed_attention_window)
        return window

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        cos: jnp.ndarray,
        sin: jnp.ndarray,
        positions: jnp.ndarray,
        segment_ids: Optional[jnp.ndarray] = None,
        cache: Optional[dict] = None,
        deterministic: bool = True,
        adapter_ids: Optional[jnp.ndarray] = None,
    ):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        pdtype = _dtype(cfg.param_dtype)
        b, s, _ = x.shape
        hd = cfg.resolved_head_dim

        def proj(name: str, features: int, use_bias: bool = False):
            return LoRADense(
                features=features, use_bias=use_bias, dtype=dtype, param_dtype=pdtype,
                name=name, **_lora_kwargs(cfg, self.lora, name),
            )

        # Qwen2-style bias on q/k/v only, never o (config.attention_bias).
        qkv_bias = cfg.attention_bias
        q = proj("q_proj", cfg.num_heads * hd, qkv_bias)(x, deterministic,
                                                         adapter_ids)
        k = proj("k_proj", cfg.num_kv_heads * hd, qkv_bias)(x, deterministic,
                                                            adapter_ids)
        v = proj("v_proj", cfg.num_kv_heads * hd, qkv_bias)(x, deterministic,
                                                            adapter_ids)

        q = q.reshape(b, s, cfg.num_heads, hd)
        k = k.reshape(b, s, cfg.num_kv_heads, hd)
        v = v.reshape(b, s, cfg.num_kv_heads, hd)

        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        new_cache = None
        if cache is not None and "block_tables" in cache:
            # Paged cache (serving engine): scatter K/V into the shared block
            # pool, then attend over this sequence's gathered logical window.
            # Stale/unallocated slots are at logical positions > the query
            # position, so the explicit-position causal mask hides them.
            from dlti_tpu.ops.kv_cache import paged_gather, paged_update, slot_mapping

            nb, blk_size = cache["k"].shape[0], cache["k"].shape[1]
            slots = slot_mapping(cache["block_tables"], positions, blk_size, nb)
            new_cache = paged_update(cache, k, v, slots)
            impl = getattr(cfg, "paged_attention_impl", "auto")
            # Under a TP mesh the pool is kv_head-sharded; pallas_call has
            # no SPMD partitioning rules (GSPMD would all-gather the whole
            # pool), so TP serving uses the sharded-einsum gather path.
            tp_sharded = (self.mesh is not None
                          and self.mesh.shape.get("tensor", 1) > 1)
            use_kernel = s == 1 and not tp_sharded and (
                impl == "kernel"
                or (impl == "auto" and jax.default_backend() == "tpu")
            )
            if use_kernel:
                # Pallas kernel: reads K/V blocks in place via the block
                # table (no O(batch*max_len) gather); decode steps only.
                from dlti_tpu.ops.pallas.paged_attention import (
                    paged_decode_attention,
                )

                out = paged_decode_attention(
                    q, new_cache["k"], new_cache["v"],
                    cache["block_tables"], positions[:, 0] + 1,
                    k_scale=new_cache.get("k_scale"),
                    v_scale=new_cache.get("v_scale"),
                    window=cfg.sliding_window,
                    # == "cpu", not != "tpu": interpret must never flip
                    # on for a real accelerator whose backend carries a
                    # plugin name (see ops/attention.py's flash gate).
                    interpret=jax.default_backend() == "cpu",
                ).astype(q.dtype)
            else:
                ck, cv = paged_gather(new_cache, cache["block_tables"])
                out = reference_attention(
                    q, ck.astype(q.dtype), cv.astype(q.dtype),
                    causal=True, q_positions=positions,
                    window=cfg.sliding_window,
                )
        elif cache is not None:
            # Fixed-capacity cache: (b, max_len, kv_heads, hd). `index` is the
            # write offset (same for the whole batch in the engine's design —
            # per-sequence offsets live in the paged serving cache instead).
            ck = jax.lax.dynamic_update_slice(cache["k"], k.astype(cache["k"].dtype),
                                              (0, cache["index"], 0, 0))
            cv = jax.lax.dynamic_update_slice(cache["v"], v.astype(cache["v"].dtype),
                                              (0, cache["index"], 0, 0))
            new_cache = {"k": ck, "v": cv, "index": cache["index"] + s}
            # Cache slot index == token position (contiguous writes), so the
            # position-explicit causal mask also masks unwritten slots.
            out = reference_attention(
                q, ck.astype(q.dtype), cv.astype(q.dtype),
                causal=True, q_positions=positions,
                window=cfg.sliding_window,
            )
        elif (self.mesh is not None and "sequence" in self.mesh.shape
              and self.mesh.shape["sequence"] > 1):
            # Sequence-parallel training: exact ring attention over the
            # 'sequence' mesh axis. RoPE positions are passed through so
            # the ring's causal mask always agrees with the embedded
            # positions; packed batches travel their segment ids around
            # the ring and segment-disjoint chunks skip their matmuls.
            # The packed doc-length bound is NOT passed here: the ring
            # masks by *per-document* positions (always < the bound), so
            # as a window it could never fire — segment disjointness is
            # the mechanism that prunes packed chunks on this path.
            from dlti_tpu.parallel.ring_attention import ring_attention

            out = ring_attention(q, k, v, self.mesh, positions=positions,
                                 segment_ids=segment_ids, causal=True,
                                 window=cfg.sliding_window)
        else:
            window = self._effective_window(segment_ids)
            if cfg.attention_impl in ("flash", "auto"):
                from dlti_tpu.ops.attention import multi_head_attention

                out = multi_head_attention(
                    q, k, v, causal=True, segment_ids=segment_ids,
                    impl=cfg.attention_impl,
                    block_q=cfg.flash_block_q, block_kv=cfg.flash_block_kv,
                    window=window,
                )
            else:
                out = reference_attention(q, k, v, causal=True,
                                          segment_ids=segment_ids,
                                          window=window)

        # Remat seam: with remat_policy="save_attn_out", the backward reuses
        # this (b, s, h*d) tensor instead of re-running the whole attention
        # (flash fwd is the most expensive thing under recompute) while
        # everything else still remats — a memory/FLOPs middle ground
        # between nothing_saveable and dots_*.
        out = checkpoint_name(out.reshape(b, s, cfg.num_heads * hd),
                              "attn_out")
        out = proj("o_proj", cfg.hidden_size)(out, deterministic, adapter_ids)
        return out, new_cache


_MLP_ACTIVATIONS = {
    "silu": nn.silu,
    "gelu_tanh": nn.gelu,  # flax default: tanh approximation
    "gelu_exact": lambda x: nn.gelu(x, approximate=False),
}


class LlamaMLP(nn.Module):
    """Gated MLP: down(act(gate(x)) * up(x)); act is SwiGLU's silu for the
    Llama/Mistral/Qwen2 families, gelu_tanh for Gemma-style configs."""

    cfg: ModelConfig
    lora: Optional[LoRAConfig] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True,
                 adapter_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        pdtype = _dtype(cfg.param_dtype)
        act = _MLP_ACTIVATIONS[cfg.mlp_activation]

        def proj(name: str, features: int):
            return LoRADense(
                features=features, use_bias=False, dtype=dtype, param_dtype=pdtype,
                name=name, **_lora_kwargs(cfg, self.lora, name),
            )

        gate = proj("gate_proj", cfg.intermediate_size)(x, deterministic,
                                                        adapter_ids)
        up = proj("up_proj", cfg.intermediate_size)(x, deterministic,
                                                    adapter_ids)
        return proj("down_proj", cfg.hidden_size)(act(gate) * up,
                                                  deterministic, adapter_ids)


class LlamaBlock(nn.Module):
    cfg: ModelConfig
    lora: Optional[LoRAConfig] = None
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, x, cos, sin, positions, segment_ids=None, cache=None,
                 deterministic: bool = True, token_mask=None,
                 adapter_ids=None):
        cfg = self.cfg
        attn_out, new_cache = LlamaAttention(cfg, self.lora, self.mesh, name="attn")(
            RMSNorm(cfg.rms_norm_eps, offset=cfg.rmsnorm_offset, name="input_norm")(x),
            cos, sin, positions, segment_ids, cache, deterministic,
            adapter_ids,
        )
        x = x + attn_out
        normed = RMSNorm(cfg.rms_norm_eps, offset=cfg.rmsnorm_offset, name="post_attn_norm")(x)
        if cfg.num_experts > 0:
            from dlti_tpu.models.moe import MoEMLP

            if self.lora is not None and any(
                    t in ("gate_proj", "up_proj", "down_proj")
                    for t in self.lora.target_modules):
                raise NotImplementedError(
                    "LoRA on MLP projections is not supported for MoE "
                    "models (experts have no adapter branch); target "
                    "attention projections only")
            mlp_out = MoEMLP(cfg, self.mesh, name="mlp")(
                normed, deterministic, token_mask)
        else:
            mlp_out = LlamaMLP(cfg, self.lora, name="mlp")(
                normed, deterministic, adapter_ids)
        return x + mlp_out, new_cache


def _remat_policy(name: str):
    policies = {
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # Save only each block's attention output (tagged in LlamaAttention):
        # the backward skips the flash-fwd recompute at the cost of one
        # (b, s, hidden) tensor per layer.
        "save_attn_out":
            jax.checkpoint_policies.save_only_these_names("attn_out"),
    }
    return policies[name]


class LlamaModel(nn.Module):
    """Transformer body (embeddings + blocks + final norm)."""

    cfg: ModelConfig
    lora: Optional[LoRAConfig] = None
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None, cache=None,
                 deterministic: bool = True, token_mask=None,
                 adapter_ids=None):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        pdtype = _dtype(cfg.param_dtype)
        b, s = input_ids.shape
        if token_mask is None and segment_ids is not None:
            token_mask = (segment_ids != 0).astype(jnp.int32)  # packed: 0 = pad

        embed = self.param(
            "embed_tokens",
            nn.initializers.normal(stddev=0.02),
            (cfg.vocab_size, cfg.hidden_size),
            pdtype,
        )
        if isinstance(embed, dict):
            # int8 serving: gather int8 rows, then scale (per-channel).
            x = (embed["q"][input_ids].astype(dtype)
                 * embed["scale"].astype(dtype))
        else:
            x = jnp.take(embed, input_ids, axis=0).astype(dtype)
        if cfg.embedding_scale:  # Gemma: embeddings scaled by sqrt(hidden)
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, dtype)

        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))

        # RoPE tables sized to cache capacity when decoding, else seq len.
        if cache is None:
            # Cover the actual sequence even past the preset's design
            # length: the table is computed (not learned), so extending it
            # is exact for in-range positions. This sizing is the
            # LOAD-BEARING invariant: apply_rope now gathers with
            # mode="clip" (r05 — the NaN-fill bounds check cost a
            # lax.cond per gather and broke vma typing under PP x SP), so
            # an under-sized table no longer NaNs loudly (the r03 bug
            # class, seq 512 > table 128) — it would silently clamp.
            # Keep every table-sizing branch >= max(positions) + 1.
            table_len = max(cfg.max_seq_len, s)
            # Trace-time enforcement of the invariant above (ADVICE r05):
            # positions here are bounded by the static sequence length
            # (arange(s) by default; packed per-doc positions < s), so an
            # under-sized table fails the trace instead of silently
            # clamping rotary angles.
            assert_rope_table_covers(table_len, s, "training/no-cache path")
        elif "block_tables" in cache[0]:
            # Paged: capacity = logical window = blocks/seq * block_size.
            # Positions are bounded by the engine's seq_len < capacity =
            # table_len by construction (not statically knowable here).
            table_len = cache[0]["block_tables"].shape[1] * cache[0]["k"].shape[1]
        else:
            table_len = cache[0]["k"].shape[1]
            # Decode over a dense cache: the query chunk's positions lie
            # inside the cache window; the chunk itself must fit.
            assert_rope_table_covers(table_len, s, "dense-cache decode path")
        cos, sin = rope_frequencies(cfg.resolved_head_dim, table_len, cfg.rope_theta)

        block_cls = LlamaBlock
        if cfg.remat and cache is None:
            block_cls = nn.remat(
                LlamaBlock,
                policy=_remat_policy(cfg.remat_policy),
                static_argnums=(7,),  # deterministic (arg 0 is the module)
            )

        new_caches = [] if cache is not None else None
        for i in range(cfg.num_layers):
            # Selective remat: every remat_stride-th block keeps its
            # activations instead of recomputing them in the backward —
            # stride k trades ~1/k of the recompute forward for that
            # fraction of saved activations in HBM.
            cls_i = block_cls
            if (cfg.remat and cache is None and cfg.remat_stride > 1
                    and i % cfg.remat_stride == 0):
                cls_i = LlamaBlock
            layer_cache = cache[i] if cache is not None else None
            x, layer_new_cache = cls_i(cfg, self.lora, self.mesh, name=f"layers_{i}")(
                x, cos, sin, positions, segment_ids, layer_cache, deterministic,
                token_mask, adapter_ids,
            )
            if cache is not None:
                new_caches.append(layer_new_cache)

        x = RMSNorm(cfg.rms_norm_eps, offset=cfg.rmsnorm_offset, name="final_norm")(x)
        return x, new_caches


def head_matrix_from_leaves(embed_leaf, head_leaf, tie_embeddings: bool,
                            anchor) -> jnp.ndarray:
    """The (hidden, vocab) head as an explicit matrix from raw param
    leaves — ONE implementation of the chunked-loss head contract, shared
    by the flat (``LlamaForCausalLM.head_matrix``) and pipeline-layout
    (``parallel.pipeline.pipeline_head_matrix``) callers so a head change
    cannot desynchronize the two chunked paths. Dtypes match __call__
    exactly: tied embeddings project in float32, untied heads in the
    activation dtype with fp32 accumulation."""
    from dlti_tpu.models.quantization import maybe_dequantize

    if tie_embeddings or head_leaf is None:
        embed = maybe_dequantize(embed_leaf, jnp.float32, anchor=anchor)
        return embed.astype(jnp.float32).T
    head = head_leaf
    if isinstance(head, dict):
        head = maybe_dequantize(head, anchor.dtype, anchor=anchor)
    return head.astype(anchor.dtype)


class LlamaForCausalLM(nn.Module):
    """Body + LM head. Returns float32 logits (stable softmax/loss)."""

    cfg: ModelConfig
    lora: Optional[LoRAConfig] = None
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None, cache=None,
                 deterministic: bool = True, token_mask=None,
                 return_hidden: bool = False, adapter_ids=None):
        cfg = self.cfg
        pdtype = _dtype(cfg.param_dtype)
        x, new_cache = LlamaModel(cfg, self.lora, self.mesh, name="model")(
            input_ids, positions, segment_ids, cache, deterministic, token_mask,
            adapter_ids,
        )
        if return_hidden:
            # Skip the LM head: the caller computes a seq-chunked loss so
            # (B, S, V) fp32 logits are never materialized whole
            # (training.step.chunked_causal_lm_loss). The head params must
            # still be grafted when this module owns them, so init traces
            # the normal path.
            if not self.is_initializing():
                return x, new_cache
        if cfg.tie_embeddings:
            from dlti_tpu.models.quantization import maybe_dequantize

            embed = maybe_dequantize(
                self.variables["params"]["model"]["embed_tokens"],
                jnp.float32, anchor=x)
            logits = jnp.einsum("bsh,vh->bsv", x.astype(jnp.float32),
                                embed.astype(jnp.float32))
        else:
            lm_head = self.param(
                "lm_head", nn.initializers.normal(stddev=0.02),
                (cfg.hidden_size, cfg.vocab_size), pdtype,
            )
            if isinstance(lm_head, dict):
                from dlti_tpu.models.quantization import maybe_dequantize

                lm_head = maybe_dequantize(lm_head, x.dtype, anchor=x)
            logits = jnp.dot(x, lm_head.astype(x.dtype),
                             preferred_element_type=jnp.float32)
        return logits.astype(jnp.float32), new_cache

    # ------------------------------------------------------------------
    def head_matrix(self, params, anchor):
        """The (hidden, vocab) projection __call__ applies after the body,
        as an explicit matrix — the input to the sequence-chunked loss
        (``training.step.chunked_causal_lm_loss``), kept here so head
        changes cannot desynchronize from the chunked path. Dtypes match
        __call__ exactly: tied embeddings project in float32
        (the einsum above), untied heads in the activation dtype with
        fp32 accumulation."""
        return head_matrix_from_leaves(
            params["model"]["embed_tokens"], params.get("lm_head"),
            self.cfg.tie_embeddings, anchor)

    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16) -> list:
        """Allocate a fixed-capacity KV cache for decode."""
        cfg = self.cfg
        hd = cfg.resolved_head_dim
        return [
            {
                "k": jnp.zeros((batch_size, max_len, cfg.num_kv_heads, hd), dtype),
                "v": jnp.zeros((batch_size, max_len, cfg.num_kv_heads, hd), dtype),
                "index": jnp.array(0, dtype=jnp.int32),
            }
            for _ in range(cfg.num_layers)
        ]
