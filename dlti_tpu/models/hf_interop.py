"""Hugging Face checkpoint interoperability.

The reference consumes stock HF hub checkpoints
(``AutoModelForCausalLM.from_pretrained``, ``training/train_baseline.py:122-126``)
and produces PEFT LoRA adapters (``trainer.save_model``,
``training/train_baseline.py:226-228``). For a reference user to switch to
this framework their artifacts must carry over, both directions:

* :func:`load_hf_checkpoint` / :func:`save_hf_checkpoint` — full-model
  weights in HF Llama layout (safetensors, single file or sharded with an
  ``model.safetensors.index.json``), mapped to/from our Flax param tree.
* :func:`load_peft_adapter` / :func:`save_peft_adapter` — PEFT-format LoRA
  adapters (``adapter_model.safetensors`` + ``adapter_config.json``), mapped
  to/from our in-tree ``lora_a``/``lora_b`` factors.
* :func:`config_from_hf` / :func:`config_to_hf` — ``config.json`` ↔
  :class:`~dlti_tpu.config.ModelConfig`.

Name mapping (HF stores ``(out, in)`` torch kernels; Flax stores
``(in, out)``):

====================================================  =========================================
HF key                                                ours (under ``params``)
====================================================  =========================================
``model.embed_tokens.weight``                         ``model.embed_tokens``
``model.layers.{i}.self_attn.{q,k,v,o}_proj.weight``  ``model.layers_{i}.attn.*.kernel`` (T)
``model.layers.{i}.self_attn.{q,k,v}_proj.bias``      ``model.layers_{i}.attn.*.bias``
``model.layers.{i}.mlp.{gate,up,down}_proj.weight``   ``model.layers_{i}.mlp.*.kernel`` (T)
``model.layers.{i}.input_layernorm.weight``           ``model.layers_{i}.input_norm.scale``
``model.layers.{i}.post_attention_layernorm.weight``  ``model.layers_{i}.post_attn_norm.scale``
``model.norm.weight``                                 ``model.final_norm.scale``
``lm_head.weight``                                    ``lm_head`` (T; absent when tied)
====================================================  =========================================
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dlti_tpu.config import LoRAConfig, ModelConfig

_ATTN_PROJS = ("q_proj", "k_proj", "v_proj", "o_proj")
_MLP_PROJS = ("gate_proj", "up_proj", "down_proj")


def _unwrap(params: Mapping[str, Any]) -> Mapping[str, Any]:
    """Accept either the Flax variables dict (``{"params": tree}``) or the
    bare param tree."""
    return params["params"] if "params" in params and "model" not in params else params


from dlti_tpu.utils.dtypes import resolve_dtype as _dtype  # shared table


# ----------------------------------------------------------------------
# config.json <-> ModelConfig
# ----------------------------------------------------------------------

def config_from_hf(hf: Mapping[str, Any], **overrides) -> ModelConfig:
    """Build a :class:`ModelConfig` from an HF ``config.json`` dict."""
    num_heads = hf.get("num_attention_heads", 32)
    kw: Dict[str, Any] = dict(
        vocab_size=hf.get("vocab_size", 32000),
        hidden_size=hf.get("hidden_size", 4096),
        intermediate_size=hf.get("intermediate_size", 11008),
        num_layers=hf.get("num_hidden_layers", 32),
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=hf.get("head_dim"),
        max_seq_len=hf.get("max_position_embeddings", 4096),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    torch_dtype = hf.get("torch_dtype")
    if torch_dtype in ("float32", "float16", "bfloat16"):
        kw["param_dtype"] = torch_dtype
        if torch_dtype == "float32":
            kw["dtype"] = "float32"
    model_type = hf.get("model_type", "llama")
    if model_type not in ("llama", "mistral", "qwen2", "gemma"):
        # A family we haven't verified forward-pass parity for (gemma2's
        # logit softcapping, phi's partial rotary, ...) must fail loudly,
        # not import as a subtly different model.
        raise NotImplementedError(
            f"model_type {model_type!r} not supported "
            f"(llama/mistral/qwen2/gemma)")
    if model_type == "gemma":
        kw["rmsnorm_offset"] = True       # (1 + w) norm parameterization
        kw["embedding_scale"] = True      # embed * sqrt(hidden)
        kw["tie_embeddings"] = bool(hf.get("tie_word_embeddings", True))
    if hf.get("attention_bias") or model_type == "qwen2":
        kw["attention_bias"] = True
    if hf.get("sliding_window"):
        if model_type == "qwen2":
            # Qwen2 ships sliding_window with use_sliding_window defaulting
            # to *false* (full attention), and when enabled applies it only
            # to layers >= max_window_layers — we support all-or-nothing:
            # mwl <= 0 windows every layer; mwl >= num_layers windows none
            # (common shipped configs set mwl == num_hidden_layers).
            if hf.get("use_sliding_window", False):
                mwl = hf.get("max_window_layers", kw["num_layers"])
                if mwl is None or mwl <= 0:
                    kw["sliding_window"] = int(hf["sliding_window"])
                elif mwl < kw["num_layers"]:
                    raise NotImplementedError(
                        "per-layer sliding window (qwen2 max_window_layers="
                        f"{mwl} of {kw['num_layers']}) is not supported; "
                        "only uniform windows")
                # else: no layer is windowed -> full attention, nothing to set
        elif hf.get("use_sliding_window", True):
            kw["sliding_window"] = int(hf["sliding_window"])
    # Gemma configs prefer "hidden_activation"; transformers force-overrides
    # a null one (and the original-release legacy hidden_act: "gelu") to
    # gelu_pytorch_tanh, so the fallback for gemma must do the same.
    if model_type == "gemma":
        act = hf.get("hidden_activation") or "gelu_pytorch_tanh"
    else:
        act = hf.get("hidden_activation") or hf.get("hidden_act", "silu")
    kw["mlp_activation"] = {
        "silu": "silu", "gelu": "gelu_exact",
        "gelu_pytorch_tanh": "gelu_tanh", "gelu_new": "gelu_tanh",
    }.get(act)
    if kw["mlp_activation"] is None:
        raise NotImplementedError(f"unsupported hidden_act {act!r}")
    kw.update(overrides)
    known = {f.name for f in dataclasses.fields(ModelConfig)}
    unsupported = sorted(set(kw) - known)
    if unsupported:
        # Never drop architecture features silently (a Qwen2 checkpoint
        # without its q/k/v biases would load and be quietly wrong).
        raise NotImplementedError(
            f"checkpoint needs ModelConfig fields not yet supported: "
            f"{unsupported}")
    return ModelConfig(**kw)


def config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    """Emit an HF-style ``config.json`` dict for :func:`save_hf_checkpoint`.

    The model_type tracks the family features so transformers picks a class
    that honors them (qwen2: q/k/v bias; mistral: sliding window)."""
    if cfg.rmsnorm_offset:
        model_type, arch = "gemma", "GemmaForCausalLM"
    elif cfg.attention_bias:
        model_type, arch = "qwen2", "Qwen2ForCausalLM"
    elif cfg.sliding_window:
        model_type, arch = "mistral", "MistralForCausalLM"
    else:
        model_type, arch = "llama", "LlamaForCausalLM"
    out = {
        "architectures": [arch],
        "model_type": model_type,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.resolved_head_dim,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "hidden_act": {"silu": "silu", "gelu_exact": "gelu",
                       "gelu_tanh": "gelu_pytorch_tanh"}[cfg.mlp_activation],
        # transformers' Gemma ignores hidden_act and reads this key.
        "hidden_activation": {"silu": "silu", "gelu_exact": "gelu",
                              "gelu_tanh": "gelu_pytorch_tanh"}[cfg.mlp_activation],
        "torch_dtype": {"bfloat16": "bfloat16", "float16": "float16",
                        "float32": "float32"}[cfg.param_dtype],
    }
    if cfg.attention_bias:
        out["attention_bias"] = True
    if cfg.sliding_window:
        out["sliding_window"] = cfg.sliding_window
        # Qwen2 ignores sliding_window unless the flag is set, and applies
        # it only to layers >= max_window_layers — 0 means every layer,
        # matching our uniform window.
        out["use_sliding_window"] = True
        out["max_window_layers"] = 0
    return out


# ----------------------------------------------------------------------
# state dict -> params
# ----------------------------------------------------------------------

def params_from_hf_state_dict(
    state_dict: Mapping[str, Any],
    cfg: ModelConfig,
) -> Dict[str, Any]:
    """Map an HF Llama state dict (numpy/jax arrays) onto our param tree.

    Raises ``KeyError`` on missing weights and ``ValueError`` on unconsumed
    HF keys, so silent architecture mismatches can't slip through.
    """
    dt = _dtype(cfg.param_dtype)
    sd = dict(state_dict)

    def take(key: str, transpose: bool = False):
        w = jnp.asarray(sd.pop(key))
        if transpose:
            w = w.T
        return w.astype(dt)

    model: Dict[str, Any] = {"embed_tokens": take("model.embed_tokens.weight")}
    for i in range(cfg.num_layers):
        hf_l = f"model.layers.{i}"
        attn: Dict[str, Any] = {}
        for p in _ATTN_PROJS:
            attn[p] = {"kernel": take(f"{hf_l}.self_attn.{p}.weight", transpose=True)}
            # q/k/v biases load iff the config declares them (KeyError when
            # declared-but-absent; declared-absent-but-present falls through
            # to the unconsumed-keys check) — bias/config mismatches are
            # never silent. o_proj is biasless in every supported family.
            if cfg.attention_bias and p != "o_proj":
                attn[p]["bias"] = take(f"{hf_l}.self_attn.{p}.bias")
        mlp = {p: {"kernel": take(f"{hf_l}.mlp.{p}.weight", transpose=True)}
               for p in _MLP_PROJS}
        model[f"layers_{i}"] = {
            "attn": attn,
            "mlp": mlp,
            "input_norm": {"scale": take(f"{hf_l}.input_layernorm.weight")},
            "post_attn_norm": {"scale": take(f"{hf_l}.post_attention_layernorm.weight")},
        }
    model["final_norm"] = {"scale": take("model.norm.weight")}

    params: Dict[str, Any] = {"model": model}
    if not cfg.tie_embeddings:
        if "lm_head.weight" in sd:
            params["lm_head"] = take("lm_head.weight", transpose=True)
        else:
            # Some tied checkpoints omit lm_head even when config says untied.
            params["lm_head"] = jnp.asarray(model["embed_tokens"]).T.astype(dt)
    else:
        sd.pop("lm_head.weight", None)
    sd.pop("model.rotary_emb.inv_freq", None)  # derived, never loaded
    leftovers = [k for k in sd if "rotary_emb" not in k]
    if leftovers:
        raise ValueError(f"unconsumed HF weights (architecture mismatch?): "
                         f"{sorted(leftovers)[:8]} (+{max(0, len(leftovers) - 8)} more)")
    return params


def hf_state_dict_from_params(params: Mapping[str, Any],
                              cfg: ModelConfig) -> Dict[str, jnp.ndarray]:
    """Our (merged, LoRA-free) param tree -> HF Llama state dict."""
    p = _unwrap(params)
    model = p["model"]
    sd: Dict[str, jnp.ndarray] = {
        "model.embed_tokens.weight": jnp.asarray(model["embed_tokens"]),
        "model.norm.weight": jnp.asarray(model["final_norm"]["scale"]),
    }
    for i in range(cfg.num_layers):
        ours = model[f"layers_{i}"]
        hf_l = f"model.layers.{i}"
        for proj in _ATTN_PROJS:
            leaf = ours["attn"][proj]
            if "lora_a" in leaf:
                raise ValueError("merge LoRA factors before HF export (merge_lora_params)")
            sd[f"{hf_l}.self_attn.{proj}.weight"] = jnp.asarray(leaf["kernel"]).T
            if "bias" in leaf:
                sd[f"{hf_l}.self_attn.{proj}.bias"] = jnp.asarray(leaf["bias"])
        for proj in _MLP_PROJS:
            leaf = ours["mlp"][proj]
            if "lora_a" in leaf:
                raise ValueError("merge LoRA factors before HF export (merge_lora_params)")
            sd[f"{hf_l}.mlp.{proj}.weight"] = jnp.asarray(leaf["kernel"]).T
        sd[f"{hf_l}.input_layernorm.weight"] = jnp.asarray(ours["input_norm"]["scale"])
        sd[f"{hf_l}.post_attention_layernorm.weight"] = jnp.asarray(
            ours["post_attn_norm"]["scale"])
    if not cfg.tie_embeddings and "lm_head" in p:
        sd["lm_head.weight"] = jnp.asarray(p["lm_head"]).T
    return sd


def graft_base_params(params: Dict[str, Any], base: Mapping[str, Any]) -> Dict[str, Any]:
    """Overlay loaded base weights onto a freshly-initialized param tree.

    Leaves present in ``base`` replace the initialized values (with a shape
    check); leaves only in ``params`` (``lora_a``/``lora_b`` factors) keep
    their initialization — the PEFT ``get_peft_model``-on-pretrained
    semantics (``training/train_baseline.py:122-140``). Base leaves with no
    counterpart in the model tree are an architecture mismatch and raise
    (mirroring :func:`params_from_hf_state_dict`'s unconsumed-key check).
    """
    dropped: list = []

    def _graft(p, b, path):
        if not isinstance(p, Mapping):
            if hasattr(b, "shape") and tuple(b.shape) != tuple(p.shape):
                raise ValueError(
                    f"{'.'.join(path)}: checkpoint shape {tuple(b.shape)} != "
                    f"model shape {tuple(p.shape)} (wrong ModelConfig?)")
            return jnp.asarray(b).astype(p.dtype)
        for k in b:
            if k not in p:
                dropped.append(".".join(path + (k,)))
        return {k: _graft(v, b[k], path + (k,)) if k in b else v
                for k, v in p.items()}

    out = _graft(params, base, ())
    if dropped:
        raise ValueError(
            f"base checkpoint has weights the model tree lacks (architecture "
            f"mismatch?): {dropped[:8]}" +
            (f" (+{len(dropped) - 8} more)" if len(dropped) > 8 else ""))
    return out


# ----------------------------------------------------------------------
# safetensors IO (single-file and HF-sharded)
# ----------------------------------------------------------------------

def _load_safetensors_dir(directory: str) -> Dict[str, jnp.ndarray]:
    from safetensors import safe_open

    index_path = os.path.join(directory, "model.safetensors.index.json")
    single_path = os.path.join(directory, "model.safetensors")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        shards = sorted(set(weight_map.values()))
    elif os.path.exists(single_path):
        shards = ["model.safetensors"]
    else:
        shards = sorted(f for f in os.listdir(directory) if f.endswith(".safetensors"))
        if not shards:
            raise FileNotFoundError(f"no .safetensors files under {directory}")
    out: Dict[str, jnp.ndarray] = {}
    for shard in shards:
        with safe_open(os.path.join(directory, shard), framework="flax") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def load_hf_checkpoint(
    directory: str,
    cfg: Optional[ModelConfig] = None,
    **config_overrides,
) -> Tuple[Dict[str, Any], ModelConfig]:
    """Load an HF Llama checkpoint directory -> ``(params, model_config)``.

    ``cfg`` overrides config.json entirely; ``config_overrides`` tweak
    individual fields (e.g. ``max_seq_len=512``, ``dtype="bfloat16"``).
    The two are mutually exclusive.
    """
    if cfg is not None and config_overrides:
        raise ValueError(
            f"pass either cfg or config overrides, not both (got cfg plus "
            f"{sorted(config_overrides)})")
    if cfg is None:
        cfg_path = os.path.join(directory, "config.json")
        with open(cfg_path) as f:
            cfg = config_from_hf(json.load(f), **config_overrides)
    sd = _load_safetensors_dir(directory)
    return params_from_hf_state_dict(sd, cfg), cfg


def save_hf_checkpoint(
    directory: str,
    params: Mapping[str, Any],
    cfg: ModelConfig,
    max_shard_bytes: int = 4 * 1024**3,
) -> None:
    """Write params as an HF-layout checkpoint (config.json + safetensors).

    Shards at ``max_shard_bytes`` with the standard
    ``model-XXXXX-of-XXXXX.safetensors`` + index layout so the output is
    loadable by ``transformers`` / vLLM / the reference stack directly —
    the portable-artifact contract of
    ``stage3_gather_16bit_weights_on_model_save``
    (``configs/ds_config_zero3.json:36``).
    """
    from safetensors.flax import save_file

    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "config.json"), "w") as f:
        json.dump(config_to_hf(cfg), f, indent=2)

    sd = hf_state_dict_from_params(params, cfg)
    # Greedy sharding by byte size, stable key order.
    shards: list = [[]]
    sizes = [0]
    for key in sd:
        nbytes = int(np.prod(sd[key].shape)) * sd[key].dtype.itemsize
        if sizes[-1] + nbytes > max_shard_bytes and shards[-1]:
            shards.append([])
            sizes.append(0)
        shards[-1].append(key)
        sizes[-1] += nbytes
    if len(shards) == 1:
        save_file(dict(sd), os.path.join(directory, "model.safetensors"))
        return
    weight_map = {}
    n = len(shards)
    for idx, keys in enumerate(shards):
        fname = f"model-{idx + 1:05d}-of-{n:05d}.safetensors"
        save_file({k: sd[k] for k in keys}, os.path.join(directory, fname))
        weight_map.update({k: fname for k in keys})
    with open(os.path.join(directory, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {"total_size": sum(sizes)},
                   "weight_map": weight_map}, f, indent=2)


# ----------------------------------------------------------------------
# PEFT adapter interop
# ----------------------------------------------------------------------

_PEFT_PREFIX = "base_model.model."


def save_peft_adapter(directory: str, params: Mapping[str, Any],
                      lora: LoRAConfig) -> None:
    """Extract in-tree LoRA factors -> PEFT ``adapter_model.safetensors``.

    Output matches what the reference's ``trainer.save_model`` writes for a
    PEFT-wrapped model (``training/train_baseline.py:226-228``), so adapters
    trained here drop into a PEFT/vLLM stack unchanged.
    """
    from safetensors.flax import save_file

    p = _unwrap(params)
    sd: Dict[str, jnp.ndarray] = {}

    def walk(tree, path):
        if not isinstance(tree, Mapping):
            return
        if "lora_a" in tree and "lora_b" in tree:
            hf_path = _our_path_to_hf(path)
            sd[f"{_PEFT_PREFIX}{hf_path}.lora_A.weight"] = jnp.asarray(tree["lora_a"]).T
            sd[f"{_PEFT_PREFIX}{hf_path}.lora_B.weight"] = jnp.asarray(tree["lora_b"]).T
            return
        for k, v in tree.items():
            walk(v, path + (k,))

    walk(p, ())
    if not sd:
        raise ValueError("no LoRA factors in params; nothing to export")
    os.makedirs(directory, exist_ok=True)
    save_file(sd, os.path.join(directory, "adapter_model.safetensors"))
    with open(os.path.join(directory, "adapter_config.json"), "w") as f:
        json.dump({
            "peft_type": "LORA",
            "r": lora.r,
            "lora_alpha": lora.alpha,
            "lora_dropout": lora.dropout,
            "target_modules": list(lora.target_modules),
            "bias": "none",
            "task_type": "CAUSAL_LM",
        }, f, indent=2)


def load_peft_adapter(directory: str, params: Dict[str, Any]) -> Dict[str, Any]:
    """Load a PEFT adapter into an existing param tree (in place of the
    zero-initialized ``lora_a``/``lora_b`` leaves). Returns the tree."""
    from safetensors import safe_open

    with safe_open(os.path.join(directory, "adapter_model.safetensors"),
                   framework="flax") as f:
        sd = {k: f.get_tensor(k) for k in f.keys()}

    p = _unwrap(params)
    for key, w in sd.items():
        stripped = key[len(_PEFT_PREFIX):] if key.startswith(_PEFT_PREFIX) else key
        stripped = stripped.removesuffix(".weight")
        which = None
        for suffix, ours in ((".lora_A", "lora_a"), (".lora_B", "lora_b")):
            if stripped.endswith(suffix):
                stripped, which = stripped.removesuffix(suffix), ours
        if which is None:
            raise ValueError(f"unrecognized adapter key {key}")
        node = _hf_path_to_node(p, stripped)
        if which not in node:
            raise ValueError(
                f"param tree has no {which} at {stripped}; build the model "
                f"with a matching LoRAConfig before loading the adapter")
        expect = node[which].shape
        got = w.T.shape
        if expect != got:
            raise ValueError(f"{key}: shape {got} != expected {expect}")
        node[which] = w.T.astype(node[which].dtype)
    return params


def _our_path_to_hf(path: tuple) -> str:
    """('model','layers_3','attn','q_proj') -> 'model.layers.3.self_attn.q_proj'."""
    out = []
    for part in path:
        if part.startswith("layers_"):
            out.append(f"layers.{part.split('_', 1)[1]}")
        elif part == "attn":
            out.append("self_attn")
        else:
            out.append(part)
    return ".".join(out)


def _hf_path_to_node(tree: Dict[str, Any], hf_path: str) -> Dict[str, Any]:
    """'model.layers.3.self_attn.q_proj' -> the q_proj dict in our tree."""
    parts = hf_path.split(".")
    node: Any = tree
    i = 0
    while i < len(parts):
        part = parts[i]
        if part == "layers" and i + 1 < len(parts) and parts[i + 1].isdigit():
            part, i = f"layers_{parts[i + 1]}", i + 1
        elif part == "self_attn":
            part = "attn"
        if part not in node:
            raise KeyError(f"{hf_path}: no '{part}' in tree level "
                           f"(have {sorted(node)[:8]})")
        node = node[part]
        i += 1
    return node
