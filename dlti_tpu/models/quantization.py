"""Weight-only int8 quantization for serving.

The reference pins ``bitsandbytes`` (``requirements.txt:12``) but never
imports it (SURVEY.md §2b: declared, unused); this is the TPU-native
realization of that latent capability. Weights rest in HBM as int8 with
per-output-channel fp32 scales (symmetric absmax) — roughly halving
weight memory, which goes straight into a bigger KV block pool — and are
dequantized inside the compiled program, where XLA fuses the
``int8 -> bf16 * scale`` expansion into the consuming matmul's prologue.

Quantized leaves are ``{"q": int8[...], "scale": f32[out]}`` dicts in
place of the original array; :func:`dequantize_params` restores the
compute-dtype tree (call it *inside* jit).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp

# Leaves worth quantizing: big 2-D+ matmul weights. Tiny/1-D leaves (norm
# scales, biases, LoRA factors) stay in their original dtype.
_MIN_QUANT_SIZE = 1 << 14


def _should_quantize(path: tuple, value: Any) -> bool:
    if not hasattr(value, "shape") or value.ndim < 2:
        return False
    if value.size < _MIN_QUANT_SIZE:
        return False
    name = str(getattr(path[-1], "key", path[-1]))
    # LoRA factors are tiny; the MoE router is deliberately fp32 (stable
    # softmax/top-k) and its consumer takes it unquantized.
    return name not in ("lora_a", "lora_b", "router")


def quantize_params_int8(params: Mapping[str, Any], donate: bool = False) -> Any:
    """Quantize matmul weights to int8 + per-out-channel scales.

    The last dim is treated as the output-channel dim ((in, out) Flax
    kernels, (vocab, hidden) embeddings, stacked expert weights alike).

    ``donate=True`` frees each source array as soon as its int8 twin is
    materialized, so peak device memory is the *source* tree + one leaf
    instead of source + quantized together — the difference between
    fitting and OOMing when quantizing a 7B bf16 tree in 16 GB of HBM.
    The caller's tree is unusable afterwards.
    """
    def leaf(path, v):
        if not _should_quantize(path, v):
            return v
        v32 = jnp.asarray(v, jnp.float32)
        # Reduce over the contraction dim only (axis -2), keeping leading
        # dims: 2-D kernels get per-out-channel scales, stacked expert
        # weights (E, h, m) get per-expert-per-channel (E, 1, m) scales —
        # one quiet expert never inherits a loud expert's scale.
        absmax = jnp.max(jnp.abs(v32), axis=-2, keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(v32 / scale), -127, 127).astype(jnp.int8)
        out = {"q": q, "scale": scale.astype(jnp.float32)}
        if donate and hasattr(v, "delete"):
            # Retire the quantize computation, then drop the source buffer.
            jax.block_until_ready(q)
            v.delete()
        return out

    return jax.tree_util.tree_map_with_path(leaf, dict(params))


def is_quant_node(node: Any) -> bool:
    return (isinstance(node, Mapping) and set(node.keys()) == {"q", "scale"}
            and getattr(node.get("q"), "dtype", None) == jnp.int8)


def maybe_dequantize(leaf: Any, dtype, anchor: Any = None) -> Any:
    """Expand one (possibly) quantized leaf to ``dtype``.

    Called at each weight's *consumer* (LoRADense / embeddings / MoE
    experts), so only the weights of the layer currently executing hold a
    dequantized copy — peak HBM stays ~int8 tree + one layer, not int8 +
    a full compute-dtype tree.

    ``anchor`` (the consumer's activation input) matters for exactly that:
    a dequant whose only inputs are weights is loop-invariant, so XLA
    hoists it out of a multi-step decode scan and schedules every layer's
    expansion at program start — pinning the full bf16 tree live (OOMs
    7B int8 serving on a 16 GB chip). The optimization barrier makes the
    expansion depend on the activation, forcing it to stay inside the
    loop, per layer, scheduled at its use.
    """
    if is_quant_node(leaf):
        q = leaf["q"]
        if anchor is not None:
            q, _ = jax.lax.optimization_barrier((q, anchor))
        return (q.astype(jnp.float32) * leaf["scale"]).astype(dtype)
    return leaf


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """Whole-tree expansion (tests/export; the model dequantizes per leaf
    at the consumer via :func:`maybe_dequantize`)."""
    if is_quant_node(params):
        return maybe_dequantize(params, dtype)
    if isinstance(params, Mapping):
        return {k: dequantize_params(v, dtype) for k, v in params.items()}
    return params


def quantization_error(params: Any, qparams: Any) -> float:
    """Worst relative per-leaf RMS error — a quick sanity metric."""
    worst = 0.0
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    deq = dequantize_params(qparams, jnp.float32)
    flat_b = jax.tree_util.tree_leaves_with_path(deq)
    for (_, a), (_, b) in zip(flat_a, flat_b):
        a32 = jnp.asarray(a, jnp.float32)
        rms = float(jnp.sqrt(jnp.mean((a32 - b) ** 2)))
        denom = float(jnp.sqrt(jnp.mean(a32 ** 2))) or 1.0
        worst = max(worst, rms / denom)
    return worst
