"""OpenAI-compatible HTTP server over the continuous-batching engine.

Closes the reference's claimed-but-absent serving leg: "High-throughput
serving with vLLM and tensor parallelism" (``README.md:10``), "REST API"
(``README.md:16``) — no code in the reference repo (SURVEY.md §0). Endpoints
mirror the vLLM/OpenAI surface the reference's pins imply:

* ``POST /v1/completions``        — text completion, optional SSE streaming
* ``POST /v1/chat/completions``   — chat with the Llama-2 template the
  reference's data pipeline defines (``scripts/prepare_dataset.py:12-25``:
  ``<s>[INST] {q} [/INST] {a}</s>``)
* ``GET /v1/models`` · ``GET /health`` · ``GET /stats`` ·
  ``GET /metrics`` (Prometheus text exposition of the same counters)

Stdlib only (``http.server`` + threads): the engine steps in one background
thread (the TPU is a single serialized stream anyway); handler threads block
on per-request token queues. No aiohttp/FastAPI dependency.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
import dataclasses
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from dlti_tpu.config import GatewayConfig, TelemetryConfig
from dlti_tpu.data.tokenizer import Tokenizer
from dlti_tpu.serving.engine import InferenceEngine, Request
from dlti_tpu.serving.gateway import (
    AdmissionError, AdmissionGateway, PRIORITIES, affinity_key_from,
    tenant_from_headers,
)
from dlti_tpu.serving.sampling import SamplingParams
from dlti_tpu.telemetry import (
    AnomalyWatchdog, FlightRecorder, MetricsRegistry, TimeSeriesSampler,
    get_recorder, get_tracer, install_recorder, render_dashboard_html,
    request_breakdown,
)
from dlti_tpu.telemetry.ledger import REQUEST_PHASES as _REQUEST_PHASES
from dlti_tpu.utils.logging import get_logger

# /stats keys exposed as Prometheus gauges (point-in-time values); every
# other numeric stat is a monotonic counter. Name-stability contract: the
# exposition names are dlti_<key> — scraped by external dashboards, so keys
# here and in the engine's stats dict must not be renamed.
_GAUGE_KEYS = ("active_seqs", "waiting", "free_blocks")


def build_registry(async_engine: "AsyncEngine") -> MetricsRegistry:
    """The single backing store for ``/stats`` and ``/metrics``: engine
    counters ride in as a scalar-source callback (the engine's ``stats``
    dict stays the source of truth — no registry lock on the decode path),
    and the engine's request-lifecycle histograms (TTFT / TPOT / queue
    time) register for exposition."""
    registry = MetricsRegistry()

    def _engine_scalars() -> dict:
        eng = async_engine.engine
        return {
            **eng.stats,
            "active_seqs": eng.num_active,
            "waiting": len(eng.waiting),
            "free_blocks": eng.num_free_blocks,
        }

    registry.add_scalar_source(_engine_scalars, gauge_keys=_GAUGE_KEYS,
                               prefix="dlti_")
    for hist in async_engine.engine.telemetry.histograms():
        registry.register(hist)
    # Self-monitoring series: the span ring's eviction counter (truncated
    # forensics must be self-announcing) plus the module-level watchdog /
    # flight-recorder counters (shared with any trainer in-process).
    registry.add_scalar_source(
        lambda: {"trace_dropped_events": get_tracer().dropped_events},
        prefix="dlti_")
    from dlti_tpu.telemetry.flightrecorder import dumps_total
    from dlti_tpu.telemetry.watchdog import alerts_total

    registry.register(alerts_total)
    registry.register(dumps_total)
    # Distributed-tracing federation counters (module-level, like the
    # watchdog/flight pair): spans adopted from fleet workers, spans
    # that arrived without any request/trace parentage, and the per-
    # worker clock-offset estimate the rebasing used. Registered even on
    # single-process engines so the series exists (at zero) and the
    # metric-naming contract can walk it.
    from dlti_tpu.telemetry import distributed_trace as _dtrace

    registry.register(_dtrace.federated_spans_total)
    registry.register(_dtrace.unparented_spans_total)
    registry.register(_dtrace.clock_offset_gauge)
    # Numeric-fault sentinel + SDC counters (dlti_tpu.training.sentinel):
    # module-level like the watchdog/flight pair, so an in-process
    # trainer's anomalies and the serving guard drills share one series
    # and /dashboard plots them.
    from dlti_tpu.training import sentinel as _sentinel

    for metric in (_sentinel.anomalies_total,
                   _sentinel.skipped_updates_total,
                   _sentinel.rollbacks_total,
                   _sentinel.quarantined_windows_total,
                   _sentinel.sdc_probes_total,
                   _sentinel.sdc_mismatches_total):
        registry.register(metric)
    # Continuous-delivery counters (serving.deploy): module-level like the
    # watchdog/flight pair, so the sampler rings them for /dashboard and
    # the watchdog's canary_regression rule watches rollbacks grow.
    from dlti_tpu.serving import deploy as _deploy

    for metric in (_deploy.candidates_total, _deploy.canaries_total,
                   _deploy.promotions_total, _deploy.rollbacks_total,
                   _deploy.rejected_total, _deploy.incumbent_step_gauge):
        registry.register(metric)
    # Tiered prefix-cache telemetry (module-level like the watchdog /
    # flight counters, so replicas aggregate into one series): per-tier
    # hit/miss/eviction/promotion/demotion counters + block gauges.
    from dlti_tpu.serving import prefix_cache as _pc

    for metric in (_pc.hits_total, _pc.misses_total, _pc.evictions_total,
                   _pc.promotions_total, _pc.demotions_total,
                   _pc.blocks_gauge):
        registry.register(metric)
    # Multi-LoRA adapter pool telemetry (serving.adapters): module-level
    # like the prefix-cache counters — pool load/evict/hit/miss counters
    # plus the slot/byte gauges, one series across replicas.
    from dlti_tpu.serving import adapters as _ad

    for metric in (_ad.loads_total, _ad.evictions_total,
                   _ad.pool_hits_total, _ad.pool_misses_total,
                   _ad.pool_slots_gauge, _ad.pool_bytes_gauge):
        registry.register(metric)

    def _prefix_hit_rate() -> dict:
        # Derived hit-rate gauge so /dashboard gets a ready-made series
        # (the raw token counters are cumulative; a sparkline of the
        # ratio is what a human actually reads during a run): fraction of
        # prompt tokens served from cache — HBM hits plus lower-tier
        # restores — over everything the engine handled.
        s = async_engine.engine.stats
        cached = s.get("prefix_cached_tokens", 0)
        restored = s.get("prefix_restored_tokens", 0)
        total = cached + restored + s.get("prefill_tokens", 0)
        return {"prefix_cache_hit_rate":
                (cached + restored) / total if total else 0.0}

    registry.add_scalar_source(_prefix_hit_rate,
                               gauge_keys=("prefix_cache_hit_rate",),
                               prefix="dlti_")

    def _spec_scalars() -> dict:
        # Speculative-decode scrape surface (SPEC_METRIC_NAMES contract):
        # explicit *_total counters for the raw draft economics plus two
        # derived gauges — cumulative acceptance ratio and the draft
        # length the adaptive ladder picked for the last decode round.
        # Derivations read the stats dict (aggregated by every engine
        # facade); draft_len is engine-local state, so facades without it
        # (replicated/disagg/fleet fronts, test fakes) expose 0.
        eng = async_engine.engine
        s = eng.stats
        p = s.get("spec_proposed", 0)
        return {
            "spec_proposed_total": p,
            "spec_accepted_total": s.get("spec_accepted", 0),
            "spec_paused_rounds_total": s.get("spec_paused_rounds", 0),
            "spec_acceptance_rate":
                s.get("spec_accepted", 0) / p if p else 0.0,
            "spec_draft_len": getattr(eng, "spec_draft_len", 0),
        }

    registry.add_scalar_source(
        _spec_scalars,
        gauge_keys=("spec_acceptance_rate", "spec_draft_len"),
        prefix="dlti_")
    # Goodput ledger + critical-path attribution (telemetry.ledger):
    # module-level like the watchdog/flight counters — the per-request
    # phase totals back the TTFT decomposition on /metrics, and an
    # in-process trainer's goodput fraction/MFU ride the same registry.
    from dlti_tpu.telemetry import ledger as _ledger

    for metric in (_ledger.goodput_fraction_gauge,
                   _ledger.goodput_seconds_total,
                   _ledger.goodput_mfu_gauge,
                   _ledger.phase_seconds_total,
                   _ledger.phase_requests_total):
        registry.register(metric)
    # HBM memory ledger (telemetry.memledger): per-owner device-memory
    # gauges — module-level like the watchdog/flight counters, so an
    # in-process trainer's ledger and the engine's share one exposition.
    from dlti_tpu.telemetry import memledger as _ml

    for metric in (_ml.hbm_bytes_gauge, _ml.hbm_peak_gauge,
                   _ml.hbm_headroom_gauge, _ml.hbm_untracked_gauge):
        registry.register(metric)
    # SLO engine (telemetry.slo): compliance / error-budget / burn-rate
    # gauges — module-level like the watchdog/flight counters, populated
    # only when a tracker is wired (empty children cost nothing on
    # exposition).
    from dlti_tpu.telemetry import slo as _slo

    for metric in (_slo.compliance_gauge, _slo.budget_remaining_gauge,
                   _slo.burn_rate_gauge):
        registry.register(metric)
    # Durable-writer health (utils.durable_io): free bytes on the
    # persistence filesystem plus path_class-labeled write-error /
    # degraded series — the watchdog's disk_pressure inputs on /metrics.
    from dlti_tpu.utils import durable_io as _dio

    for metric in (_dio.free_bytes_gauge, _dio.write_errors_total,
                   _dio.degraded_gauge):
        registry.register(metric)
    # Replica lifecycle (serving.lifecycle): quarantine / reinstate /
    # flap / migration counters plus the per-replica state gauge —
    # module-level so every fleet in the process (both disagg pools)
    # shares one exposition.
    from dlti_tpu.serving import lifecycle as _lc

    for metric in (_lc.quarantines_total, _lc.reinstates_total,
                   _lc.flaps_total, _lc.migrations_total,
                   _lc.migration_fallbacks_total, _lc.replica_state_gauge):
        registry.register(metric)
    # Disaggregated serving (serving.disagg): per-pool gauges + KV-handoff
    # counters ride in via the controller's pool_scalars source, plus the
    # module-level handoff-latency histogram.
    if hasattr(async_engine.engine, "pool_scalars"):
        from dlti_tpu.serving import disagg as _disagg

        registry.add_scalar_source(async_engine.engine.pool_scalars,
                                   gauge_keys=_disagg.POOL_GAUGE_KEYS,
                                   prefix="dlti_")
        registry.register(_disagg.handoff_seconds)
    # Multi-process fleet (serving.fleet): per-worker federated series
    # (dlti_fleet_w{i}_*) + fleet-level gauges ride in via the
    # supervisor's fleet_scalars source; the module-level wire-protocol
    # and respawn counters register alongside.
    if hasattr(async_engine.engine, "fleet_scalars"):
        from dlti_tpu.serving import fleet as _fleet
        from dlti_tpu.serving import wire as _wire

        registry.add_scalar_source(
            async_engine.engine.fleet_scalars,
            gauge_keys=tuple(async_engine.engine.fleet_gauge_keys),
            prefix="dlti_")
        for metric in (_wire.frames_total, _wire.wire_bytes_total,
                       _fleet.workers_alive_gauge, _fleet.respawns_total):
            registry.register(metric)
    return registry


def llama2_chat_prompt(messages: List[dict]) -> str:
    """Messages -> Llama-2 chat string (the reference's training format,
    ``scripts/prepare_dataset.py:12-25``), so serve-time prompts match the
    fine-tuning distribution."""
    system = ""
    turns: List[Tuple[str, str]] = []  # (user, assistant?) pairs
    pending_user: Optional[str] = None
    for m in messages:
        role, content = m.get("role"), m.get("content", "")
        if role == "system":
            system = content
        elif role == "user":
            if pending_user is not None:
                turns.append((pending_user, ""))
            pending_user = content
        elif role == "assistant":
            turns.append((pending_user or "", content))
            pending_user = None
    if pending_user is not None:
        turns.append((pending_user, None))

    out = []
    first = True
    for user, assistant in turns:
        u = user
        if first and system:
            u = f"<<SYS>>\n{system}\n<</SYS>>\n\n{user}"
        first = False
        if assistant is None:
            out.append(f"[INST] {u} [/INST]")
        else:
            out.append(f"[INST] {u} [/INST] {assistant}")
    return " ".join(out)


class AsyncEngine:
    """Thread-safe facade: a single stepper thread drives the engine;
    callers get a per-request event queue for streaming."""

    def __init__(self, engine: InferenceEngine):
        self.engine = engine
        self.logger = get_logger()
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: Dict[str, queue.Queue] = {}
        self._seen: Dict[str, int] = {}
        self._stop = False
        self._dead = False  # set when even fault recovery failed
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dlti-engine-stepper")
        self._thread.start()

    @property
    def dead(self) -> bool:
        """True once even fault recovery failed and the stepper parked
        (every future submit raises; ``/health`` must stop reporting ok)."""
        return self._dead

    def submit(self, prompt_ids: List[int], params: SamplingParams,
               request_id: Optional[str] = None,
               q: Optional[queue.Queue] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "",
               trace_id: str = "",
               ) -> Tuple[Request, queue.Queue]:
        """Enqueue a request; returns (request, event queue).

        Queue events: ``("token", token_id, logprob)`` per generated token,
        then ``("done", finish_reason)`` — or ``("error", message)``.
        ``q`` lets a caller that pre-created the consumer queue (the
        admission gateway hands it to the HTTP handler before dispatch)
        receive events on its own instance. ``affinity_key`` rides through
        to the engine's submit (session/prefix replica stickiness — a
        no-op on a single engine); ``adapter`` names the LoRA adapter the
        request decodes under ("" = shared base).
        """
        q = q if q is not None else queue.Queue()
        with self._work:
            if self._dead:
                raise RuntimeError(
                    "engine is down (unrecoverable step fault)")
            req = self.engine.submit(
                prompt_ids, params, request_id,
                **({"affinity_key": affinity_key} if affinity_key else {}),
                **({"adapter": adapter} if adapter else {}),
                **({"trace_id": trace_id} if trace_id else {}))
            self._queues[req.request_id] = q
            self._seen[req.request_id] = 0
            self._work.notify()
        return req, q

    def shutdown(self) -> None:
        with self._work:
            self._stop = True
            self._work.notify()
        self._thread.join(timeout=10)

    def _run(self) -> None:
        while True:
            with self._work:
                while not self._stop and not self.engine.has_work:
                    if getattr(self.engine, "lifecycle_pending", False):
                        # A quarantined replica awaits its probe or a
                        # rolling reload is in flight: poll instead of
                        # parking, so the fleet's lifecycle tick runs
                        # even on an idle server (a no-work step() is
                        # just the tick). Engines without a lifecycle
                        # keep the legacy untimed park.
                        self._work.wait(timeout=0.05)
                        break
                    self._work.wait()
                if self._stop:
                    for q in self._queues.values():
                        q.put(("error", "server shutting down"))
                    return
            # Step OUTSIDE the lock: one step is a compiled-program call
            # (>1 s at large steps_per_sync), and holding the lock across
            # it serializes every HTTP submit against the device — the
            # measured 54-66% slot occupancy under load vs 94% offline
            # (results/int8_kv_7b.json). Concurrent engine.submit() only
            # appends to the waiting deque (GIL-atomic) and touches its
            # own stats key; admission consumes the deque at one point
            # inside step(), so a racing submit lands this step or next.
            try:
                self.engine.step()
            except Exception as e:  # surface engine faults to the waiters
                self.logger.exception("engine step failed")
                rec = get_recorder()
                if rec is not None:
                    # Black box first, cleanup second: abort_all below
                    # rewrites the very state (slots, waiting, stats) the
                    # forensics need.
                    from dlti_tpu.telemetry.memledger import is_oom_error
                    rec.dump(reason="oom" if is_oom_error(e)
                             else "engine_step_fault", exc=e, force=True)
                with self._work:
                    # Fail fast: abort every request the engine holds
                    # (slots + waiting; KV is NOT prefix-cache-registered
                    # — it may never have been written) and error EVERY
                    # registered consumer, including requests that
                    # finished during the failing step and any submit()
                    # that raced into the fault window (engine state is
                    # suspect; one clean 500, client may retry). The
                    # engine ends empty: no hot-loop on a persistent
                    # fault, no decoding into deleted queues.
                    try:
                        self.engine.abort_all(reason="error")
                    except Exception:
                        # Even the abort failed — bookkeeping is beyond
                        # recovery; park the stepper and fail all future
                        # submits instead of serving from a corrupt
                        # engine while /health looks ok.
                        self.logger.exception(
                            "engine abort failed; stepper parked")
                        self._dead = True
                        self._stop = True
                    for q in self._queues.values():
                        q.put(("error", f"{type(e).__name__}: {e}"))
                    self._queues.clear()
                    self._seen.clear()
                    if self._stop:
                        return
                continue
            with self._work:
                self._drain_events()

    def _drain_events(self) -> None:
        """Push tokens generated since the last step to per-request queues."""
        live = list(self.engine.slots)
        reqs = [s.request for s in live if s.request is not None]
        reqs.extend(r for r in list(self.engine.finished)
                    if r.request_id in self._queues)
        for req in reqs:
            q = self._queues.get(req.request_id)
            if q is None:
                continue
            seen = self._seen.get(req.request_id, 0)
            for i in range(seen, len(req.output_token_ids)):
                q.put(("token", req.output_token_ids[i], req.output_logprobs[i]))
            self._seen[req.request_id] = len(req.output_token_ids)
            if req.done:
                if req.finish_reason == "error":
                    # Replica failover exhausted its retries (or no
                    # survivors): this one request failed, fleet stays up.
                    q.put(("error", "request failed: replica fault, "
                                    "retries exhausted"))
                else:
                    q.put(("done", req.finish_reason))
                del self._queues[req.request_id]
                del self._seen[req.request_id]


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 8000
    model_name: str = "dlti-tpu-model"
    request_timeout_s: float = 600.0
    default_params: SamplingParams = field(default_factory=SamplingParams)
    # Admission gateway (dlti_tpu.serving.gateway): None or disabled keeps
    # the legacy direct-admission path byte-for-byte.
    gateway: Optional["GatewayConfig"] = None
    # Self-monitoring (dlti_tpu.telemetry): trace_dir feeds the on-demand
    # POST /debug/profile capture; the watchdog / flight_recorder blocks
    # enable the anomaly rules and the black-box dumps. None keeps only
    # the always-on /debug/vars sampler + /dashboard.
    telemetry: Optional["TelemetryConfig"] = None


class _Handler(BaseHTTPRequestHandler):
    """One instance per connection (ThreadingHTTPServer)."""

    server_version = "dlti-tpu"
    protocol_version = "HTTP/1.1"

    # Injected via functools-partial-style subclassing in serve().
    async_engine: AsyncEngine
    tokenizer: Tokenizer
    cfg: ServerConfig
    registry: "MetricsRegistry"
    gateway = None  # AdmissionGateway when ServerConfig.gateway enables it
    sampler = None  # TimeSeriesSampler behind /debug/vars + /dashboard
    slo = None  # SLOTracker behind /debug/slo (telemetry.slo)
    deploy = None  # DeploymentController behind /v1/deploy (serving.deploy)
    profile_lock = None  # threading.Lock guarding POST /debug/profile

    def log_message(self, fmt, *args):  # route through our logger
        get_logger().debug("http: " + fmt, *args)

    # -- helpers -------------------------------------------------------
    def _json(self, code: int, obj: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str,
               retry_after: Optional[float] = None) -> None:
        headers = None
        if retry_after is not None:
            # Integral seconds per RFC 9110 §10.2.3, never rounded to 0 —
            # a 429 whose Retry-After says "now" just invites the same
            # overload back immediately.
            headers = {"Retry-After": str(max(1, int(-(-retry_after // 1))))}
        err_type = ("rate_limit_error" if code == 429
                    else "overloaded_error" if code == 503
                    else "invalid_request_error")
        self._json(code, {"error": {"message": message, "type": err_type}},
                   headers=headers)

    def _read_body(self) -> Optional[dict]:
        try:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "invalid JSON body")
            return None

    class _StopMatcher:
        """Stateful windowed stop-string scanner shared by the stream and
        non-stream paths (one implementation of the window arithmetic, so
        the two cannot diverge): feed(text) -> (cut, safe) with the scan
        window advanced past already-scanned text."""

        def __init__(self, stops: tuple):
            self.stops = stops
            self._max = max((len(s) for s in stops), default=0)
            self._prev = 0

        def feed(self, text: str) -> tuple:
            cut, safe = _Handler._scan_stops(
                text, self.stops, start=self._prev - self._max + 1)
            self._prev = len(text)
            return cut, safe

    @staticmethod
    def _scan_stops(text: str, stops: tuple, start: int = 0) -> tuple:
        """(cut, safe): ``cut`` is the index of the earliest stop-string
        match (None if absent); ``safe`` is how much of ``text`` may be
        emitted now — held back so a stop string arriving across token
        boundaries is never partially streamed and then impossible to
        retract (the OpenAI contract excludes the stop string from the
        returned text). ``start`` windows the search: a caller scanning
        per token passes the previous length minus the longest stop, so
        the total scan work stays linear in the output length."""
        cut = None
        for s in stops:
            i = text.find(s, max(0, start))
            if i != -1 and (cut is None or i < cut):
                cut = i
        if cut is not None:
            return cut, cut
        hold = 0
        for s in stops:
            for k in range(1, len(s)):
                if text.endswith(s[:k]):
                    hold = max(hold, k)
        return None, len(text) - hold

    @staticmethod
    def _stops_from(body: dict) -> tuple:
        """OpenAI ``stop``: a string or list of strings (<= 4)."""
        stop = body.get("stop")
        if stop is None:
            return ()
        if isinstance(stop, str):
            stop = [stop]
        if (not isinstance(stop, list) or len(stop) > 4
                or not all(isinstance(s, str) and s for s in stop)):
            raise ValueError(
                "stop must be a non-empty string or a list of up to 4")
        return tuple(stop)

    def _params_from(self, body: dict) -> SamplingParams:
        # Every client-supplied field is cast here, before the request
        # reaches the engine stepper thread — a malformed value must fail
        # this one request with a 400, not error out every in-flight one.
        d = self.cfg.default_params
        stop_ids = tuple(int(t) for t in body.get("stop_token_ids", ()))
        seed = body.get("seed")
        return SamplingParams(
            temperature=float(body.get("temperature", d.temperature)),
            top_k=int(body.get("top_k", d.top_k)),
            top_p=float(body.get("top_p", d.top_p)),
            max_tokens=int(body.get("max_tokens", d.max_tokens)),
            stop_token_ids=stop_ids,
            seed=int(seed) if seed is not None else None,
            logprobs=bool(body.get("logprobs", False)),
        )

    # -- routes --------------------------------------------------------
    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/debug/vars":
            # Time-series ring snapshot (JSON): every registry scalar +
            # histogram summary, sampled on a cadence — what the
            # /dashboard page and the loadgen's end-of-run scrape read.
            if self.sampler is None:
                return self._error(404, "no time-series sampler")
            tail = None
            if query.startswith("tail="):
                try:
                    tail = max(1, int(query[5:]))
                except ValueError:
                    return self._error(400, "tail must be an integer")
            return self._json(200, self.sampler.snapshot(tail))
        if path == "/debug/slow":
            # Critical-path attribution (telemetry.ledger): the K worst
            # requests retained with their full phase timelines — "why
            # was this p99 request slow: queue, prefill, tier restore,
            # or failover?" answered without a trace viewer.
            cp = self.async_engine.engine.telemetry.critical_path
            n = None
            if query.startswith("n="):
                try:
                    n = max(1, int(query[2:]))
                except ValueError:
                    return self._error(400, "n must be an integer")
            worst = cp.slow.worst(n)
            return self._json(200, {
                "k": cp.slow.k, "retained": len(cp.slow),
                "phases": list(_REQUEST_PHASES),
                "worst": worst,
            })
        if path == "/debug/trace":
            # Chrome-trace snapshot — the process-global tracer merged
            # with every fleet worker's federated span tail (already
            # rebased onto this process's clock), one pid per source so
            # Perfetto renders a multi-process timeline. With
            # ?request_id= (optionally &latency_s=<client-observed>):
            # the merged, clock-aligned span tree for ONE request across
            # all processes, with per-leg durations and the residual.
            tracer = get_tracer()
            fed = getattr(self.async_engine.engine, "trace", None)
            if not tracer.enabled and fed is None:
                return self._error(404, "tracing disabled (start the "
                                        "server with --trace-dir)")
            qp = {}
            for part in query.split("&"):
                k, _, v = part.partition("=")
                if k:
                    qp[k] = v
            rid = qp.get("request_id", "")
            if not rid:
                if fed is not None:
                    return self._json(200, fed.merged_dict(
                        tracer if tracer.enabled else None))
                return self._json(200, tracer.to_dict())
            from dlti_tpu.telemetry.distributed_trace import (
                request_timeline,
            )

            latency = None
            if qp.get("latency_s"):
                try:
                    latency = float(qp["latency_s"])
                except ValueError:
                    return self._error(400, "latency_s must be a float")
            events = list(fed.events()) if fed is not None else []
            if tracer.enabled:
                events.extend(tracer.events())
            tl = request_timeline(events, rid, client_latency_s=latency)
            if not tl["spans"]:
                return self._error(404, f"no spans retained for request "
                                        f"{rid!r} (ring evicted, or id "
                                        f"unknown)")
            return self._json(200, tl)
        if path == "/debug/slo":
            # Declared objectives vs reality (telemetry.slo): per-
            # (objective, class) compliance, error budget remaining,
            # burn rates per alert window, breaching tiers — the JSON
            # twin of the flight dump's slo.json, and what loadgen's
            # LoadReport.slo cross-checks itself against.
            if self.slo is None:
                return self._error(404, "slo engine disabled (start the "
                                        "server with --slo)")
            return self._json(200, self.slo.to_dict())
        if path == "/debug/memory":
            # Full "where the memory lives" map (telemetry.memledger):
            # per-owner bytes, untracked/residual buckets summing to
            # bytes-in-use, activation-peak estimate, top untracked
            # arrays — the JSON twin of the flight dump's memory.json.
            ledger = getattr(self.async_engine.engine, "memledger", None)
            if ledger is None or not ledger.enabled:
                return self._error(404, "memory ledger disabled")
            return self._json(200, ledger.to_dict(top_k=8))
        if path == "/dashboard":
            # Self-contained live dashboard: inline CSS/JS polling
            # /debug/vars — watching a run needs a browser, not a
            # Prometheus deployment.
            body = render_dashboard_html().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path == "/health":
            # Load-balancer truth: a parked stepper or a draining gateway
            # must read unhealthy so traffic routes elsewhere — 200 here
            # while submits 503 kept corpses in rotation.
            body = {}
            eng = self.async_engine.engine
            counts = getattr(eng, "lifecycle_counts", None)
            if counts is not None:
                # Fleet lifecycle detail: "quarantined" replicas are
                # healing (probe pending) and expected back; "dead" ones
                # are gone for good — a balancer weighs them differently.
                body.update(counts())
            states = getattr(eng, "worker_states", None)
            if states is not None:
                # Multi-process fleet: per-worker liveness
                # (live/quarantined/draining/respawning/dead).
                body["workers"] = states()
            if self.async_engine.dead:
                self._json(503, {"status": "dead", **body})
            elif self.gateway is not None and self.gateway.draining:
                self._json(503, {"status": "draining", **body})
            elif states is not None and not any(
                    s == "live" for s in body["workers"].values()):
                # No worker live: unhealthy — but a respawn may be
                # imminent, so advertise its backoff as Retry-After
                # (a degraded fleet with ANY live worker stays 200).
                headers = {}
                ra = getattr(eng, "respawn_retry_after_s", 0.0)
                if ra > 0:
                    headers["Retry-After"] = str(max(1, int(-(-ra // 1))))
                self._json(503, {"status": "no_live_workers", **body},
                           headers=headers)
            else:
                self._json(200, {"status": "ok", **body})
        elif self.path == "/stats":
            # Raw engine counters/gauges + request-latency histogram
            # summaries (count/sum/mean/p50/p90/p99), all served from the
            # shared MetricsRegistry.
            self._json(200, self.registry.stats_dict())
        elif self.path == "/metrics":
            # Prometheus text exposition (vLLM-parity observability),
            # rendered from the shared MetricsRegistry: the legacy
            # dlti_<stat> counters/gauges byte-for-byte, plus the
            # request-lifecycle histograms (TTFT/TPOT/queue time).
            body = self.registry.render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/v1/deploy":
            # Continuous-delivery state (serving.deploy): incumbent
            # step/digest, canary in flight, refused steps, gate verdict
            # of the last candidate — the JSON twin of the flight dump's
            # deploy.json.
            if self.deploy is None:
                return self._error(404, "deploy controller disabled "
                                        "(start the server with "
                                        "--deploy-watch)")
            self._json(200, self.deploy.status())
        elif self.path == "/v1/models":
            self._json(200, {"object": "list", "data": [{
                "id": self.cfg.model_name, "object": "model",
                "owned_by": "dlti_tpu",
            }]})
        elif self.path == "/v1/adapters":
            # Registered adapter names (process-global catalog) — what a
            # client may put in X-Adapter right now.
            from dlti_tpu.serving.adapters import get_catalog

            self._json(200, {"object": "list",
                             "data": get_catalog().names()})
        else:
            self._error(404, f"no route {self.path}")

    def do_POST(self):
        if self.path == "/v1/completions":
            self._completions(chat=False)
        elif self.path == "/v1/chat/completions":
            self._completions(chat=True)
        elif self.path == "/v1/adapters":
            self._register_adapter()
        elif self.path == "/v1/reload":
            self._reload_weights()
        elif self.path == "/v1/deploy":
            self._deploy_control()
        elif self.path == "/debug/profile":
            self._profile()
        else:
            self._error(404, f"no route {self.path}")

    def _reload_weights(self) -> None:
        """Zero-downtime rolling weight upgrade:
        ``POST /v1/reload {"directory": d}`` where ``d`` is a params
        export written by ``checkpoint.store.save_pytree`` (the same
        artifact class adapters hot-load from). The fleet hot-swaps the
        weights one replica at a time — drain via live KV migration,
        rebuild, canary, reinstate — so clients never see an error. The
        artifact is digest-verified on the stepper thread before any
        replica swaps; 409 while a roll is already in progress; 400 when
        the engine has no lifecycle support (single-engine servers
        restart instead)."""
        body = self._read_body()
        if body is None:
            return
        directory = str(body.get("directory", "") or "")
        if not directory:
            return self._error(400, "directory is required")
        if not os.path.isfile(os.path.join(directory, "MANIFEST.json")):
            return self._error(
                400, f"{directory!r} is not a checkpoint-store params "
                     f"export (no MANIFEST.json)")
        request_reload = getattr(self.async_engine.engine,
                                 "request_reload", None)
        if request_reload is None:
            return self._error(
                400, "engine has no replica lifecycle (rolling reload "
                     "needs a replicated fleet; restart single-engine "
                     "servers instead)")
        from dlti_tpu.checkpoint.store import (
            load_pytree, manifest_digest, verify_pytree_dir,
        )

        def _provider():
            # Runs once on the stepper thread: digest-verified load — a
            # corrupt artifact aborts the roll before any replica swaps.
            return load_pytree(directory, verify=True)

        # Pin the digest NOW, then re-verify immediately before EVERY
        # per-replica swap: an artifact corrupted mid-roll (bit rot, a
        # re-export racing the roll) aborts the remaining swaps instead
        # of shipping different bytes to different replicas.
        expect_digest = manifest_digest(directory)

        def _verify() -> bool:
            if manifest_digest(directory) != expect_digest:
                return False
            return verify_pytree_dir(directory)[0]

        if not request_reload(_provider, verify=_verify):
            return self._error(409, "a rolling reload is already in "
                                    "progress")
        with self.async_engine._work:
            self.async_engine._work.notify()  # wake an idle stepper
        self._json(200, {"status": "reloading", "directory": directory})

    def _deploy_control(self) -> None:
        """Operator switch for the continuous-delivery pipeline:
        ``POST /v1/deploy {"enabled": bool}``. Disabling cancels any
        in-flight canary without judging it (the step stays eligible);
        enabling resumes the watch loop. 404 when no controller is
        wired (start the server with ``--deploy-watch``)."""
        if self.deploy is None:
            return self._error(404, "deploy controller disabled (start "
                                    "the server with --deploy-watch)")
        body = self._read_body()
        if body is None:
            return
        if "enabled" not in body:
            return self._error(400, "enabled is required")
        self.deploy.set_enabled(bool(body["enabled"]))
        self._json(200, self.deploy.status())

    def _register_adapter(self) -> None:
        """Hot-register a trained adapter checkpoint with zero restart:
        ``POST /v1/adapters {"name": n, "directory": d}``. The directory
        is digest-verified through the checkpoint store before the name
        exists; a corrupt checkpoint is quarantined and 400s here — the
        name stays unknown, so completions keep 404ing it."""
        body = self._read_body()
        if body is None:
            return
        name = str(body.get("name", "") or "")
        directory = str(body.get("directory", "") or "")
        if not name or not directory:
            return self._error(400, "name and directory are required")
        from dlti_tpu.serving.adapters import AdapterError, register_adapter

        try:
            register_adapter(name, directory)
        except AdapterError as e:
            return self._error(400, str(e))
        self._json(200, {"object": "adapter", "name": name,
                         "directory": directory})

    def _profile(self) -> None:
        """On-demand ``jax.profiler`` capture around the live engine:
        ``POST /debug/profile {"seconds": s}`` writes a device trace into
        the configured ``--trace-dir`` (the trainer has its profile
        window flags; this is serving's equivalent, without a restart).
        One capture at a time — concurrent requests get 409."""
        body = self._read_body()
        if body is None:
            return
        trace_dir = (self.cfg.telemetry.trace_dir
                     if self.cfg.telemetry is not None else "")
        if not trace_dir:
            return self._error(
                400, "profiling needs a trace dir: start the server with "
                     "--trace-dir")
        try:
            seconds = float(body.get("seconds", 3.0))
        except (TypeError, ValueError):
            return self._error(400, "seconds must be a number")
        if not 0.0 < seconds <= 120.0:
            return self._error(400, "seconds must be in (0, 120]")
        if self.profile_lock is None or not self.profile_lock.acquire(
                blocking=False):
            # jax.profiler is process-global: a second start_trace would
            # raise (or corrupt the first capture), so refuse loudly.
            return self._error(409, "a profile capture is already running")
        try:
            import jax

            out_dir = os.path.join(trace_dir, "serve_profile")
            t0 = time.monotonic()
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            self._json(200, {"status": "ok", "trace_dir": out_dir,
                             "seconds": round(time.monotonic() - t0, 3)})
        except Exception as e:  # profiler backends vary; fail this request
            self._error(500, f"profiler: {type(e).__name__}: {e}")
        finally:
            self.profile_lock.release()

    # -- completion core ----------------------------------------------
    def _completions(self, chat: bool) -> None:
        body = self._read_body()
        if body is None:
            return
        tok = self.tokenizer
        if chat:
            messages = body.get("messages")
            if not isinstance(messages, list) or not messages:
                return self._error(400, "messages must be a non-empty list")
            prompt = llama2_chat_prompt(messages)
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            if not isinstance(prompt, str) or not prompt:
                return self._error(400, "prompt must be a non-empty string")

        prompt_ids = tok.encode(prompt, add_bos=True)
        try:
            params = self._params_from(body)
            stops = self._stops_from(body)
        except (TypeError, ValueError) as e:
            return self._error(400, f"invalid sampling parameter: {e}")
        max_len = self.async_engine.engine.cfg.max_model_len
        if len(prompt_ids) >= max_len:
            return self._error(400, f"prompt has {len(prompt_ids)} tokens; "
                                    f"max_model_len is {max_len}")

        try:
            n = int(body.get("n", 1))
        except (TypeError, ValueError):
            return self._error(400, "n must be an integer")
        if not 1 <= n <= self.async_engine.engine.cfg.max_seqs:
            return self._error(
                400, f"n must be in [1, {self.async_engine.engine.cfg.max_seqs}]")
        if n > 1 and body.get("stream"):
            return self._error(400, "n > 1 does not support stream=true")
        if n > 1 and (params.temperature == 0.0 or params.top_k == 1):
            return self._error(
                400, "n > 1 with deterministic sampling (temperature=0 or "
                     "top_k=1) would return n identical choices; relax the "
                     "sampling or drop n")

        # Multi-LoRA routing: X-Adapter header first (works with AND
        # without a gateway), else the gateway's tenant→adapter map.
        # Unknown names 404 HERE, before any queue/slot is consumed —
        # the engine only ever sees catalog-registered adapters.
        adapter = str(self.headers.get("X-Adapter", "") or "").strip()
        if adapter:
            from dlti_tpu.serving.adapters import get_catalog

            if adapter not in get_catalog():
                return self._error(
                    404, f"unknown adapter {adapter!r}: register it via "
                         "POST /v1/adapters first")

        # Admission metadata (gateway only): tenant from headers, priority
        # class + queued-deadline from the body. Validated before submit so
        # a bad value 400s this request, same contract as sampling params.
        tenant = priority = None
        deadline_s = 0.0
        affinity_key = None
        if self.gateway is not None:
            tenant = tenant_from_headers(
                self.headers, self.gateway.cfg.default_tenant)
            priority = str(body.get("priority")
                           or self.headers.get("X-Priority")
                           or "interactive")
            if priority not in PRIORITIES:
                return self._error(
                    400, f"priority must be one of {PRIORITIES}")
            try:
                deadline_s = float(body.get("deadline_s", 0) or 0)
            except (TypeError, ValueError):
                return self._error(400, "deadline_s must be a number")
            if not adapter:
                adapter = self.gateway.adapter_for(tenant)
            if self.gateway.cfg.affinity:
                # Cache-affinity routing: a session (X-Session) or
                # hashed prompt-prefix key makes repeat traffic land on
                # the replica whose prefix cache is already warm. The
                # adapter id is part of the key: adapter A's warm KV is
                # useless to adapter B.
                affinity_key = affinity_key_from(
                    self.headers, prompt_ids,
                    self.gateway.cfg.affinity_prefix_tokens,
                    adapter=adapter)

        def _submit(p_ids, p, rid_):
            if self.gateway is not None:
                return self.gateway.submit(
                    p_ids, p, rid_, tenant=tenant, priority=priority,
                    deadline_s=deadline_s, affinity_key=affinity_key,
                    adapter=adapter)
            return self.async_engine.submit(
                p_ids, p, rid_,
                **({"adapter": adapter} if adapter else {}))

        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        created = int(time.time())
        try:
            if n == 1:
                req, q = _submit(prompt_ids, params, rid)
            else:
                # n choices = n engine requests decoding CONCURRENTLY in
                # the continuous batch (they share prefill via the prefix
                # cache). A user seed derives per-choice seeds so the
                # response stays reproducible without n identical samples.
                subs = []
                try:
                    for i in range(n):
                        p_i = params if params.seed is None else \
                            dataclasses.replace(params, seed=params.seed + i)
                        subs.append(_submit(prompt_ids, p_i, f"{rid}-{i}"))
                except Exception:
                    # A submit failed mid-loop (e.g. the stepper parked
                    # between choices): early-cancel every choice already
                    # submitted, or they decode to max_tokens into queues
                    # nobody reads — the orphan burn the disconnect/stop
                    # cancels exist to prevent.
                    for other, _ in subs:
                        other.cancel_requested = True
                    raise
        except AdmissionError as e:  # gateway refusal: 429/503 + Retry-After
            return self._error(e.status, e.message, retry_after=e.retry_after)
        except ValueError as e:
            return self._error(400, str(e))
        except RuntimeError as e:  # engine parked after unrecoverable fault
            return self._error(503, str(e))

        if body.get("stream"):
            self._stream_response(req, q, chat, created, stops)
        elif n == 1:
            self._full_response(req, q, chat, created, stops)
        else:
            self._multi_response(subs, rid, chat, created, stops)

    def _collect(self, q: queue.Queue, req: Optional[Request] = None):
        """Yield events until done/error/reject/timeout.

        On timeout the request is early-cancelled first (same contract as
        the disconnect/stop cancels): without it a timed-out request kept
        decoding to max_tokens into a queue nobody reads, burning a slot
        live requests were waiting for."""
        deadline = time.monotonic() + self.cfg.request_timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if req is not None:
                    req.cancel_requested = True
                yield ("error", "request timed out")
                return
            try:
                ev = q.get(timeout=min(remaining, 1.0))
            except queue.Empty:
                continue
            yield ev
            if ev[0] in ("done", "error", "reject"):
                return

    def _collect_choice(self, req: Request, q: queue.Queue,
                        stops: tuple) -> tuple:
        """Drain one non-streaming request to completion: returns
        ((token_ids, logprobs, text, finish), (status, error_message))
        with exactly one of the pair set. THE one
        collect/stop-scan/truncate implementation for the n==1 and n>1
        paths, so they cannot diverge. Stop STRINGS (OpenAI `stop`;
        token-boundary-agnostic, so matched on detokenized text here, not
        in the engine) request early cancel and keep draining until the
        engine's done event so the slot release is observed; the scan is
        windowed past already-scanned text."""
        token_ids: List[int] = []
        logprobs: List[float] = []
        finish = "stop"
        cut = None
        matcher = self._StopMatcher(stops)
        for ev in self._collect(q, req):
            if ev[0] == "token":
                token_ids.append(ev[1])
                logprobs.append(ev[2])
                if stops and cut is None:
                    cut, _ = matcher.feed(self.tokenizer.decode(token_ids))
                    if cut is not None:
                        req.cancel_requested = True
            elif ev[0] == "done":
                finish = ev[1]
            elif ev[0] == "reject":  # gateway shed (e.g. queued deadline)
                # Pass any retry-after hint through to _error (the shed
                # tuple grew a 4th element; older 3-element producers
                # still work).
                return None, tuple(ev[1:])
            else:
                return None, (500, ev[1])
        text = self.tokenizer.decode(token_ids)
        if cut is not None:
            text, finish = text[:cut], "stop"
        return (token_ids, logprobs, text, finish), None

    @staticmethod
    def _phases_of(req) -> Optional[dict]:
        """Server-side critical-path breakdown of a finished request
        (telemetry.ledger): ``{"total_s", "ttft_s", <phase>: s, ...}``.
        None when the engine request isn't resolvable/finished (so a
        refusal path never grows a bogus breakdown)."""
        eng_req = getattr(req, "_req", None) or req
        if getattr(eng_req, "finish_time", None) is None:
            return None
        try:
            b = request_breakdown(eng_req)
        except Exception:  # attribution must never fail a response
            return None
        return {"total_s": b["total_s"], "ttft_s": b["ttft_s"],
                **b["phases"]}

    def _full_response(self, req: Request, q: queue.Queue, chat: bool,
                       created: int, stops: tuple = ()) -> None:
        got, err = self._collect_choice(req, q, stops)
        if err is not None:
            return self._error(*err)
        token_ids, logprobs, text, finish = got
        usage = {
            "prompt_tokens": len(req.prompt_token_ids),
            "completion_tokens": len(token_ids),
            "total_tokens": len(req.prompt_token_ids) + len(token_ids),
        }
        if chat:
            choice = {"index": 0, "message": {"role": "assistant", "content": text},
                      "finish_reason": finish}
            obj = "chat.completion"
        else:
            choice = {"index": 0, "text": text, "finish_reason": finish}
            obj = "text_completion"
        if req.params.logprobs:
            choice["logprobs"] = {"token_logprobs": logprobs,
                                  "tokens": token_ids}
        out = {
            "id": req.request_id, "object": obj, "created": created,
            "model": self.cfg.model_name, "choices": [choice], "usage": usage,
        }
        phases = self._phases_of(req)
        if phases is not None:
            # Server-side phase attribution (gateway queue, engine queue,
            # tier restore, prefill, failover, decode): lets a client —
            # and the loadgen — decompose the latency it observed.
            out["phases"] = phases
        eng_req = getattr(req, "_req", None) or req
        # Lifecycle visibility: how many times this request was live-
        # migrated (paged-KV handoff mid-decode) or failover-resubmitted
        # — rolling-restart drills assert "zero errors AND the migrations
        # actually happened".
        out["migrations"] = getattr(eng_req, "num_migrations", 0)
        out["retries"] = getattr(eng_req, "num_retries", 0)
        # Trace context: lets the client (and the loadgen) fetch the
        # merged cross-process timeline via /debug/trace?request_id=.
        out["trace_id"] = getattr(eng_req, "trace_id", "")
        self._json(200, out)

    def _multi_response(self, subs: list, rid: str, chat: bool,
                        created: int, stops: tuple = ()) -> None:
        """OpenAI ``n`` > 1: the n requests decode concurrently in the
        continuous batch (submitted before this runs); collect each in
        turn — later queues buffer while earlier ones drain."""
        choices = []
        total_completion = 0
        prompt_tokens = len(subs[0][0].prompt_token_ids)
        for i, (req, q) in enumerate(subs):
            got, err = self._collect_choice(req, q, stops)
            if err is not None:
                # One choice failed/timed out: early-cancel every other
                # still-running choice before erroring — without this the
                # remaining n-1 requests decode to max_tokens into queues
                # nobody reads (the orphan-burn disconnect-cancel exists
                # to prevent).
                for other, _ in subs:
                    other.cancel_requested = True
                return self._error(*err)
            token_ids, logprobs, text, finish = got
            total_completion += len(token_ids)
            if chat:
                choice = {"index": i,
                          "message": {"role": "assistant", "content": text},
                          "finish_reason": finish}
            else:
                choice = {"index": i, "text": text, "finish_reason": finish}
            if req.params.logprobs:
                choice["logprobs"] = {"token_logprobs": logprobs,
                                      "tokens": token_ids}
            choices.append(choice)
        self._json(200, {
            "id": rid,
            "object": "chat.completion" if chat else "text_completion",
            "created": created, "model": self.cfg.model_name,
            "choices": choices,
            "usage": {"prompt_tokens": prompt_tokens,
                      "completion_tokens": total_completion,
                      "total_tokens": prompt_tokens + total_completion},
        })

    def _stream_response(self, req: Request, q: queue.Queue, chat: bool,
                         created: int, stops: tuple = ()) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(data: str) -> None:
            payload = f"data: {data}\n\n".encode()
            self.wfile.write(f"{len(payload):x}\r\n".encode() + payload + b"\r\n")
            self.wfile.flush()

        obj = "chat.completion.chunk" if chat else "text_completion"
        # Incremental detokenization: decode the full id list and emit the
        # suffix, so multi-token unicode never splits mid-character.
        token_ids: List[int] = []
        emitted = ""
        finish = None
        try:
            if chat:
                chunk(json.dumps({
                    "id": req.request_id, "object": obj, "created": created,
                    "model": self.cfg.model_name,
                    "choices": [{"index": 0, "delta": {"role": "assistant"},
                                 "finish_reason": None}]}))
            cancelled = False
            matcher = self._StopMatcher(stops)
            for ev in self._collect(q, req):
                if ev[0] == "token":
                    if cancelled:
                        # Stop already matched: drain (the engine finishes
                        # within one decode window of the cancel flag) so
                        # the final usage chunk reads a settled request.
                        continue
                    token_ids.append(ev[1])
                    text = self.tokenizer.decode(token_ids)
                    if stops:
                        # Stop strings: emit only up to the earliest match
                        # (the stop string itself is never streamed), and
                        # hold back any tail that could be the start of a
                        # match arriving across token boundaries.
                        cut, safe = matcher.feed(text)
                        if cut is not None:
                            delta = text[len(emitted):cut]
                            emitted += delta
                            if delta:
                                key = "delta" if chat else "text"
                                val = {"content": delta} if chat else delta
                                chunk(json.dumps({
                                    "id": req.request_id, "object": obj,
                                    "created": created,
                                    "model": self.cfg.model_name,
                                    "choices": [{"index": 0, key: val,
                                                 "finish_reason": None}]}))
                            req.cancel_requested = True
                            cancelled = True
                            continue
                        text = text[:safe]
                    delta = text[len(emitted):]
                    emitted += delta
                    if not delta:
                        continue  # partial unicode / held-back stop prefix
                    key = "delta" if chat else "text"
                    val = {"content": delta} if chat else delta
                    chunk(json.dumps({
                        "id": req.request_id, "object": obj, "created": created,
                        "model": self.cfg.model_name,
                        "choices": [{"index": 0, key: val, "finish_reason": None}]}))
                elif ev[0] == "done":
                    finish = "stop" if cancelled else ev[1]
                    if stops and not cancelled:
                        # Flush the held-back tail: the request ended
                        # without a stop match, so the conservative
                        # hold-back (a possible stop prefix) is real
                        # output the client must still receive.
                        tail = self.tokenizer.decode(token_ids)[len(emitted):]
                        if tail:
                            emitted += tail
                            key = "delta" if chat else "text"
                            val = {"content": tail} if chat else tail
                            chunk(json.dumps({
                                "id": req.request_id, "object": obj,
                                "created": created,
                                "model": self.cfg.model_name,
                                "choices": [{"index": 0, key: val,
                                             "finish_reason": None}]}))
                else:
                    # ("error", msg) or a gateway ("reject", status, msg):
                    # headers are already on the wire, so the refusal
                    # arrives as a terminal SSE error frame.
                    chunk(json.dumps({"error": {"message": ev[-1]}}))
                    break
            if finish is not None:
                key = "delta" if chat else "text"
                val = {} if chat else ""
                final = {
                    "id": req.request_id, "object": obj, "created": created,
                    "model": self.cfg.model_name,
                    "choices": [{"index": 0, key: val, "finish_reason": finish}],
                    # Token-accurate usage in the final chunk (OpenAI
                    # stream_options.include_usage semantics, always on):
                    # SSE event count != token count (multi-step decode
                    # batches tokens per sync; detokenization can emit
                    # empty deltas), so load tests need this for honest
                    # streaming throughput numbers.
                    "usage": {
                        "prompt_tokens": len(req.prompt_token_ids),
                        "completion_tokens": len(req.output_token_ids),
                        "total_tokens": len(req.prompt_token_ids)
                        + len(req.output_token_ids),
                    }}
                phases = self._phases_of(req)
                if phases is not None:
                    final["phases"] = phases
                eng_req = getattr(req, "_req", None) or req
                final["migrations"] = getattr(eng_req, "num_migrations", 0)
                final["retries"] = getattr(eng_req, "num_retries", 0)
                final["trace_id"] = getattr(eng_req, "trace_id", "")
                chunk(json.dumps(final))
            chunk("[DONE]")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            # Early-cancel the orphaned request: without this the engine
            # keeps burning decode windows into a queue nobody reads,
            # up to max_tokens, while live requests wait for the slot.
            req.cancel_requested = True
            get_logger().info("client disconnected mid-stream: %s", req.request_id)


def make_server(engine: InferenceEngine, tokenizer: Tokenizer,
                cfg: Optional[ServerConfig] = None, *,
                deploy=None,
                ) -> Tuple[ThreadingHTTPServer, AsyncEngine]:
    """Build (but don't start) the HTTP server; caller runs serve_forever().

    When ``cfg.gateway`` is set and enabled, an
    :class:`~dlti_tpu.serving.gateway.AdmissionGateway` is built between
    the handlers and the engine (reachable as ``httpd.gateway``); left
    unset, admission is the legacy direct path.

    ``deploy`` is an optional
    :class:`~dlti_tpu.serving.deploy.DeploymentController` (built by
    ``scripts/serve.py --deploy-watch``): it gains the ``/v1/deploy``
    surface, a ``deploy.json`` section in flight dumps, and its thread is
    started here / stopped by :func:`serve`'s shutdown path.
    """
    cfg = cfg or ServerConfig()
    async_engine = AsyncEngine(engine)
    registry = build_registry(async_engine)
    # Name this process's row in merged Perfetto exports — fleet workers
    # label themselves "worker<N>"; the front process is "supervisor"
    # when it runs a fleet (it federates worker span tails) and plain
    # "server" otherwise.
    get_tracer().process_label = (
        "supervisor" if getattr(engine, "trace", None) is not None
        else "server")
    gateway = None
    if cfg.gateway is not None and cfg.gateway.enabled:
        gateway = AdmissionGateway(async_engine, cfg.gateway, registry)

    # Self-monitoring layer (dlti_tpu.telemetry): the time-series ring is
    # always on (it is what /debug/vars and /dashboard serve — one
    # registry read per interval); watchdog and flight recorder follow
    # cfg.telemetry.
    tcfg = cfg.telemetry
    wcfg = tcfg.watchdog if tcfg is not None else None
    sampler = TimeSeriesSampler(
        interval_s=wcfg.interval_s if wcfg is not None else 1.0,
        registry=registry)
    if getattr(engine, "memledger", None) is not None \
            and engine.memledger.enabled:
        # Ledger scalars into the ring: /debug/vars + /dashboard get the
        # "where the memory lives" series, and the watchdog's
        # hbm_pressure rule reads hbm_headroom_frac from here.
        sampler.add_source(engine.memledger.scalars)
    # SLO engine (telemetry.slo): objectives over the SLIs the registry
    # already carries — lifecycle histograms (bucket-snapped latency
    # cuts), gateway admission counters (per-class availability). The
    # tracker is pull-driven: the sampler's interval pull doubles as its
    # evaluation cadence (ring series for /dashboard), the watchdog pulls
    # active_burns, /debug/slo pulls to_dict.
    slo_tracker = None
    if tcfg is not None and getattr(tcfg, "slo", None) is not None:
        from dlti_tpu.telemetry.slo import build_tracker as _build_slo

        classes = ()
        if gateway is not None:
            from dlti_tpu.serving.gateway import PRIORITIES

            classes = PRIORITIES
        slo_tracker = _build_slo(
            tcfg.slo, telemetry=engine.telemetry,
            stats_fn=registry.stats_dict if gateway is not None else None,
            classes=classes)
        if slo_tracker is not None:
            sampler.add_source(slo_tracker.scalars)
    sampler.start()
    recorder = None
    if tcfg is not None and tcfg.flight_recorder.enabled:
        import dataclasses as _dc

        fcfg = tcfg.flight_recorder
        if not get_tracer().enabled:
            # The black box needs a span tail even when no --trace-dir
            # export was requested (same rationale as the trainer's).
            from dlti_tpu.telemetry import configure_tracer

            configure_tracer(enabled=True, capacity=tcfg.trace_capacity)
        recorder = FlightRecorder(
            fcfg.dir, sampler=sampler, config=_dc.asdict(cfg),
            max_spans=fcfg.max_spans, timeseries_tail=fcfg.timeseries_tail,
            keep=fcfg.keep)
        recorder.add_metrics_source(registry.stats_dict)
        if getattr(engine, "memledger", None) is not None \
                and engine.memledger.enabled:
            recorder.add_memory_source(engine.memledger.to_dict)
        if slo_tracker is not None:
            recorder.add_slo_source(slo_tracker.to_dict)
        if deploy is not None:
            recorder.add_deploy_source(deploy.to_dict)
        recorder.note(role="serving", model=cfg.model_name)
        install_recorder(recorder)
    watchdog = None
    if wcfg is not None and wcfg.enabled:
        watchdog = AnomalyWatchdog(wcfg, sampler, slo=slo_tracker)
        if recorder is not None:
            recorder.add_context_source(
                lambda: {"watchdog_alerts": list(watchdog.alerts)})
        watchdog.start()

    if deploy is not None:
        deploy.start()

    handler = type("BoundHandler", (_Handler,), {
        "async_engine": async_engine, "tokenizer": tokenizer, "cfg": cfg,
        "registry": registry, "gateway": gateway, "sampler": sampler,
        "slo": slo_tracker, "deploy": deploy,
        "profile_lock": threading.Lock(),
    })
    httpd = ThreadingHTTPServer((cfg.host, cfg.port), handler)
    httpd.daemon_threads = True
    httpd.gateway = gateway
    httpd.sampler = sampler
    httpd.watchdog = watchdog
    httpd.flight_recorder = recorder
    httpd.slo = slo_tracker
    httpd.deploy = deploy
    return httpd, async_engine


def serve(engine: InferenceEngine, tokenizer: Tokenizer,
          cfg: Optional[ServerConfig] = None, *, deploy=None) -> None:
    """Blocking entry point (used by ``scripts/serve.py``)."""
    cfg = cfg or ServerConfig()
    httpd, async_engine = make_server(engine, tokenizer, cfg,
                                      deploy=deploy)
    gateway = httpd.gateway
    get_logger().info("serving on http://%s:%d (model=%s)",
                      cfg.host, cfg.port, cfg.model_name)
    # SIGTERM (k8s eviction, orchestrator `kill`) gets the same clean
    # path as Ctrl-C: unblock serve_forever so the finally drains the
    # stepper and closes the socket instead of dying mid-decode. With a
    # gateway the path is a GRACEFUL DRAIN: new admissions 503, /health
    # flips to "draining" (the LB stops routing), queued + in-flight
    # requests finish (bounded by drain_grace_s), then the server exits.
    # httpd.shutdown() must run OFF the serving thread (it joins it).
    import signal as _signal

    def _graceful_stop():
        if httpd.flight_recorder is not None:
            # SIGTERM is a trigger too: the black box records what was
            # in flight when the orchestrator pulled the plug.
            httpd.flight_recorder.dump(reason="sigterm_drain", force=True)
        if gateway is not None:
            gateway.drain()
            gateway.wait_idle(gateway.cfg.drain_grace_s)
        httpd.shutdown()

    def _on_term(signum, frame):
        threading.Thread(target=_graceful_stop, daemon=True).start()

    prev_handler = None
    installed = False
    try:
        prev_handler = _signal.signal(_signal.SIGTERM, _on_term)
        installed = True
    except ValueError:
        pass  # not the main thread (embedded use): SIGTERM stays default
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if installed:
            # Restore (trainer.py's pattern): a stale handler closing
            # over the dead httpd would otherwise swallow every later
            # SIGTERM for the process lifetime.
            _signal.signal(_signal.SIGTERM,
                           prev_handler or _signal.SIG_DFL)
        if httpd.deploy is not None:
            # Stop the delivery pipeline FIRST: a promotion racing the
            # drain would roll replicas while the stepper is parking.
            httpd.deploy.stop()
        if gateway is not None:
            gateway.shutdown()
        if httpd.watchdog is not None:
            httpd.watchdog.stop()
        httpd.sampler.stop()
        if httpd.flight_recorder is not None and \
                get_recorder() is httpd.flight_recorder:
            install_recorder(None)
        async_engine.shutdown()
        httpd.server_close()
