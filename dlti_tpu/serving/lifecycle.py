"""Replica lifecycle: the serving fleet's self-healing state machine.

Training got evict → reshape → resume → rejoin in the elastic supervisor;
this is the serving counterpart. Instead of ``_fail_replica`` marking a
replica dead forever, each replica walks a small state machine:

    live → quarantined → probing → live
                 ↘ (flap breaker) → evicted

* A fault quarantines the replica (its engine is torn down and rebuilt
  from known-good weights by :class:`~dlti_tpu.serving.replicas.ReplicatedEngine`).
* After an exponential probation delay (``probation_initial_s *
  probation_backoff**failures``, capped at ``probation_max_s``) the
  replica is probed: a short greedy canary generation on the rebuilt
  engine, checked against a digest pinned at fleet construction (and
  re-pinned on weight reload). A passing probe reinstates; a failing one
  re-quarantines with a longer probation.
* The flap breaker evicts permanently: more than ``flap_max_cycles``
  quarantines inside ``flap_window_s`` means the replica is genuinely
  bad (flaky interconnect, cooked HBM) and re-probing it only churns the
  fleet — the eviction bumps the flaps counter, which the watchdog's
  ``replica_flap`` rule turns into an alert.

``draining`` is the planned-exit state (rolling reload, chaos
``preempt``): the replica stops taking dispatch while its in-flight
decodes migrate to survivors over the paged-KV handoff path.

The class is pure bookkeeping on an injectable clock — no engine calls,
no threads — so the state machine is unit-testable on a fake clock; the
owning :class:`~dlti_tpu.serving.replicas.ReplicatedEngine` performs the
actual rebuild/probe/migration work from its stepper thread.
"""

from __future__ import annotations

import hashlib
import time
from collections import deque
from typing import Callable, Dict, List, Sequence

from dlti_tpu.config import ReplicaLifecycleConfig
from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Name-stability contract (pinned in tests/test_bench_contract.py).
LIFECYCLE_METRIC_NAMES = (
    "dlti_replica_lifecycle_quarantines_total",
    "dlti_replica_lifecycle_reinstates_total",
    "dlti_replica_lifecycle_flaps_total",
    "dlti_replica_lifecycle_migrations_total",
    "dlti_replica_lifecycle_migration_fallbacks_total",
    "dlti_replica_state",
)

# Module-level metrics (the checkpoint-store / watchdog pattern): every
# fleet in the process shares them; the server registry registers them
# for /metrics exposition.
quarantines_total = Counter(
    LIFECYCLE_METRIC_NAMES[0],
    help="replicas quarantined after a fault or planned preemption")
reinstates_total = Counter(
    LIFECYCLE_METRIC_NAMES[1],
    help="quarantined replicas reinstated after a passing canary probe")
flaps_total = Counter(
    LIFECYCLE_METRIC_NAMES[2],
    help="replicas permanently evicted by the flap breaker")
migrations_total = Counter(
    LIFECYCLE_METRIC_NAMES[3],
    help="in-flight decodes moved to a survivor via paged-KV handoff")
migration_fallbacks_total = Counter(
    LIFECYCLE_METRIC_NAMES[4],
    help="drain migrations that fell back to failover re-prefill")
replica_state_gauge = Gauge(
    LIFECYCLE_METRIC_NAMES[5],
    help="per-replica lifecycle state code "
         "(0=live 1=quarantined 2=probing 3=draining 4=evicted)")

LIVE, QUARANTINED, PROBING, DRAINING, EVICTED = STATES = (
    "live", "quarantined", "probing", "draining", "evicted")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}


def canary_digest(tokens: Sequence[int]) -> str:
    """Stable digest of a canary generation's token ids (the reinstate
    gate compares the rebuilt replica's output against the pinned one)."""
    h = hashlib.sha256()
    for t in tokens:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


class ReplicaLifecycle:
    """Per-replica state machine + probation/flap bookkeeping.

    All methods are cheap and non-blocking; the owner calls them from
    its stepper thread. ``clock`` is injectable for fake-clock tests
    (the :class:`~dlti_tpu.telemetry.watchdog.AnomalyWatchdog` pattern).
    """

    def __init__(self, cfg: ReplicaLifecycleConfig, n_replicas: int, *,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self.clock = clock
        self._state: Dict[int, str] = {i: LIVE for i in range(n_replicas)}
        self._probe_failures: Dict[int, int] = {i: 0 for i in range(n_replicas)}
        self._next_probe_t: Dict[int, float] = {}
        # Quarantine entry timestamps inside the flap window, per replica.
        self._flap_times: Dict[int, deque] = {
            i: deque() for i in range(n_replicas)}
        # Local counters (aggregated into ReplicatedEngine.stats and the
        # postmortem dump); the module Counters feed /metrics.
        self.counters = {"quarantines": 0, "reinstates": 0, "flaps": 0,
                         "migrations": 0, "migration_fallbacks": 0}
        for i in range(n_replicas):
            self._publish(i)

    # ------------------------------------------------------------------
    def _publish(self, idx: int) -> None:
        replica_state_gauge.labels(replica=str(idx)).set(
            _STATE_CODE[self._state[idx]])

    def _probation_s(self, idx: int) -> float:
        c = self.cfg
        return min(c.probation_max_s,
                   c.probation_initial_s
                   * (c.probation_backoff ** self._probe_failures[idx]))

    # ------------------------------------------------------------------
    def state(self, idx: int) -> str:
        return self._state[idx]

    def states(self) -> Dict[int, str]:
        return dict(self._state)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in STATES}
        for s in self._state.values():
            out[s] += 1
        return out

    # ------------------------------------------------------------------
    def on_fault(self, idx: int) -> str:
        """A replica faulted (or finished a planned drain). Returns the
        state it landed in: ``quarantined``, or ``evicted`` when the flap
        breaker tripped."""
        if self._state[idx] == EVICTED:
            return EVICTED
        now = self.clock()
        window = self._flap_times[idx]
        window.append(now)
        while window and now - window[0] > self.cfg.flap_window_s:
            window.popleft()
        if len(window) > self.cfg.flap_max_cycles:
            self._state[idx] = EVICTED
            self.counters["flaps"] += 1
            flaps_total.inc()
            self._publish(idx)
            logger.error(
                "replica %d evicted by flap breaker: %d quarantines inside "
                "%.0fs window (limit %d)", idx, len(window),
                self.cfg.flap_window_s, self.cfg.flap_max_cycles)
            return EVICTED
        self._state[idx] = QUARANTINED
        self.counters["quarantines"] += 1
        quarantines_total.inc()
        self._next_probe_t[idx] = now + self._probation_s(idx)
        self._publish(idx)
        logger.warning("replica %d quarantined; probe in %.1fs",
                       idx, self._probation_s(idx))
        return QUARANTINED

    def begin_drain(self, idx: int) -> None:
        """Planned exit (rolling reload, chaos preempt): stop dispatch
        while in-flight work migrates off."""
        if self._state[idx] not in (EVICTED,):
            self._state[idx] = DRAINING
            self._publish(idx)

    def due_probes(self) -> List[int]:
        """Quarantined replicas whose probation has elapsed."""
        now = self.clock()
        return [i for i, s in sorted(self._state.items())
                if s == QUARANTINED and now >= self._next_probe_t.get(i, 0.0)]

    def begin_probe(self, idx: int) -> None:
        self._state[idx] = PROBING
        self._publish(idx)

    def on_probe_result(self, idx: int, ok: bool) -> str:
        """Canary verdict for a probing replica. Pass → live (probation
        resets); fail → re-quarantined with exponentially longer
        probation."""
        if ok:
            self._state[idx] = LIVE
            self._probe_failures[idx] = 0
            self.counters["reinstates"] += 1
            reinstates_total.inc()
            self._publish(idx)
            logger.info("replica %d reinstated after passing canary", idx)
            return LIVE
        self._probe_failures[idx] += 1
        self._state[idx] = QUARANTINED
        self._next_probe_t[idx] = self.clock() + self._probation_s(idx)
        self._publish(idx)
        logger.warning(
            "replica %d canary failed (%d consecutive); next probe in %.1fs",
            idx, self._probe_failures[idx], self._probation_s(idx))
        return QUARANTINED

    def evict(self, idx: int) -> None:
        """Permanent removal outside the flap breaker (e.g. rebuild
        itself keeps failing)."""
        if self._state[idx] != EVICTED:
            self._state[idx] = EVICTED
            self.counters["flaps"] += 1
            flaps_total.inc()
            self._publish(idx)
            logger.error("replica %d permanently evicted", idx)

    def mark_dead(self, idx: int) -> None:
        """Terminal state WITHOUT flap accounting: the legacy
        healing-disabled death (a fault with ``enabled=False`` — the
        replica was never quarantined, it just died)."""
        if self._state[idx] != EVICTED:
            self._state[idx] = EVICTED
            self._publish(idx)

    # ------------------------------------------------------------------
    def note_migration(self, n: int = 1) -> None:
        self.counters["migrations"] += n
        migrations_total.inc(n)

    def note_migration_fallback(self, n: int = 1) -> None:
        self.counters["migration_fallbacks"] += n
        migration_fallbacks_total.inc(n)

    # ------------------------------------------------------------------
    def scalars(self) -> Dict[str, float]:
        """Flat snapshot for stats aggregation / flight dumps."""
        out = {f"replica_lifecycle_{k}_total": v
               for k, v in self.counters.items()}
        for s, n in self.counts().items():
            out[f"replica_lifecycle_{s}"] = n
        return out
