"""Continuous-batching inference engine.

The TPU-native re-design of the vLLM engine the reference claims but never
ships (``README.md:10,16``; ``requirements.txt:18``). Architecture, XLA-first:

* **Two compiled programs, static shapes.** Prefill runs one request at a
  time at a bucketed prompt length (one compile per bucket); decode runs the
  whole slot batch one token per step. Nothing recompiles as requests come
  and go — liveness is data (positions / block tables), not shape.
* **Paged KV.** One physical block pool per layer in HBM
  (``dlti_tpu.ops.kv_cache``); the host-side :class:`BlockManager` hands out
  blocks; block tables are tiny int32 arrays shipped to the device each step.
* **Continuous batching.** Between decode steps the scheduler retires
  finished slots, admits waiting requests into free slots (prefill), and
  grows block tables as sequences cross block boundaries. Out-of-memory is
  handled by preempting the youngest sequence back to the waiting queue
  (recompute-on-readmit, vLLM's recompute policy).
* **Fused sampling.** Greedy / temperature / top-k / top-p are per-slot
  *data* (``dlti_tpu.serving.sampling``), sampled inside the compiled decode
  step — mixed batches never branch. Per-request ``seed`` keys make a
  request's draw stream independent of batch composition.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dlti_tpu.config import LoRAConfig, ModelConfig
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.ops.kv_cache import init_paged_cache
from dlti_tpu.serving.adapters import AdapterError
from dlti_tpu.serving.block_manager import BlockManager
from dlti_tpu.serving.sampling import SamplingParams, sample_tokens
from dlti_tpu.telemetry import RequestTelemetry
from dlti_tpu.telemetry.distributed_trace import mint_trace_id
from dlti_tpu.telemetry.flightrecorder import get_recorder
from dlti_tpu.telemetry.memledger import (
    MemoryLedger, is_oom_error, tree_nbytes,
)
from dlti_tpu.utils.logging import get_logger

# Speculative-decode /metrics names (registered by server.build_registry's
# spec scalar source; the engine's stats dict stays the source of truth).
# Name-stability contract — external dashboards scrape these; pinned in
# tests/test_bench_contract.py.
SPEC_METRIC_NAMES = (
    "dlti_spec_proposed_total",
    "dlti_spec_accepted_total",
    "dlti_spec_paused_rounds_total",
    "dlti_spec_acceptance_rate",
    "dlti_spec_draft_len",
)


@dataclass
class EngineConfig:
    """Engine sizing. Defaults suit a tiny test model; production configs
    come from ``scripts/serve.py``."""

    max_seqs: int = 8              # decode batch slots
    block_size: int = 16           # tokens per KV block
    num_blocks: int = 256          # physical pool size (per layer)
    max_model_len: int = 512       # max prompt+generation length per request
    prefill_buckets: Sequence[int] = ()  # default: powers of 2 up to max_model_len
    cache_dtype: str = "bfloat16"
    eos_token_id: int = 2          # Llama-2 </s>
    # Automatic prefix caching (dlti_tpu.serving.prefix_cache): retired
    # sequences' full KV blocks are kept content-addressed and reused by
    # later requests sharing a prompt prefix; unreferenced blocks are
    # evicted LRU under pool pressure.
    enable_prefix_caching: bool = False
    # Prefix-cache tiering (dlti_tpu.serving.prefix_tiers): with a host
    # and/or disk budget set (and prefix caching on), evicted HBM blocks
    # demote HBM -> host RAM -> disk instead of being discarded, and a
    # prefix match that runs past the HBM blocks restores lower-tier
    # blocks with a host->device scatter (charged as a restore, not a
    # re-prefill). prefix_host_blocks bounds the host tier (blocks);
    # prefix_disk_blocks bounds digest-verified block dirs under
    # prefix_disk_dir (0 = that tier off).
    prefix_host_blocks: int = 0
    prefix_disk_dir: str = ""
    prefix_disk_blocks: int = 0
    # Multi-step decode: run this many decode iterations inside ONE
    # compiled program (lax.scan: forward -> sample -> feed back), syncing
    # with the host only at the boundary. Amortizes per-step dispatch and
    # host round-trips (vLLM's multi-step scheduling); the trade-off is up
    # to steps_per_sync-1 discarded tokens after an EOS and coarser
    # admission cadence.
    steps_per_sync: int = 1
    # Weight-only quantization: "int8" stores matmul weights as int8 +
    # per-channel scales (~half the weight HBM -> bigger KV pool),
    # dequantized inside the compiled programs. "none" keeps param_dtype.
    quantization: str = "none"
    # Speculative decoding: "ngram" proposes draft tokens by prompt lookup
    # (match the trailing n-gram against earlier context, copy what
    # followed) and verifies them in a (k+1)-position forward — greedy-exact
    # up to batched-matmul numerics (a (k+1)-position forward tiles
    # differently than a 1-position one, the same ~1e-2 bf16 logit delta any
    # batch-shape change causes; ties only flip on near-ties, which trained
    # models rarely produce at the argmax). Proposal, verification, and
    # acceptance all run ON DEVICE, and ``steps_per_sync`` such rounds chain
    # inside one compiled program (lax.scan over a token-history buffer), so
    # speculation COMPOSES with multi-step decode: up to
    # steps_per_sync*(num_draft_tokens+1) tokens per host sync on
    # repetitive text. Gating is PER SLOT: greedy slots accept draft
    # prefixes while sampling slots in the same batch take their
    # single-step sampled token (same fold_in rng stream), so one sampling
    # request no longer disables speculation batch-wide. Caveat of that
    # composition: a sampling slot's position-0 logits then come from a
    # (k+1)-position forward, which tiles differently than the 1-position
    # plain decode — the same ~1e-2 bf16 logit delta as above. Greedy
    # argmax only flips on near-ties, but a categorical draw can flip
    # whenever the shifted CDF crosses the rng uniform, so under
    # speculative mode a seeded sampling request's tokens are reproducible
    # for a fixed engine config but not bitwise-independent of batch
    # composition on bf16 (exact on f32). speculative="none" keeps the
    # strict batch-independence promise.
    speculative: str = "none"          # "none" | "ngram"
    num_draft_tokens: int = 4
    ngram_size: int = 2
    # Adaptive gate: a greedy slot-round wins (emitted-1) extra tokens over
    # plain decode. When the mean win over the last >=spec_probe_window
    # greedy slot-rounds drops below spec_min_acceptance (extra tokens per
    # round — rounds where prompt lookup finds no match count as 0), pause
    # proposing for spec_cooldown engine rounds (which run the plain
    # multi-step path), then re-probe. 0.0 disables the gate (always
    # speculate). On by default: on text where prompt lookup never hits,
    # the (k+1)-position forwards are pure overhead, and the gate is what
    # makes --speculative ngram safe to leave enabled.
    spec_min_acceptance: float = 0.25
    spec_probe_window: int = 64
    spec_cooldown: int = 32
    # Draft-length ladder: compile spec programs for the pow2 halving
    # ladder of k (num_draft_tokens, /2, ..., 1) and pick the dispatch k
    # each engine round from the live per-slot acceptance windows —
    # shorter drafts on text where prompt lookup barely lands, full-k on
    # repetitive text. Greedy exactness holds at every k (an accepted
    # prefix under smaller k is a prefix of the full-k acceptance), so
    # this only trades verify-forward width for wasted lanes. False pins
    # dispatch at k=num_draft_tokens (the pre-ladder behavior).
    spec_adaptive: bool = True
    # Ragged multi-admission prefill: instead of grouping prefill chunks
    # by their own pow2 bucket (each group padded to its widest member's
    # bucket), pack chunks from many admissions FCFS into shared groups
    # bounded by padded total tokens — one prefill call advances several
    # admissions. Rows keep their own block tables and last-token
    # indices, so outputs are byte-identical ragged on/off; the win is
    # fewer program dispatches (and fewer distinct jit specializations)
    # under a multi-admission wave.
    ragged_prefill: bool = False
    # Device-resident decode state (dlti_tpu.serving.decode_state): block
    # tables, slot keys, gen counts, and sampling params live as
    # persistent device arrays maintained incrementally with per-slot
    # dirty tracking — a clean decode step uploads nothing. False falls
    # back to the legacy full re-upload (jnp.asarray of every mirror,
    # every step); outputs are byte-identical either way.
    decode_state_cache: bool = True
    # Chunked prefill (the vLLM latency lever the throughput headline
    # lacks): cap prompt tokens prefilled per engine step, so admission
    # never stalls running decodes for a whole prompt length — partially
    # prefilled slots carry their remaining suffix across steps and join
    # the decode batch when it lands. 0 = unbounded (throughput mode:
    # whole prompts in one batched call per bucket).
    max_prefill_tokens_per_step: int = 0
    # Numeric output guard (PR 8): before ANY token from a decode round
    # is appended/streamed, its logprob (computed device-side alongside
    # the sample — NaN/inf logits surface there) must be finite; a
    # nonfinite round raises NumericFault, which AsyncEngine treats as an
    # engine fault and ReplicatedEngine answers by quarantining the
    # replica and recomputing the round's requests on survivors — users
    # never see the garbage tokens a numerically-dead replica samples.
    guard_nonfinite: bool = True
    # Token-storm guard: N consecutive decode steps in which EVERY active
    # slot (>= 2 of them) sampled the same token reads as a degenerate
    # output distribution (the all-pad storm a silently-corrupted model
    # produces) and raises NumericFault. 0 = off (legitimate decodes CAN
    # agree; enable with a window sized for your traffic).
    guard_token_storm: int = 0
    # Memory ledger (telemetry.memledger): per-owner HBM attribution
    # (params / kv_block_pool / prefix_cache_hbm / decode_state_cache),
    # feeding /debug/memory, the hbm_* metric gauges, and memory.json in
    # engine flight dumps.
    memory_ledger: bool = True
    # HBM capacity budget in bytes for headroom accounting (0 =
    # auto-detect from device memory_stats(); unknown on CPU unless set).
    hbm_budget_bytes: int = 0
    # Headroom-aware admission: defer admitting new requests while ledger
    # headroom is below this fraction of capacity (0 = gating off, and it
    # is also off whenever capacity is unknown). Deferred requests stay
    # queued — the degraded mode is latency, never a client error.
    admit_min_headroom_frac: float = 0.0
    # Multi-LoRA serving (dlti_tpu.serving.adapters): with adapter_slots
    # > 0 the executor carries a stacked per-module A/B adapter pool
    # ((slots+1, in, r) and (slots+1, r, out) per targeted projection;
    # row 0 is the all-zero base no-op) and every compiled program
    # gathers each batch row's factors by adapter id — one program
    # serves a batch of heterogeneous adapters (S-LoRA/Punica's BGMV).
    # 0 keeps every program signature byte-identical to an adapter-free
    # engine. adapter_rank is the pool-wide max (smaller adapters
    # zero-pad, which is float-exact); adapter_targets name the
    # projections the pool covers.
    adapter_slots: int = 0
    adapter_rank: int = 16
    adapter_targets: Sequence[str] = (
        "q_proj", "k_proj", "v_proj", "o_proj")

    def buckets(self) -> List[int]:
        if self.prefill_buckets:
            return sorted(self.prefill_buckets)
        out, b = [], self.block_size
        while b < self.max_model_len:
            out.append(b)
            b *= 2
        out.append(self.max_model_len)
        return out

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.max_model_len // self.block_size)


class NumericFault(RuntimeError):
    """A decode round produced numerically-dead output (nonfinite
    logits/logprobs, or an all-slots token storm). Raised BEFORE any of
    the round's tokens are appended, so nothing garbage is ever streamed;
    the replica layer answers by quarantining the engine and recomputing
    its requests on survivors (:meth:`ReplicatedEngine._fail_replica`)."""


@dataclass
class Request:
    """One generation request (token-level; text handled by the server)."""

    request_id: str
    prompt_token_ids: List[int]
    params: SamplingParams = field(default_factory=SamplingParams)
    arrival_time: float = field(default_factory=time.monotonic)
    # Filled by the engine:
    output_token_ids: List[int] = field(default_factory=list)
    output_logprobs: List[float] = field(default_factory=list)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    finish_reason: Optional[str] = None
    num_preemptions: int = 0
    # Which replica owns this request (set by ReplicatedEngine.submit).
    replica: int = 0
    # Failover resubmissions consumed (ReplicatedEngine moves a dead
    # replica's requests onto survivors up to a retry cap).
    num_retries: int = 0
    # Live migrations survived (planned drains hand this request's paged
    # KV to a survivor mid-decode instead of re-prefilling; the server
    # surfaces the count so load drills can assert on it).
    num_migrations: int = 0
    # Admission metadata (set by the gateway when one is configured; the
    # engine itself schedules FCFS and ignores them).
    tenant: str = ""
    priority: str = ""
    # Absolute monotonic deadline (None = none). The gateway sheds queued
    # requests past it before prefill and flips cancel_requested on
    # in-flight ones.
    deadline: Optional[float] = None
    # When the request was first admitted into a decode slot (monotonic;
    # None while queued). Kept across preemption/re-admission so the
    # queue-time histogram measures the first wait only.
    admitted_time: Optional[float] = None
    # Early-cancel flag (server stop-string matching, client disconnect):
    # SET from any thread (a GIL-atomic bool write, the same contract as
    # AsyncEngine.submit), CONSUMED by the stepper thread at the next
    # token-emission walk — the slot is released there, so a cancelled
    # request costs at most one decode window.
    cancel_requested: bool = False
    # Critical-path attribution inputs (telemetry.ledger): when the
    # request came through the admission gateway, its enqueue time (the
    # client-observed t0); seconds spent restoring lower-tier prefix
    # blocks at admission; requeue stalls by kind ("failover"/"preempt")
    # with the pre-first-token portion split out; and the open requeue
    # mark note_requeue/note_readmitted maintain.
    gateway_enqueue_time: Optional[float] = None
    restore_s: float = 0.0
    stall_s: Dict[str, float] = field(default_factory=dict)
    stall_prefill_s: float = 0.0
    _requeue_mark: Optional[tuple] = None
    # Multi-LoRA serving: the registered adapter this request generates
    # under ("" = base model). _adapter_slot is the resolved pool row
    # (-1 = unresolved): acquisition happens at admission and the pin is
    # dropped with the decode slot, so preemption and failover
    # re-acquire — the row may have been evicted meanwhile.
    adapter: str = ""
    _adapter_slot: int = -1
    # Deployment-controller shadow mirror (serving.deploy): results never
    # reach a client, and telemetry/SLO/gateway accounting skips these.
    shadow: bool = False
    # Distributed-trace context (telemetry.distributed_trace): minted at
    # the gateway (or at submit for direct clients) and PROPAGATED — it
    # rides the FT_SUBMIT descriptor, handoff envelopes, drain
    # migrations, failover resubmits, disagg staging, and shadow-tap
    # replays, so spans emitted in any process for any leg of this
    # request share one id. "" = untraced (wire canaries, old peers).
    trace_id: str = ""

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


@dataclass
class GenerationResult:
    request_id: str
    prompt_token_ids: List[int]
    output_token_ids: List[int]
    output_logprobs: List[float]
    finish_reason: str
    ttft_s: float
    latency_s: float


class _Slot:
    """Host state for one active decode slot."""

    def __init__(self, slot_id: int):
        self.slot_id = slot_id
        self.request: Optional[Request] = None
        self.blocks: List[int] = []
        self.seq_len = 0  # tokens written to the KV cache
        self.last_token = 0
        # Chunked prefill bookkeeping, as positions into the request's
        # (prompt + output) token list: next_pos = where the next chunk
        # starts, prefill_end = one past the last prompt token. A slot
        # with next_pos < prefill_end is admitted but not yet decodable.
        self.next_pos = 0
        self.prefill_end = 0

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefilling(self) -> bool:
        return self.request is not None and self.next_pos < self.prefill_end


class EngineExecutor:
    """The device half of the engine: weights, paged-KV pools, and every
    compiled program (bucketed prefill, the decode ladder, speculative
    decode, fused sampling, the tier-restore scatter), plus the
    device<->host block transport (:meth:`fetch_block_kv` /
    :meth:`restore_block`).

    Holds NO scheduling state — slots, queues, block accounting,
    admission, and retirement live in :class:`InferenceEngine`, which
    assembles host-side batches and calls in. The split is what
    disaggregated serving (``serving.disagg``) builds on: a prefill-only
    engine's executor never runs (or warms) the decode ladder, and
    paged-KV handoff between pools talks to the executor's block
    transport directly.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg: Optional[LoRAConfig] = None,
        mesh=None,
        donate_params: bool = False,
    ):
        self.cfg = engine_cfg
        self.model_cfg = model_cfg
        self.logger = get_logger()
        self.mesh = mesh
        if mesh is not None:
            # Tensor-parallel serving: weights and KV pools shard over the
            # 'tensor' axis (attention heads / MLP hidden / vocab); GSPMD
            # inserts the collectives in the jitted prefill/decode programs.
            # Other axes stay 1 — batch-level scaling is a replica concern.
            bad = [ax for ax, n in mesh.shape.items()
                   if n > 1 and ax != "tensor"]
            if bad:
                raise ValueError(
                    f"serving mesh may only extend the 'tensor' axis; got "
                    f"{dict(mesh.shape)} (axes {bad} > 1)")
            tp = mesh.shape["tensor"]
            if model_cfg.num_kv_heads % tp or model_cfg.num_heads % tp:
                raise ValueError(
                    f"tensor={tp} must evenly divide num_heads="
                    f"{model_cfg.num_heads} and num_kv_heads="
                    f"{model_cfg.num_kv_heads}")
        self.model = LlamaForCausalLM(model_cfg, lora_cfg, mesh)
        self._quantized = engine_cfg.quantization == "int8"
        if engine_cfg.quantization not in ("none", "int8"):
            raise ValueError(f"unknown quantization {engine_cfg.quantization!r}")
        if self._quantized:
            # Composes with TP: the sharding rules match quantized
            # {"q","scale"} leaves on the kernel's own path (int8 kernels
            # shard like their fp ancestors; scales follow the output
            # channels and replicate for row-parallel kernels).
            # donate_params frees each source leaf as it quantizes — at 7B
            # the bf16 and int8 trees cannot coexist in one chip's HBM.
            from dlti_tpu.models.quantization import quantize_params_int8

            params = quantize_params_int8(params, donate=donate_params)
        self._device = None
        if mesh is None:
            # Pin host-resident weights to a serving device once.
            # Checkpoint restores hand back numpy arrays; without this
            # every compiled call re-uploads the whole tree (measured:
            # ~40 s per decode step for a 300M model over the remote
            # relay). Leaves that are already committed jax.Arrays keep
            # their placement — ReplicatedEngine pins each replica's copy
            # to its own device before construction — and that device
            # becomes THE engine device: the KV pool is committed to it
            # too (below), so warmup's AOT lowering and every compiled
            # call agree on placement instead of relying on jit's
            # uncommitted-operand migration.
            dev = next((d for leaf in jax.tree_util.tree_leaves(params)
                        if isinstance(leaf, jax.Array)
                        and getattr(leaf, "committed", False)
                        for d in leaf.devices()), jax.devices()[0])
            self._device = dev
            params = jax.tree_util.tree_map(
                lambda x: x if isinstance(x, jax.Array)
                and getattr(x, "committed", False)
                else jax.device_put(x, dev), params)
        self.params = params

        # Multi-LoRA adapter pool: stacked per-module A/B tensors the
        # compiled programs gather per batch row (serving.adapters). Built
        # AFTER quantization/placement so the target-shape walk sees the
        # final param layout (int8 kernels keep their shape in "q") and
        # the pool lands on the engine device alongside the weights.
        self.adapter_pool = None
        if engine_cfg.adapter_slots > 0:
            from dlti_tpu.serving.adapters import AdapterPool

            self.adapter_pool = AdapterPool(
                self.params, engine_cfg.adapter_slots,
                engine_cfg.adapter_rank, engine_cfg.adapter_targets,
                device=self._device, mesh=mesh)

        ec = engine_cfg
        from dlti_tpu.utils.dtypes import resolve_dtype

        # "int8" selects the quantized pool layout (int8 payload +
        # per-row fp32 scales — ops.kv_cache): half the KV HBM of bf16,
        # which buys roughly twice the decode slots on a fixed chip.
        dtype = "int8" if ec.cache_dtype == "int8" else resolve_dtype(ec.cache_dtype)
        self.cache = init_paged_cache(
            model_cfg.num_layers, ec.num_blocks, ec.block_size,
            model_cfg.num_kv_heads, model_cfg.resolved_head_dim, dtype,
        )
        if mesh is not None:
            self._shard_for_tp(mesh)
        elif self._device is not None:
            # Commit the pool to the engine device (see the params pin
            # above): a replica off the default device otherwise starts
            # with a device-0 pool that only migrates on first dispatch.
            self.cache = jax.device_put(self.cache, self._device)

        self._restore_fn = None  # lazily-jitted tier/handoff restore scatter
        # Block fetches stage device→host through pinned_host when the
        # backend exposes it (TPU) — the ZeRO-3 offload path; CPU's
        # default memory space is host already. Probed unconditionally:
        # both prefix-tier demotion and disaggregated KV handoff use it.
        self._demote_sharding = None
        try:
            dev = self._device or jax.devices()[0]
            kinds = {m.kind for m in dev.addressable_memories()}
            if "pinned_host" in kinds:
                from jax.sharding import SingleDeviceSharding

                self._demote_sharding = SingleDeviceSharding(
                    dev, memory_kind="pinned_host")
        except Exception:  # noqa: BLE001 — staging is an optimization
            self._demote_sharding = None

        self._prefill_fns: Dict[int, callable] = {}
        self._decode_fn = self._build_decode_fn()
        # Multi-step decode programs, one per window length on the halving
        # ladder (K, K//2, ..., 1; see _window_steps) — compiled lazily on
        # first use. Bounded at ~log2(K)+1 variants.
        self._multi_decode_fns: Dict[int, callable] = {}
        # Speculative program: rounds = steps_per_sync (>=1), so spec and
        # multi-step are one composed program, not alternatives.
        self._spec_rounds = max(1, ec.steps_per_sync)
        # Token-history rows: positions 0..max_model_len-1, one slack cell
        # for the in-flight input token, one scratch cell absorbing masked
        # scatter writes (see _build_spec_decode_fn).
        self._spec_hist_width = ec.max_model_len + ec.num_draft_tokens + 2
        self._spec_fn = (
            self._build_spec_decode_fn(ec.num_draft_tokens, self._spec_rounds)
            if ec.speculative == "ngram" else None)
        # Draft-length ladder (spec_adaptive): one spec program per pow2 k
        # on the halving ladder, compiled lazily on first dispatch at that
        # k. The max-k program above is eagerly built (it doubles as the
        # "speculation is on" sentinel) and seeds the ladder dict.
        self._spec_fns: Dict[int, callable] = (
            {ec.num_draft_tokens: self._spec_fn}
            if self._spec_fn is not None else {})
        if ec.speculative not in ("none", "ngram"):
            raise ValueError(f"unknown speculative mode {ec.speculative!r}")
        self._sample_fn = jax.jit(sample_tokens)

        # Batched per-slot key folding (the same fold the decode program
        # applies to raw uint32 key data): one async dispatch instead of a
        # synchronous device round trip per admitted row.
        self._fold_keys = jax.jit(jax.vmap(jax.random.fold_in))

    # ------------------------------------------------------------------
    def _shard_for_tp(self, mesh) -> None:
        """Place weights and KV pools on the TP mesh.

        Params follow the training TP rules (column/row-parallel
        projections, sharded vocab); each layer's K/V pool shards its
        kv_heads dim. Block tables and sampling state stay replicated.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dlti_tpu.config import Config, ParallelConfig
        from dlti_tpu.parallel.sharding import param_shardings

        cfg = Config(model=self.model_cfg,
                     parallel=ParallelConfig(tensor=mesh.shape["tensor"]))
        p_sh = param_shardings(self.params, cfg, mesh)
        self.params = jax.tree_util.tree_map(jax.device_put, self.params, p_sh)
        kv_sh = NamedSharding(mesh, P(None, None, "tensor", None))
        scale_sh = NamedSharding(mesh, P(None, None, "tensor"))
        self.cache = [
            {k: jax.device_put(v, scale_sh if k.endswith("_scale") else kv_sh)
             for k, v in l.items()}
            for l in self.cache
        ]

    # ------------------------------------------------------------------
    # Compiled programs
    # ------------------------------------------------------------------
    def _model_cache_call(self, params, cache_kv, block_tables, input_ids,
                          positions, adapter_ids=None, adapters=None):
        """Run the model over a paged cache; returns (logits, new k/v list).

        Quantized params pass through as-is — each module dequantizes its
        own weights at the consumer (``models.quantization.maybe_dequantize``),
        so only the executing layer holds a compute-dtype copy even inside
        the multi-step decode scan.

        With a multi-LoRA pool, ``adapters`` (the stacked A/B tree) rides
        in as a Flax variable collection and ``adapter_ids`` (one pool row
        per batch row) gathers each row's factors inside LoRADense; both
        absent leaves the traced program identical to an adapter-free
        engine (the branch is Python-static)."""
        cache = [
            {**layer, "block_tables": block_tables} for layer in cache_kv
        ]
        variables = {"params": params}
        kw = {}
        if adapters is not None:
            variables["adapters"] = adapters
            kw["adapter_ids"] = adapter_ids
        logits, new_cache = self.model.apply(
            variables, input_ids, positions=positions, cache=cache,
            deterministic=True, **kw,
        )
        return logits, [{k: v for k, v in c.items() if k != "block_tables"}
                        for c in new_cache]

    def prefill_fn(self, bucket: int):
        """The compiled prefill program for a suffix bucket (lazily built)."""
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            fn = self._prefill_fns[bucket] = self._build_prefill_fn(bucket)
        return fn

    def _build_prefill_fn(self, bucket: int):
        @partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache_kv, input_ids, positions, block_table,
                    last_idx, *lora):
            # input_ids/positions: (B, bucket); block_table: (B, nblk) —
            # sliced so attention's gathered window is bucket-sized, not
            # max_model_len-sized. B > 1 batches several admissions into
            # one program call (padding rows carry position -1, whose
            # writes slot_mapping drops); last_idx (B,) selects each
            # row's final real logit. With a multi-LoRA pool, *lora is
            # (adapter_ids, adapters) — per-row adapter gather; empty
            # otherwise (the traced program is then unchanged).
            logits, new_kv = self._model_cache_call(
                params, cache_kv, block_table, input_ids, positions, *lora
            )
            last = jnp.take_along_axis(
                logits, last_idx[:, None, None], axis=1)[:, 0]
            return new_kv, last

        return prefill

    def _build_decode_fn(self):
        @partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache_kv, input_ids, positions, block_tables,
                   slot_keys, gen_counts, temperature, top_k, top_p, *lora):
            # input_ids/positions: (S, 1); block_tables: (S, max_blocks).
            # *lora: (adapter_ids, adapters) when the multi-LoRA pool is
            # on (adapter_ids rides in decode-state argument order, the
            # pool tree LAST so state threading stays contiguous).
            logits, new_kv = self._model_cache_call(
                params, cache_kv, block_tables, input_ids, positions, *lora
            )
            rngs = jax.vmap(jax.random.fold_in)(slot_keys, gen_counts)
            tokens, logprobs = sample_tokens(
                logits[:, 0, :], rngs, temperature, top_k, top_p
            )
            return new_kv, tokens, logprobs

        return decode

    @staticmethod
    def _aot_or_jit(compiled, jit_fn):
        """Dispatch through an AOT executable, permanently falling back to
        the jit path the first time the executable REJECTS the inputs
        (aval/sharding drift — should not happen with the engine's static
        decode shapes, but a warmup must never be able to break serving).
        Only input-validation errors raised BEFORE execution (so no
        donated buffer is consumed) trigger the fallback: TypeError, and
        the sharding-mismatch ValueError (e.g. a replica pinned off the
        default device meeting an executable compiled for it). A runtime
        failure mid-execution may already have consumed the donated KV
        cache, so retrying via jit would only mask the real error with
        'Array has been deleted' — let it propagate."""
        state = {"aot": True}

        def _is_input_rejection(e: Exception) -> bool:
            return isinstance(e, TypeError) or (
                isinstance(e, ValueError)
                and "Compiled object called with input sharding" in str(e))

        def call(*a):
            if state["aot"]:
                try:
                    return compiled(*a)
                except (TypeError, ValueError) as e:
                    if not _is_input_rejection(e):
                        raise
                    state["aot"] = False
                    get_logger().warning(
                        "AOT decode executable rejected inputs (%s); "
                        "falling back to jit dispatch permanently", e)
            return jit_fn(*a)

        call._aot_state = state  # test hook: did dispatch stay on the AOT path?
        call._jit_fn = jit_fn    # warmup idempotency: the lowerable fn
        return call

    def _build_multi_decode_fn(self, num_steps: int):
        """K decode iterations in one program: the sampled token feeds the
        next forward inside a lax.scan; the host syncs once per K tokens.

        The per-slot rng stream (fold_in(key, gen_count)) advances exactly
        as in single-step decode, so results are identical for a given
        request regardless of steps_per_sync.
        """
        @partial(jax.jit, donate_argnums=(1,))
        def decode_multi(params, cache_kv, input_ids, positions, block_tables,
                         slot_keys, gen_counts, temperature, top_k, top_p,
                         *lora):
            def body(carry, _):
                cache, tok, pos, cnt = carry
                logits, new_kv = self._model_cache_call(
                    params, cache, block_tables, tok, pos, *lora
                )
                rngs = jax.vmap(jax.random.fold_in)(slot_keys, cnt)
                nxt, lp = sample_tokens(
                    logits[:, 0, :], rngs, temperature, top_k, top_p)
                return (new_kv, nxt[:, None], pos + 1, cnt + 1), (nxt, lp)

            (new_kv, _, _, _), (toks, lps) = jax.lax.scan(
                body, (cache_kv, input_ids, positions, gen_counts),
                None, length=num_steps)
            # (K, S) -> (S, K)
            return new_kv, toks.T, lps.T

        return decode_multi

    def _build_spec_decode_fn(self, k: int, rounds: int):
        """``rounds`` propose→verify→accept iterations in ONE program.

        Each round, entirely on device (no host round-trip between rounds):

        1. **Propose** (prompt lookup): per slot, match the trailing
           ``ngram_size``-gram of the token history against every earlier
           position (one vectorized window comparison on the VPU) and copy
           the k tokens that followed the most recent hit; no hit → an
           all-(-1) draft, which degrades that slot to single-step.
        2. **Verify**: one forward over (S, k+1) positions — the current
           input token plus the k drafts.
        3. **Accept**: greedy slots emit the longest draft prefix matching
           the argmax plus one bonus token (exact greedy decoding);
           sampling slots emit their position-0 ``sample_tokens`` draw
           (identical fold_in rng stream to plain decode). Accepted tokens
           are scattered back into the history so the *next* round's
           proposal sees them — this is what makes speculation compose
           with multi-step instead of excluding it.

        The host syncs once per call: up to rounds*(k+1) tokens. KV writes
        past a slot's accepted prefix are garbage but live at positions its
        next round (or next plain decode) overwrites before any query can
        attend to them (causal masking; same invariant as chunked prefill's
        trash-block masking).
        """
        n = self.cfg.ngram_size
        W = self._spec_hist_width

        def propose(hist, seq_len):
            # hist rows hold context tokens at their positions (the input
            # token already placed at seq_len); valid length = seq_len+1.
            S = hist.shape[0]
            tails = jax.vmap(
                lambda row, sl: jax.lax.dynamic_slice(row, (sl + 1 - n,), (n,))
            )(hist, seq_len)                                     # (S, n)
            win = jnp.stack(
                [hist[:, j:W - n + 1 + j] for j in range(n)], axis=-1
            )                                                    # (S, W-n+1, n)
            eq = jnp.all(win == tails[:, None, :], axis=-1)
            ii = jnp.arange(W - n + 1)[None, :]
            # A hit must be an *earlier* occurrence fully inside known
            # context: window ends at ii+n-1 <= seq_len-1.
            valid = eq & (ii <= (seq_len - n)[:, None]) & (seq_len >= n)[:, None]
            found = jnp.any(valid, axis=1)
            best = jnp.argmax(jnp.where(valid, ii, -1), axis=1)  # most recent
            drafts = jax.vmap(
                lambda row, b: jax.lax.dynamic_slice(row, (b,), (k,))
            )(hist, best + n)                                    # (S, k)
            j = jnp.arange(k)[None, :]
            ok = found[:, None] & ((best + n)[:, None] + j <= seq_len[:, None])
            return jnp.where(ok, drafts, -1)

        @partial(jax.jit, donate_argnums=(1,))
        def spec_decode(params, cache_kv, hist, t_in, seq_len, spec_mask,
                        block_tables, slot_keys, gen_counts, temperature,
                        top_k, top_p, *lora):
            S = t_in.shape[0]
            rows = jnp.arange(S)
            is_greedy = temperature == 0.0

            def body(carry, _):
                cache, hist, t_in, seq_len, cnt = carry
                hist = hist.at[rows, seq_len].set(t_in)
                drafts = propose(hist, seq_len)                  # (S, k)
                # Per-slot gate: a paused slot's draft is forced to the
                # all-(-1) no-hit form, degrading just that slot to
                # single-step while its neighbors keep speculating.
                drafts = jnp.where(spec_mask[:, None], drafts, -1)
                ids = jnp.concatenate(
                    [t_in[:, None], jnp.maximum(drafts, 0)], axis=1)
                pos = seq_len[:, None] + jnp.arange(k + 1)[None, :]
                logits, new_kv = self._model_cache_call(
                    params, cache, block_tables, ids, pos, *lora)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
                g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (S, k+1)
                g_lp = jnp.take_along_axis(
                    logp, g[..., None], axis=-1)[..., 0]
                # Position-0 emission via sample_tokens for EVERY slot:
                # greedy rows reduce to the same argmax, sampling rows get
                # exactly the plain-decode draw for fold_in(key, cnt).
                rngs = jax.vmap(jax.random.fold_in)(slot_keys, cnt)
                s_tok, s_lp = sample_tokens(
                    logits[:, 0, :], rngs, temperature, top_k, top_p)
                eq = (drafts == g[:, :k]) & (drafts >= 0)
                m = jnp.sum(jnp.cumprod(eq.astype(jnp.int32), axis=1), axis=1)
                emit = jnp.where(is_greedy, m + 1, 1).astype(jnp.int32)
                toks = g.at[:, 0].set(s_tok)
                lps = g_lp.at[:, 0].set(s_lp)
                # Scatter emitted tokens into the history at context
                # positions seq_len+1+j; masked lanes hit the scratch cell.
                cols = seq_len[:, None] + 1 + jnp.arange(k + 1)[None, :]
                cols = jnp.where(
                    jnp.arange(k + 1)[None, :] < emit[:, None], cols, W - 1)
                hist = hist.at[rows[:, None], cols].set(toks)
                t_in2 = toks[rows, emit - 1]
                prop_cnt = jnp.sum(drafts >= 0, axis=1).astype(jnp.int32)
                carry = (new_kv, hist, t_in2, seq_len + emit, cnt + emit)
                return carry, (toks, lps, emit, prop_cnt, m)

            (new_kv, _, _, _, _), (toks, lps, emit, prop, acc) = jax.lax.scan(
                body, (cache_kv, hist, t_in, seq_len, gen_counts),
                None, length=rounds)
            # (R, S, ...) -> slot-major for the host walk.
            return (new_kv, toks.transpose(1, 0, 2), lps.transpose(1, 0, 2),
                    emit.T, prop.T, acc.T)

        return spec_decode

    def spec_fn(self, k: int):
        """The spec program for draft length ``k`` (pow2 halving-ladder
        member), compiled lazily on first dispatch at that k — the same
        bounded-variants pattern as ``_multi_decode_fns``."""
        fn = self._spec_fns.get(k)
        if fn is None:
            fn = self._build_spec_decode_fn(k, self._spec_rounds)
            self._spec_fns[k] = fn
        return fn

    # -- paged-KV block transport (tier demotion + disagg handoff) -----
    def fetch_block_kv(self, block: int):
        """One physical block's KV rows from every layer pool, fetched
        device→host — the prefix-tier demotion path, reused verbatim as
        the disaggregated-serving handoff transport. Runs on the stepper
        thread; ``self.cache`` then holds the committed output of the
        last dispatched program, so the read sees every write the block
        ever received. Payload keys follow the disk format
        ("l00000": {"k": ..., "v": ..., int8 scales if present})."""
        try:
            rows = [{name: arr[block] for name, arr in layer.items()}
                    for layer in self.cache]
            if self._demote_sharding is not None:
                # Stage through pinned_host: the D2H DMA lands in pinned
                # memory the host reads without a bounce (TPU path).
                rows = jax.device_put(rows, self._demote_sharding)
            host = jax.device_get(rows)
        except Exception as e:  # noqa: BLE001 — the fetch is best-effort:
            # a failure degrades to discard (demotion) or re-prefill
            # (handoff), never faults the step loop that triggered it.
            self.logger.warning("block KV fetch failed "
                                "(%s: %s); block discarded",
                                type(e).__name__, e)
            return None
        return {f"l{i:05d}": {k: np.asarray(v) for k, v in r.items()}
                for i, r in enumerate(host)}

    def restore_block(self, block: int, payload: dict) -> None:
        """Scatter a fetched payload into physical ``block`` of every
        layer pool. Dispatch is async (jit): the scatter overlaps host-side
        admission work, and the following prefill/decode programs see the
        restored rows through the ``self.cache`` data dependency."""
        if self._restore_fn is None:
            @partial(jax.jit, donate_argnums=(0,))
            def restore(cache_kv, rows, bid):
                return [
                    {k: v.at[bid].set(r[k].astype(v.dtype)) for k, v in
                     layer.items()}
                    for layer, r in zip(cache_kv, rows)
                ]

            self._restore_fn = restore
        rows = [payload[f"l{i:05d}"] for i in range(len(self.cache))]
        self.cache = self._restore_fn(self.cache, rows,
                                      jnp.asarray(block, jnp.int32))


class InferenceEngine:
    """Synchronous engine core: ``submit()`` requests, ``step()`` in a loop.

    The HTTP server wraps this in a background thread; ``generate()`` is the
    offline batch entry point.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg: Optional[LoRAConfig] = None,
        mesh=None,
        donate_params: bool = False,
        telemetry: Optional[RequestTelemetry] = None,
    ):
        # Request-lifecycle telemetry (dlti_tpu.telemetry.lifecycle):
        # TTFT/TPOT/queue-time histograms observed on-engine + per-request
        # Chrome-trace spans. A shared instance (ReplicatedEngine) makes
        # the histograms aggregate across replicas.
        self.telemetry = telemetry if telemetry is not None \
            else RequestTelemetry()
        self._tracer = self.telemetry.tracer
        if engine_cfg.max_blocks_per_seq > engine_cfg.num_blocks - 1:
            # Block 0 is the reserved trash block, so only num_blocks-1 are
            # allocatable. A config where one max-length sequence can never
            # fit would livelock _admit() at the FCFS head forever.
            raise ValueError(
                f"max_model_len={engine_cfg.max_model_len} needs "
                f"{engine_cfg.max_blocks_per_seq} KV blocks but the pool has "
                f"only {engine_cfg.num_blocks - 1} allocatable "
                f"(num_blocks={engine_cfg.num_blocks} minus the reserved "
                f"trash block); raise num_blocks or lower max_model_len"
            )
        self.cfg = engine_cfg
        self.model_cfg = model_cfg
        self.logger = get_logger()
        self.mesh = mesh
        # The device half (scheduler/executor split): weights, KV pools,
        # and every compiled program live in the executor; this class
        # keeps ONLY host-side scheduling state (slots, queues, block
        # accounting, mirrors) and calls in with assembled batches.
        self.executor = EngineExecutor(
            model_cfg, params, engine_cfg, lora_cfg, mesh=mesh,
            donate_params=donate_params)
        del params  # the executor owns (a possibly quantized copy of) them
        ec = engine_cfg
        self.block_manager = BlockManager(ec.num_blocks, ec.block_size)
        self.prefix_cache = None
        if ec.enable_prefix_caching:
            from dlti_tpu.serving.prefix_cache import PrefixCachingAllocator

            tier_store = None
            if ec.prefix_host_blocks > 0 or ec.prefix_disk_blocks > 0:
                from dlti_tpu.serving.prefix_tiers import TieredBlockStore

                tier_store = TieredBlockStore(
                    host_blocks=ec.prefix_host_blocks,
                    disk_dir=ec.prefix_disk_dir,
                    disk_blocks=ec.prefix_disk_blocks)
            self.prefix_cache = PrefixCachingAllocator(
                self.block_manager, tier_store=tier_store,
                kv_fetch=self._fetch_block_kv if tier_store is not None
                else None)
        self.slots = [_Slot(i) for i in range(ec.max_seqs)]
        self.waiting: collections.deque[Request] = collections.deque()
        # Recently-finished requests, for observability only (results are
        # returned via step()/generate()); bounded so a long-lived server
        # doesn't grow without limit.
        self.finished: collections.deque[Request] = collections.deque(maxlen=256)
        self._rng = jax.random.PRNGKey(0)
        self._req_counter = itertools.count()

        # Host mirrors of the per-slot device inputs.
        S, MB = ec.max_seqs, ec.max_blocks_per_seq
        self._block_tables = np.zeros((S, MB), np.int32)
        self._temperature = np.ones((S,), np.float32)
        self._top_k = np.zeros((S,), np.int32)
        self._top_p = np.ones((S,), np.float32)
        # Per-slot sampling key (uint32[2] threefry data) + tokens generated
        # so far; decode folds key with the count, so a seeded request's
        # draws don't depend on batch composition or admission order.
        self._slot_keys = np.zeros((S, 2), np.uint32)
        self._gen_counts = np.zeros((S,), np.int32)
        # Multi-LoRA: each slot's adapter-pool row (0 = the all-zero base
        # row). Maintained unconditionally so _state_mirrors stays
        # uniform; without a pool it is never shipped to the device.
        self._adapter_ids = np.zeros((S,), np.int32)

        # Host mirror of every slot's token history at its context
        # positions, maintained incrementally at admission/append — the
        # spec program's proposal input, without rebuilding O(context)
        # arrays from Python lists every sync. Rows beyond a slot's
        # seq_len are never read (proposal masks on seq_len), so stale
        # tails from previous occupants need no zeroing.
        self._spec_hist = (
            np.zeros((ec.max_seqs, self._spec_hist_width), np.int32)
            if ec.speculative == "ngram" else None)
        # Per-slot adaptive controller (replaces the old engine-wide
        # _spec_pause): each slot carries its own rolling acceptance
        # window and cooldown, so one zero-hit slot pauses alone while
        # its batchmates keep speculating. prop/acc count slot-rounds and
        # extra accepted tokens since that slot's last gate decision;
        # pause is decode rounds left in that slot's cooldown; ewma is
        # the smoothed accepted-drafts-per-round estimate feeding the
        # draft-length ladder (optimistically seeded at full k so a fresh
        # slot probes with the widest draft).
        self._spec_slot_prop = np.zeros((S,), np.int64)
        self._spec_slot_acc = np.zeros((S,), np.int64)
        self._spec_slot_pause = np.zeros((S,), np.int32)
        self._spec_slot_ewma = np.full((S,), float(ec.num_draft_tokens),
                                       np.float64)
        # Last dispatched draft length (0 = no spec round in flight /
        # speculation off) — the dlti_spec_draft_len gauge.
        self._spec_last_k = 0

        # Disaggregated serving (serving/disagg.py): a prefill-only engine
        # runs admission and chunked prefill but never dispatches decode —
        # finished prefills are harvested via export_handoff() and their
        # KV migrated to a decode replica, which continues the stream via
        # adopt_handoff(). Plain engines leave this False.
        self.prefill_only = False

        # Aggregate stats for the /stats endpoint and load reports.
        self.stats = {"requests": 0, "generated_tokens": 0, "prefill_tokens": 0,
                      "preemptions": 0, "decode_steps": 0,
                      # slot x step units CONSUMED (a slot that hits
                      # EOS/limit mid-window stops counting, even though
                      # the device still runs its dead steps — that waste
                      # deliberately shows up as occupancy < 100%);
                      # decode_slot_steps / (max_seqs * decode_steps) is
                      # the mean slot occupancy — the first thing to look
                      # at when throughput undershoots (synchronized
                      # cohort retirement drains slots faster than
                      # admission refills them; results/int8_kv_7b.json).
                      "decode_slot_steps": 0,
                      "prefix_cached_tokens": 0,
                      # Tokens whose KV came back from a LOWER tier (host
                      # or disk) via a restore scatter instead of either
                      # an HBM hit or a re-prefill. Present (at 0) even
                      # without tiering so the /metrics schema is stable.
                      "prefix_restored_tokens": 0,
                      # Prefill program dispatches (ragged packing exists
                      # to shrink this under multi-admission waves).
                      # Present (at 0) so the /metrics schema is stable.
                      "prefill_batches": 0,
                      "spec_proposed": 0, "spec_accepted": 0,
                      "spec_paused_rounds": 0,
                      # Decode-state cache accounting (decode_state.py):
                      # upload syncs / rows shipped / clean (zero-upload)
                      # syncs. Present (at 0) even with the cache disabled
                      # so the /metrics exposition schema is stable.
                      "decode_state_uploads": 0, "decode_state_rows": 0,
                      "decode_state_clean_syncs": 0,
                      # Numeric-guard trips (nonfinite decode outputs /
                      # token storms). Present (at 0) so the /metrics
                      # schema is stable.
                      "numeric_faults": 0,
                      # Headroom-aware memory control (telemetry.
                      # memledger): admission passes skipped for want of
                      # HBM headroom, and decode windows shrunk to one
                      # step when KV growth found the pool exhausted —
                      # both defer work instead of faulting. Present (at
                      # 0) so the /metrics schema is stable.
                      "hbm_deferred_admissions": 0,
                      "hbm_growth_deferrals": 0}
        # Token-storm guard run length (consecutive all-slots-identical
        # decode steps).
        self._storm_run = 0

        # Device-resident twins of the per-slot mirrors, maintained
        # incrementally (per-slot dirty tracking; clean steps upload
        # nothing). All cache interaction happens on the stepper thread —
        # same thread-safety contract as the mirrors themselves.
        self._state_cache = None
        if ec.decode_state_cache:
            from dlti_tpu.serving.decode_state import DecodeStateCache

            self._state_cache = DecodeStateCache(
                ec.max_seqs, device=self._device, mesh=mesh,
                stats=self.stats,
                extra_fields=(("adapter_ids",)
                              if ec.adapter_slots > 0 else ()))

        # Memory ledger (telemetry.memledger): the engine's owners. The
        # params and cache handles are callables because both rebind
        # (donated decode programs return a fresh cache list); prefix-
        # cached blocks live INSIDE the pool arrays, so that owner is a
        # carve — bytes move from kv_block_pool to prefix_cache_hbm
        # without double counting.
        self.memledger = MemoryLedger(
            enabled=ec.memory_ledger, capacity_bytes=ec.hbm_budget_bytes)
        self.memledger.register("params", lambda: self.params)
        self.memledger.register("kv_block_pool", lambda: self.cache)
        self.memledger.register(
            "decode_state_cache",
            lambda: (self._state_cache._dev
                     if self._state_cache is not None else None))
        self.memledger.register(
            "lora_adapters",
            lambda: (self.adapter_pool.tree
                     if self.adapter_pool is not None else None))
        if self.prefix_cache is not None:
            kv_pool_bytes = tree_nbytes(self.cache)
            per_block = kv_pool_bytes // max(1, ec.num_blocks)
            self.memledger.register_carve(
                "prefix_cache_hbm", "kv_block_pool",
                lambda: self.prefix_cache.num_cached_blocks() * per_block)

    # ------------------------------------------------------------------
    # Executor delegation: scheduler code (and external callers — tests,
    # replicas' NaN-poison fault injection, the memledger owner lambdas)
    # keep addressing device state through the engine; the attributes
    # live on the executor since the scheduler/executor split.
    # ------------------------------------------------------------------
    @property
    def params(self):
        return self.executor.params

    @params.setter
    def params(self, value):
        self.executor.params = value

    @property
    def cache(self):
        return self.executor.cache

    @cache.setter
    def cache(self, value):
        self.executor.cache = value

    @property
    def model(self):
        return self.executor.model

    @property
    def _device(self):
        return self.executor._device

    @property
    def _demote_sharding(self):
        return self.executor._demote_sharding

    @property
    def _quantized(self):
        return self.executor._quantized

    @property
    def adapter_pool(self):
        return self.executor.adapter_pool

    @property
    def _prefill_fns(self):
        return self.executor._prefill_fns

    @property
    def _decode_fn(self):
        return self.executor._decode_fn

    @_decode_fn.setter
    def _decode_fn(self, value):
        self.executor._decode_fn = value

    @property
    def _multi_decode_fns(self):
        return self.executor._multi_decode_fns

    @property
    def _spec_fn(self):
        return self.executor._spec_fn

    def _spec_fn_for(self, k: int):
        return self.executor.spec_fn(k)

    @property
    def _spec_rounds(self):
        return self.executor._spec_rounds

    @property
    def _spec_hist_width(self):
        return self.executor._spec_hist_width

    @property
    def _sample_fn(self):
        return self.executor._sample_fn

    @property
    def _fold_keys(self):
        return self.executor._fold_keys

    def _build_prefill_fn(self, bucket: int):
        return self.executor._build_prefill_fn(bucket)

    def _build_multi_decode_fn(self, num_steps: int):
        return self.executor._build_multi_decode_fn(num_steps)

    def _fetch_block_kv(self, block: int):
        return self.executor.fetch_block_kv(block)

    def _restore_block(self, block: int, payload: dict) -> None:
        self.executor.restore_block(block, payload)

    _aot_or_jit = staticmethod(EngineExecutor._aot_or_jit)

    def _window_steps(self, active: list) -> int:
        """Budget-clamped multi-step window (the r03 occupancy lever).

        A slot that exhausts its token budget at step j of a K-step window
        idles for K-j device steps, and uniform workloads retire whole
        cohorts inside one window — the measured 77.7% decode occupancy at
        the r03 headline (results/serving_7b_report.json). So never run a
        window longer than the smallest PREDICTABLE retirement among
        active slots (max_tokens budget or model-length room; natural EOS
        is unpredictable and still wastes its tail). Window lengths come
        from the halving ladder K, K//2, ..., 1 so the compile surface
        stays ~log2(K)+1 programs instead of one per distinct remainder.
        Side effect: near max_model_len the old batch-wide fallback to
        k=1 becomes a right-sized window instead.
        """
        ec = self.cfg
        # Length retirement fires at prompt+output >= max_model_len
        # (_append_token), which is one step EARLIER than KV room
        # (output leads seq_len by one at dispatch): remaining decode
        # steps until a length stop = max_model_len - (prompt + output).
        min_rem = min(
            min(s.request.params.max_tokens - len(s.request.output_token_ids),
                ec.max_model_len - len(s.request.prompt_token_ids)
                - len(s.request.output_token_ids))
            for s in active)
        # Round UP to the ladder: the smallest ladder length >= min_rem.
        # Rounding down would fragment a 63-step tail into 32+16+8+4+2+1 —
        # five extra host syncs (~0.5 s each on a relay link) to save a
        # handful of dead device steps (~11 ms each). Round-up keeps one
        # window with < k/2 dead steps, and still lands exact fits
        # (min_rem a ladder value) at 100% occupancy.
        k = ec.steps_per_sync
        while k > 1 and k // 2 >= min_rem:
            k //= 2
        # ...but NEVER past hard KV room: dead steps past a budget stop are
        # merely discarded samples, while steps past max_model_len would
        # grow a slot's block table beyond max_blocks_per_seq (an
        # out-of-bounds block-table write). Round DOWN under the room cap.
        min_room = min(ec.max_model_len - s.seq_len for s in active)
        while k > 1 and k > min_room:
            k //= 2
        return k

    def warmup_decode_ladder(self) -> None:
        """Pre-compile the decode programs (single-step + every multi-step
        halving-ladder length) BEFORE traffic: a window length's first use
        otherwise stalls the live decode loop on an XLA compile at an
        unpredictable moment. AOT-lowers on abstract shapes (donation only
        consumes avals here — no scratch KV pool is materialized), then
        KEEPS the compiled executables and swaps them into the dispatch
        path: relying on the persistent compilation cache alone silently
        does nothing when the cache is disabled (DLTI_NO_COMPILE_CACHE=1)
        or the compile finishes under its min-compile-time floor (r04
        advisor finding)."""
        def avals(tree):
            # Carry each leaf's ACTUAL sharding: a ReplicatedEngine pins
            # every replica's params/KV to its own device, and an aval
            # without it lowers for the default device — an executable
            # replica 1 can only reject at dispatch time. Host-mirror
            # args (ids/positions/tables/keys) stay plain avals: they
            # arrive uncommitted and follow the committed operands.
            return jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=getattr(v, "sharding", None)), tree)

        S = self.cfg.max_seqs
        i32, f32, u32 = jnp.int32, jnp.float32, jnp.uint32
        if self._state_cache is not None:
            # The decode-state cache feeds COMMITTED device arrays into
            # the compiled programs; lower with their actual shardings so
            # the AOT executables accept them (same reason params/cache
            # carry theirs). Syncing here is correct at any time — it just
            # brings the resident copies up to date with the mirrors.
            state_avals = avals(self._state_cache.sync(
                self._state_mirrors(), self._masked_rows()))
        else:
            state_avals = (
                jax.ShapeDtypeStruct(self._block_tables.shape, i32),
                jax.ShapeDtypeStruct((S, 2), u32),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S,), f32),
                jax.ShapeDtypeStruct((S,), i32),
                jax.ShapeDtypeStruct((S,), f32))
            if self.adapter_pool is not None:
                state_avals += (jax.ShapeDtypeStruct((S,), i32),)
        args = (avals(self.params), avals(self.cache),
                jax.ShapeDtypeStruct((S, 1), i32),
                jax.ShapeDtypeStruct((S, 1), i32),
                *state_avals)
        if self.adapter_pool is not None:
            args = args + (avals(self.adapter_pool.tree),)
        # Idempotent: a re-warm unwraps back to the raw jit fn (the
        # _aot_or_jit wrapper has no .lower) and rebuilds the executable.
        raw = getattr(self._decode_fn, "_jit_fn", self._decode_fn)
        self._decode_fn = self._aot_or_jit(raw.lower(*args).compile(), raw)
        k = self.cfg.steps_per_sync
        while k > 1:
            fn = self._multi_decode_fns.get(k)
            if fn is None:
                fn = self._build_multi_decode_fn(k)
            raw = getattr(fn, "_jit_fn", fn)
            self._multi_decode_fns[k] = self._aot_or_jit(
                raw.lower(*args).compile(), raw)
            k //= 2

    def _bucket_for(self, n: int) -> int:
        for b in self.cfg.buckets():
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds max_model_len={self.cfg.max_model_len}")

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------
    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "", trace_id: str = "") -> Request:
        """Enqueue a request. Returns immediately; tokens arrive via step().

        ``trace_id`` adopts an upstream-minted distributed-trace context
        (gateway admission, fleet supervisor descriptor); "" mints a
        fresh one — direct clients get traced too.

        ``affinity_key`` is a replica-routing concern (session/prefix
        stickiness — :meth:`ReplicatedEngine.submit`); a single engine
        has nowhere to route, so it is accepted and ignored here to keep
        the two submit surfaces interchangeable.

        ``adapter`` names a catalog-registered LoRA adapter ("" = base
        model); resolution to a pool row — including any checkpoint-store
        load — happens at admission on the stepper thread, keeping this
        method's thread-safety contract intact.

        THREAD-SAFETY CONTRACT (load-bearing): AsyncEngine runs step() on
        its stepper thread *without* holding a lock while HTTP handlers
        call submit() concurrently. That is only sound because submit()
        does nothing beyond (a) one GIL-atomic ``self.waiting.append`` and
        (b) touching its own ``stats["requests"]`` key — no slot, cache,
        block-allocator, or prefix-cache state. Admission consumes
        ``waiting`` at a single point inside step(), so a racing submit
        lands this step or the next. If you add ANY engine-state work here
        (prefix-cache probing, block preallocation, ...), it must move
        into step()-side admission or AsyncEngine must buffer submissions
        on its own lock and hand them over from the stepper thread.
        """
        if not prompt_token_ids:
            raise ValueError("prompt must contain at least one token")
        if len(prompt_token_ids) >= self.cfg.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt_token_ids)} tokens) must be shorter than "
                f"max_model_len={self.cfg.max_model_len}"
            )
        req = Request(
            request_id=request_id or f"req-{next(self._req_counter)}",
            prompt_token_ids=list(prompt_token_ids),
            params=params or SamplingParams(),
            adapter=adapter,
            # A local uuid when no upstream context arrived — no engine
            # state touched, so the thread-safety contract below holds.
            trace_id=trace_id or mint_trace_id(),
        )
        self.waiting.append(req)
        self.stats["requests"] += 1
        # Tracer-only (no engine state): an instant event under the
        # tracer's own lock, a no-op when tracing is disabled — within
        # the thread-safety contract above.
        self.telemetry.on_submitted(req)
        return req

    def resubmit(self, req: Request) -> None:
        """Re-enqueue an EXISTING request (replica failover): the request
        keeps its id, params, arrival time, and generated-so-far tokens —
        admission recomputes prompt+output exactly like re-admission after
        preemption. Same thread-safety contract as :meth:`submit` (one
        GIL-atomic deque append); ``stats["requests"]`` is NOT incremented
        — the request was already counted at first submission.

        The adapter-pool pin does NOT survive failover (the dead
        replica's pool is gone; this engine's pool may not even hold the
        adapter): reset to unresolved so admission re-acquires here —
        ``req.adapter`` itself rides along, so the request finishes
        under the same adapter it started with."""
        req._adapter_slot = -1
        self.waiting.append(req)

    @property
    def num_active(self) -> int:
        return sum(not s.free for s in self.slots)

    @property
    def num_free_blocks(self) -> int:
        return self.block_manager.num_free

    @property
    def spec_acceptance_rate(self) -> float:
        """Cumulative accepted/proposed draft-token ratio (0.0 before any
        proposal) — the dlti_spec_acceptance_rate gauge."""
        p = self.stats.get("spec_proposed", 0)
        return self.stats.get("spec_accepted", 0) / p if p else 0.0

    @property
    def spec_draft_len(self) -> int:
        """Draft length of the last dispatched decode round (0 = the
        round ran plain decode: speculation off, paused, or no greedy
        slot) — the dlti_spec_draft_len gauge."""
        return self._spec_last_k

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_active > 0

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        """Offline batch generation: submit all, step until drained."""
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        by_id = {r.request_id: r for r in reqs}
        return [self._result(by_id[r.request_id]) for r in reqs]

    def step(self) -> List[Request]:
        """One scheduler iteration: retire, admit (prefill), decode.

        Returns requests that finished during this step.
        """
        # Async scheduling: dispatch the decode program FIRST (JAX dispatch
        # is asynchronous — the host gets control back while the device
        # works), then do admission prefills, whose host-side cost (and
        # per-call RTT on relay-attached chips) hides under the in-flight
        # decode; sync decode results last. Admitted slots were free when
        # the decode was dispatched, so its block-table snapshot writes
        # their rows to the trash block — no KV interleaving hazard — and
        # they join the NEXT round's decode batch (their first token comes
        # from prefill sampling either way, so TTFT only improves).
        tr = self._tracer
        try:
            pending = None
            if not self.prefill_only and any(
                    not s.free and not s.prefilling for s in self.slots):
                with tr.span("engine/decode_dispatch", cat="engine"):
                    pending = self._decode_dispatch()
            with tr.span("engine/admit", cat="engine"):
                self._admit()
            if self.cfg.max_prefill_tokens_per_step > 0:
                with tr.span("engine/prefill_chunks", cat="engine"):
                    self._prefill_work()
            if pending is None:
                return []
            with tr.span("engine/decode_sync", cat="engine"):
                return self._decode_complete(pending)
        except Exception as e:
            if is_oom_error(e):
                # OOM forensics: file the black box as an OOM (with
                # memory.json carrying the ownership map at death) before
                # the fault propagates to the replica/server layer.
                rec = get_recorder()
                if rec is not None:
                    rec.dump(reason="oom", force=True, exc=e,
                             extra={"where": "engine_step"})
            raise

    # ------------------------------------------------------------------
    # Scheduling internals
    # ------------------------------------------------------------------
    def _alloc(self, n: int) -> Optional[List[int]]:
        """Allocate blocks, evicting LRU cached prefixes under pressure."""
        if self.prefix_cache is not None:
            return self.prefix_cache.allocate(n)
        return self.block_manager.allocate(n)

    def _admit(self) -> None:
        """Admit waiting requests into free slots via bucketed prefill.

        Admissions collected in one pass are prefilled in *batched*
        program calls (grouped by suffix bucket): on a deep queue the
        admission stall is a handful of model calls instead of one per
        request — the dominant TTFT term once decode windows are long.
        """
        # Headroom-aware admission (telemetry.memledger): under HBM
        # pressure (a fragmented allocator, a co-tenant balloon, a tier
        # restore burst), DEFER the whole admission pass rather than
        # prefill into memory that is about to run out — the queue holds
        # the requests, the next step retries, and the client sees
        # latency, never an error. Gating needs a known capacity; when
        # capacity is unknown (CPU without a budget) it stays off.
        if (self.memledger.enabled
                and self.cfg.admit_min_headroom_frac > 0 and self.waiting):
            snap = self.memledger.snapshot()
            cap = snap.get("capacity_bytes", 0)
            headroom = snap.get("headroom_bytes")
            if (cap and headroom is not None
                    and headroom < self.cfg.admit_min_headroom_frac * cap):
                self.stats["hbm_deferred_admissions"] += 1
                return

        admissions: List[tuple] = []
        for slot in self.slots:
            # Cancelled while queued (disconnect before admission): finish
            # without ever taking a slot or prefilling.
            while self.waiting and self.waiting[0].cancel_requested:
                req = self.waiting.popleft()
                # A queue-head request may hold an adapter pin from an
                # earlier pass that then broke on block exhaustion.
                self._release_adapter(req)
                req.finish_reason = "stop"
                req.finish_time = time.monotonic()
                self.finished.append(req)
                self.telemetry.on_finished(req)
            if not self.waiting or not slot.free:
                continue
            req = self.waiting[0]
            # Resolve the request's adapter to a pool row BEFORE any
            # block work: a pool-full miss leaves the request queued
            # (FCFS, the KV-exhaustion contract), a load failure fails
            # THIS request without touching engine state, and a hit/load
            # pins the row until the slot releases. Idempotent across
            # passes via the -1 sentinel (a pass that pinned the row but
            # broke on blocks does not re-acquire).
            if req._adapter_slot < 0:
                if not req.adapter:
                    req._adapter_slot = 0
                elif self.adapter_pool is None:
                    self.waiting.popleft()
                    self._fail_waiting(
                        req, f"request names adapter {req.adapter!r} but "
                        "the engine has no adapter pool "
                        "(adapter_slots=0)")
                    continue
                else:
                    t_ad = time.monotonic()
                    try:
                        row, loaded = self.adapter_pool.acquire(req.adapter)
                    except AdapterError as e:
                        self.waiting.popleft()
                        self._fail_waiting(req, str(e))
                        continue
                    if row < 0:
                        break  # every row pinned: FCFS, retry next step
                    req._adapter_slot = row
                    if loaded:
                        # A pool-miss load is restore work on THIS
                        # request's critical path (telemetry.ledger) —
                        # same phase as a tier restore, and visibly NOT
                        # queueing or prefill.
                        now = time.monotonic()
                        req.restore_s += now - t_ad
                        self._tracer.complete(
                            "engine/adapter_load", t_ad, now, cat="engine",
                            id=req.request_id, adapter=req.adapter)
            tokens = req.prompt_token_ids + req.output_token_ids
            cached_blocks: List[int] = []
            n_cached = 0
            tier_keys: List[tuple] = []
            if self.prefix_cache is not None:
                # Chain keys are namespaced by the request's adapter: the
                # same prompt under two adapters produces different KV,
                # so cross-adapter block reuse would be silent corruption.
                cached_blocks, n_cached = self.prefix_cache.match_prefix(
                    tokens, ns=req.adapter or None)
                # Pin the matched blocks BEFORE allocating the suffix —
                # otherwise the allocation's own eviction could reclaim them.
                self.prefix_cache.acquire(cached_blocks)
                # Continue the chain into host/disk tiers: these keys'
                # payloads restore into freshly allocated blocks below
                # (a restore scatter instead of a re-prefill).
                tier_keys = self.prefix_cache.match_tiers(
                    tokens, len(cached_blocks), ns=req.adapter or None)
            need = (self.block_manager.blocks_needed(len(tokens) + 1)
                    - len(cached_blocks))
            blocks = self._alloc(need)
            if blocks is None:
                if cached_blocks:
                    self.prefix_cache.release(cached_blocks)
                break  # head-of-line blocking: FCFS, no starvation
            restored_by_tier: Dict[str, int] = {}
            n_restored = 0
            t_restore = time.monotonic() if tier_keys else 0.0
            for j, key in enumerate(tier_keys):
                # The alloc's own evictions may have demoted MORE blocks
                # since the match, but never removed these keys (puts
                # only add); a fetch can still miss if the alloc cascaded
                # them off the bounded disk tier, or fail verification —
                # either way the chain stops and the rest prefills.
                payload, tier = self.prefix_cache.fetch_restore(key)
                if payload is None:
                    break
                self._restore_block(blocks[j], payload)
                self.prefix_cache.register_restored(key, blocks[j])
                restored_by_tier[tier] = restored_by_tier.get(tier, 0) + 1
                n_restored += 1
            if n_restored:
                # Charge the tier fetch + restore dispatch to THIS
                # request's critical path (telemetry.ledger): a warm-tier
                # admission's TTFT decomposes into restore vs prefill.
                now = time.monotonic()
                req.restore_s += now - t_restore
                self._tracer.complete(
                    "engine/tier_restore", t_restore, now, cat="engine",
                    id=req.request_id, blocks=n_restored)
            if self.prefix_cache is not None:
                self.stats["prefix_cached_tokens"] += n_cached
                self.stats["prefix_restored_tokens"] += \
                    n_restored * self.cfg.block_size
                self.prefix_cache.record_admission(cached_blocks,
                                                   restored_by_tier)
            self.waiting.popleft()
            n_prefix = n_cached + n_restored * self.cfg.block_size
            admissions.append((slot, req, cached_blocks + blocks, n_prefix))

        if self.cfg.max_prefill_tokens_per_step > 0:
            # Chunked mode: register now, prefill in bounded chunks from
            # _prefill_work — decode slots never stall for a prompt length.
            for slot, req, blocks, n_cached in admissions:
                tokens = req.prompt_token_ids + req.output_token_ids
                self._register_slot(slot, req, blocks, len(tokens))
                slot.next_pos = n_cached  # _register_slot set it to the end
            return

        suffix_lens = [len(req.prompt_token_ids) + len(req.output_token_ids)
                       - n_cached
                       for _slot, req, _blocks, n_cached in admissions]
        if self.cfg.ragged_prefill:
            # Ragged: one call advances admissions of MIXED suffix lengths
            # (group width = widest member's bucket, padding bounded) —
            # a heterogeneous admission wave stops costing one program
            # call (and one jit specialization) per distinct bucket.
            for width, group in self._ragged_groups(admissions, suffix_lens):
                self._prefill_group(width, group)
            return
        by_bucket: Dict[int, List[tuple]] = {}
        for adm, suffix_len in zip(admissions, suffix_lens):
            by_bucket.setdefault(self._bucket_for(suffix_len), []).append(adm)
        for bucket, group in by_bucket.items():
            # Chunk very wide admission waves: past ~8 rows the batched
            # program's marginal win flattens while its padded work and
            # jit-shape surface keep growing.
            for i in range(0, len(group), 8):
                self._prefill_group(bucket, group[i:i + 8])

    def _prefill_work(self) -> None:
        """Chunked prefill: spend up to ``max_prefill_tokens_per_step``
        prompt tokens on partially-prefilled slots (FCFS by arrival), in
        per-bucket batched program calls. A slot whose suffix completes
        samples its first token and joins the next decode step."""
        budget = self.cfg.max_prefill_tokens_per_step
        chunks: List[tuple] = []  # (slot, tokens, start_pos, is_last)
        for slot in sorted((s for s in self.slots if s.prefilling),
                           key=lambda s: s.request.arrival_time):
            if budget <= 0:
                break
            req = slot.request
            remaining = slot.prefill_end - slot.next_pos
            take = min(remaining, budget)
            # Position p holds (prompt + output)[p], so the chunk is an
            # index slice — no per-slot token copy is carried between steps.
            tokens = req.prompt_token_ids + req.output_token_ids
            piece = tokens[slot.next_pos: slot.next_pos + take]
            chunks.append((slot, piece, slot.next_pos, take == remaining))
            slot.next_pos += take
            budget -= take
        if self.cfg.ragged_prefill:
            for width, group in self._ragged_groups(
                    chunks, [len(c[1]) for c in chunks]):
                self._run_prefill_batch(width, group)
            return
        by_bucket: Dict[int, List[tuple]] = {}
        for ch in chunks:
            by_bucket.setdefault(self._bucket_for(len(ch[1])), []).append(ch)
        for bucket, group in by_bucket.items():
            for i in range(0, len(group), 8):
                self._run_prefill_batch(bucket, group[i:i + 8])

    def _ragged_groups(self, items: List, lengths: List[int]) -> List[tuple]:
        """FCFS ragged packing for multi-admission prefill: ``(width,
        members)`` groups where width is the widest member's pow2 bucket.

        A group closes at 8 rows (same flattening point as the bucketed
        path) or when its padded footprint — pow2-padded row count times
        group width — would exceed twice the members' own bucketed token
        work. The 2x bound is the padding overhead the bucketed path
        already tolerates per row, accounted group-wide: short chunks
        pack behind a long one only while the wasted lanes stay cheaper
        than a second program dispatch. Rows keep their own positions,
        block tables, and last-token indices, so grouping choice never
        changes any row's output (byte-identical ragged on/off)."""
        groups: List[tuple] = []
        cur: List = []
        wid = real = 0
        for it, ln in zip(items, lengths):
            w = self._bucket_for(ln)
            nwid = max(wid, w)
            nreal = real + w
            rows_pow2 = 1
            while rows_pow2 < len(cur) + 1:
                rows_pow2 *= 2
            if cur and (len(cur) >= 8 or rows_pow2 * nwid > 2 * nreal):
                groups.append((wid, cur))
                cur = []
                nwid, nreal = w, w
            cur.append(it)
            wid, real = nwid, nreal
        if cur:
            groups.append((wid, cur))
        return groups

    def _register_slot(self, slot: _Slot, req: Request, blocks: List[int],
                       n: int) -> None:
        """Host-side bookkeeping for an admitted request (block table row,
        sampling params, per-slot key + generated-token count)."""
        ec = self.cfg
        self.telemetry.on_admitted(req)
        slot.request = req
        slot.blocks = blocks
        slot.seq_len = n
        # Fully prefilled by default (throughput mode); the chunked-admit
        # path rewinds next_pos to the cached-prefix boundary.
        slot.next_pos = n
        slot.prefill_end = n
        row = np.zeros((ec.max_blocks_per_seq,), np.int32)
        row[: len(blocks)] = blocks
        self._block_tables[slot.slot_id] = row
        self._temperature[slot.slot_id] = req.params.temperature
        self._top_k[slot.slot_id] = req.params.top_k
        self._top_p[slot.slot_id] = req.params.top_p
        if req.params.seed is not None:
            key = jax.random.PRNGKey(req.params.seed)
        else:
            self._rng, key = jax.random.split(self._rng)
        self._slot_keys[slot.slot_id] = np.asarray(jax.random.key_data(key)
                                                   if jnp.issubdtype(key.dtype, jax.dtypes.prng_key)
                                                   else key, np.uint32)
        # Count of tokens generated so far (nonzero on re-admission after
        # preemption, so the seeded draw stream continues where it left off).
        self._gen_counts[slot.slot_id] = len(req.output_token_ids)
        # max(.., 0): requests that never resolved a pool row (no pool,
        # handoff adoption of a base request) decode under row 0, the
        # all-zero base adapter.
        self._adapter_ids[slot.slot_id] = max(req._adapter_slot, 0)
        self._mark_state_dirty(slot.slot_id)
        if self._spec_hist is not None:
            ctx = req.prompt_token_ids + req.output_token_ids
            self._spec_hist[slot.slot_id, :len(ctx)] = ctx

    def _prefill_group(self, bucket: int, group: List[tuple]) -> None:
        """Batched bucketed prefill: one program call for every admission
        sharing a suffix bucket (throughput mode: whole suffixes at once).

        On re-admission after preemption the generated-so-far tokens are
        part of the recomputed prompt (vLLM recompute semantics); with a
        prefix-cache hit only the suffix past the cached blocks is
        prefilled.
        """
        chunks = []
        for slot, req, blocks, n_cached in group:
            tokens = req.prompt_token_ids + req.output_token_ids
            self._register_slot(slot, req, blocks, len(tokens))
            chunks.append((slot, tokens[n_cached:], n_cached, True))
        self._run_prefill_batch(bucket, chunks)

    def _run_prefill_batch(self, bucket: int, chunks: List[tuple]) -> None:
        """One prefill program call over ``chunks``: rows of
        ``(slot, tokens, start_pos, is_last)`` sharing a length bucket.

        Rows are padded to a power of two — padding rows carry position -1
        everywhere, which slot_mapping turns into dropped writes. Each
        *final* chunk's first generated token is sampled from its last
        real logit in one batched sample call; non-final chunks (chunked
        prefill) write KV only.
        """
        ec = self.cfg
        B = 1
        while B < len(chunks):
            B *= 2
        nblk_needed = 1
        for slot, tokens, start, _ in chunks:
            nblk_needed = max(nblk_needed, self.block_manager.blocks_needed(
                start + len(tokens)))
        # Block-table width quantized so jit specializations stay
        # O(log^2) over (suffix bucket, table bucket) x O(log) batch.
        nblk_bucket = 1
        while nblk_bucket < nblk_needed:
            nblk_bucket *= 2
        nblk_bucket = min(nblk_bucket, ec.max_blocks_per_seq)

        # Program dispatches — with ragged packing this is the number a
        # multi-admission wave is supposed to shrink.
        self.stats["prefill_batches"] += 1
        ids = np.zeros((B, bucket), np.int32)
        pos = np.full((B, bucket), -1, np.int32)  # -1 -> write dropped
        bt = np.zeros((B, nblk_bucket), np.int32)
        last_idx = np.zeros((B,), np.int32)
        slot_keys = np.zeros((B, 2), np.uint32)
        counts = np.zeros((B,), np.int32)
        temps = np.ones((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        for r, (slot, tokens, start, is_last) in enumerate(chunks):
            req = slot.request
            ids[r, : len(tokens)] = tokens
            pos[r, : len(tokens)] = np.arange(start, start + len(tokens))
            bt[r, : min(len(slot.blocks), nblk_bucket)] = \
                slot.blocks[:nblk_bucket]
            last_idx[r] = len(tokens) - 1
            slot_keys[r] = self._slot_keys[slot.slot_id]
            counts[r] = self._gen_counts[slot.slot_id]
            temps[r] = req.params.temperature
            top_k[r] = req.params.top_k
            top_p[r] = req.params.top_p
            self.stats["prefill_tokens"] += len(tokens)

        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = self._build_prefill_fn(bucket)
        lora_args = ()
        if self.adapter_pool is not None:
            ad = np.zeros((B,), np.int32)
            for r, (slot, *_rest) in enumerate(chunks):
                ad[r] = self._adapter_ids[slot.slot_id]
            lora_args = (jnp.asarray(ad), self.adapter_pool.tree)
        self.cache, last_logits = self._prefill_fns[bucket](
            self.params, self.cache, jnp.asarray(ids), jnp.asarray(pos),
            jnp.asarray(bt), jnp.asarray(last_idx), *lora_args,
        )
        if not any(is_last for *_, is_last in chunks):
            return  # mid-prompt chunks: KV writes only, nothing to sample
        # Same per-slot key + count stream the decode path uses, folded in
        # one async dispatch (no host round trip per row).
        keys = self._fold_keys(jnp.asarray(slot_keys), jnp.asarray(counts))
        toks, lps = self._sample_fn(
            last_logits, keys, jnp.asarray(temps),
            jnp.asarray(top_k), jnp.asarray(top_p),
        )
        toks = np.asarray(jax.device_get(toks))
        lps = np.asarray(jax.device_get(lps))
        if self.cfg.guard_nonfinite:
            bad = [slot.slot_id
                   for r, (slot, *_rest, is_last) in enumerate(chunks)
                   if is_last and not np.isfinite(lps[r])]
            if bad:
                # First-token guard: a numerically-dead model's prefill
                # sample must not stream either (failover's resubmit
                # preserves generated-so-far tokens).
                self.stats["numeric_faults"] += 1
                raise NumericFault(
                    f"nonfinite prefill output on slot(s) {bad}: the "
                    f"model is producing NaN/inf logits")
        for r, (slot, tokens, start, is_last) in enumerate(chunks):
            if is_last:
                self._append_token(slot, int(toks[r]), float(lps[r]))
                # Prefill completion: the first sampled token bumped the
                # slot's gen count, and a chunked-mode slot's block-table
                # row sheds its trash-block masking — either way the row
                # must re-upload before the slot joins the decode batch.
                self._mark_state_dirty(slot.slot_id)

    def _mark_state_dirty(self, slot_id: int) -> None:
        """A scheduling event changed ``slot_id``'s per-slot state mirrors
        (admission, release, block growth, prefill completion): the next
        decode dispatch must re-upload that row."""
        if self._state_cache is not None:
            self._state_cache.mark_dirty(slot_id)

    def _state_mirrors(self) -> dict:
        return {"block_tables": self._block_tables,
                "slot_keys": self._slot_keys,
                "gen_counts": self._gen_counts,
                "temperature": self._temperature,
                "top_k": self._top_k, "top_p": self._top_p,
                "adapter_ids": self._adapter_ids}

    def _masked_rows(self) -> list:
        return [s.slot_id for s in self.slots if s.prefilling]

    def _decode_block_tables(self) -> np.ndarray:
        """Block tables as the decode-side programs may see them: rows of
        partially-prefilled slots are zeroed (the reserved trash block), so
        a decode call can never scribble on KV those slots have written —
        decode fills their rows with position 0, and block 0 absorbs it."""
        if not any(s.prefilling for s in self.slots):
            return self._block_tables
        bt = self._block_tables.copy()
        for s in self.slots:
            if s.prefilling:
                bt[s.slot_id] = 0
        return bt

    def _decode_dispatch(self):
        """Schedule this round's decode work and dispatch its program call
        WITHOUT syncing: returns an opaque pending tuple whose device
        arrays are still being computed, for :meth:`_decode_complete`.
        All host mirrors are snapshotted here (jnp.asarray copies at call
        time), so admission may mutate them while the call is in flight."""
        ec = self.cfg
        # Multi-step windows are budget-clamped per round (_window_steps):
        # max_model_len safety lives in its min(...) term, so there is no
        # batch-wide all-or-nothing room gate anymore. Prefilling slots are
        # admitted but not yet decodable: excluded everywhere below, with
        # their block-table rows masked to the trash block.
        k_steps = 1
        active0 = [s for s in self.slots if not s.free and not s.prefilling]
        # Speculative decode engages per ROUND when any active greedy slot
        # is unpaused (per-slot gating: _spec_round_gate ticks cooldowns
        # and returns this round's participants, and the program masks the
        # rest to single-step) and every active slot has room for the
        # worst-case window at the SELECTED draft length. When every
        # greedy slot is paused the round falls back to plain multi-step —
        # the (k+1)-wide verify forwards would be pure overhead.
        # Trade-off: the room check is batch-wide (R is compile-static),
        # so one slot within R*(k+1) tokens of max_model_len falls the
        # whole batch back to plain multi-step until it retires — at most
        # its last R*(k+1) decode rounds. A per-slot R would need one
        # compiled variant per window size; not worth the compile surface.
        spec_parts: list = []
        spec_k = 0
        if self._spec_fn is not None and active0:
            spec_parts = self._spec_round_gate(active0)
        if spec_parts:
            spec_k = self._spec_pick_k(spec_parts)
        spec_window = self._spec_rounds * (spec_k + 1)
        use_spec = bool(spec_parts) and all(
            s.seq_len + spec_window <= ec.max_model_len for s in active0)
        self._spec_last_k = spec_k if use_spec else 0
        if use_spec:
            k_steps = spec_window  # block-growth window
        elif ec.steps_per_sync > 1 and active0:
            k_steps = self._window_steps(active0)

        # Grow block tables to cover the decode window; preempt the
        # youngest if the pool is exhausted. (Prefilling slots already own
        # blocks for prompt+1 from admission and are not decoding yet.)
        def grow_tables(win_steps: int, spec: bool) -> bool:
            for slot in sorted(
                (s for s in self.slots if not s.free and not s.prefilling),
                key=lambda s: s.request.arrival_time,
            ):
                if slot.free:  # preempted by an earlier iteration
                    continue
                window = win_steps
                if spec and slot.request.params.temperature != 0.0:
                    # Sampling slots advance exactly one real token per
                    # spec round; their draft-position writes past that
                    # land on the trash block (unallocated table entries
                    # are 0), so don't allocate — and possibly preempt
                    # for — the full window.
                    window = self._spec_rounds
                need = self.block_manager.blocks_needed(
                    slot.seq_len + window)
                while need > len(slot.blocks):
                    got = self._alloc(1)
                    if got is None:
                        if not self._preempt_youngest(exclude=slot):
                            return False
                        continue
                    slot.blocks.extend(got)
                    self._block_tables[
                        slot.slot_id, len(slot.blocks) - 1] = got[0]
                    self._mark_state_dirty(slot.slot_id)
            return True

        if not grow_tables(k_steps, use_spec):
            if k_steps > 1:
                # Defer, don't fault: a multi-step window that cannot
                # reserve its worst-case blocks shrinks to a single-step
                # round (blocks already granted stay on their slots and
                # carry over; table rows past the shrunk window are never
                # read). One block per active slot is guaranteed by the
                # admission-time max_blocks_per_seq check, so win=1 can
                # only fail on genuine exhaustion.
                self.stats["hbm_growth_deferrals"] += 1
                use_spec = False
                self._spec_last_k = 0
                k_steps = 1
            if not grow_tables(k_steps, use_spec):
                raise RuntimeError(
                    "KV pool exhausted and nothing to preempt; "
                    "increase num_blocks or lower max_seqs"
                )

        active = [s for s in self.slots
                  if not s.free and not s.prefilling]
        if not active:
            return None
        if use_spec:
            return self._spec_dispatch(active, spec_parts, spec_k)

        t_prep = time.perf_counter()
        ids = np.zeros((ec.max_seqs, 1), np.int32)
        pos = np.zeros((ec.max_seqs, 1), np.int32)  # inactive -> trash block
        for s in active:
            ids[s.slot_id, 0] = s.last_token
            pos[s.slot_id, 0] = s.seq_len  # position of the new token
        if self._state_cache is not None:
            # Device-resident per-slot state: only rows dirtied since the
            # last dispatch are shipped; a clean step uploads nothing and
            # _decode_block_tables' full rebuild becomes a row update.
            state_args = self._state_cache.sync(
                self._state_mirrors(), self._masked_rows())
        else:
            state_args = (
                jnp.asarray(self._decode_block_tables()),
                jnp.asarray(self._slot_keys),
                jnp.asarray(self._gen_counts),
                jnp.asarray(self._temperature), jnp.asarray(self._top_k),
                jnp.asarray(self._top_p),
            )
            if self.adapter_pool is not None:
                state_args += (jnp.asarray(self._adapter_ids),)
        args = (self.params, self.cache, jnp.asarray(ids), jnp.asarray(pos),
                *state_args)
        if self.adapter_pool is not None:
            # The pool tree rides LAST; NOT donated — an in-flight async
            # window may still read the previous buffers, and a one-row
            # scatter (acquire miss) rebinds pool.tree between windows.
            args = args + (self.adapter_pool.tree,)
        # Host prep cost of this dispatch (batch assembly + state sync) —
        # the term dirty tracking is meant to hold flat as max_seqs grows.
        self.telemetry.host_prep.observe(time.perf_counter() - t_prep)
        if k_steps > 1:
            fn = self._multi_decode_fns.get(k_steps)
            if fn is None:
                fn = self._multi_decode_fns[k_steps] = \
                    self._build_multi_decode_fn(k_steps)
            self.cache, tokens, logprobs = fn(*args)
        else:
            self.cache, tokens, logprobs = self._decode_fn(*args)
            tokens = tokens[:, None]
            logprobs = logprobs[:, None]
        if self._state_cache is not None:
            # The window advances every surviving slot's gen count by
            # exactly k_steps (a slot finishing mid-window is released,
            # which marks it dirty) — advance the resident counts on
            # device instead of re-uploading the one every-step mirror.
            self._state_cache.bump_gen_counts(k_steps)
        return ("plain", active, k_steps, tokens, logprobs)

    def _decode_complete(self, pending) -> List[Request]:
        """Sync a dispatched decode round's results and walk emissions."""
        if pending[0] == "spec":
            return self._spec_complete(pending)
        _, active, k_steps, tokens, logprobs = pending
        tokens = np.asarray(jax.device_get(tokens))      # (S, k_steps)
        logprobs = np.asarray(jax.device_get(logprobs))
        self.stats["decode_steps"] += k_steps

        # Numeric guard — the WHOLE round is validated before any token
        # is appended: a partially-appended round would survive failover
        # (resubmit keeps generated-so-far tokens) and stream garbage.
        if self.cfg.guard_nonfinite:
            bad = [s.slot_id for s in active
                   if not np.isfinite(logprobs[s.slot_id, :k_steps]).all()]
            if bad:
                self.stats["numeric_faults"] += 1
                raise NumericFault(
                    f"nonfinite decode output on slot(s) {bad} "
                    f"(window of {k_steps} step(s)): the model is "
                    f"producing NaN/inf logits")
        if self.cfg.guard_token_storm > 0 and len(active) >= 2:
            for k in range(k_steps):
                col = {int(tokens[s.slot_id, k]) for s in active}
                self._storm_run = self._storm_run + 1 if len(col) == 1 \
                    else 0
                if self._storm_run >= self.cfg.guard_token_storm:
                    self.stats["numeric_faults"] += 1
                    raise NumericFault(
                        f"token storm: every active slot sampled the "
                        f"same token for {self._storm_run} consecutive "
                        f"steps (token {col.pop()})")

        finished = []
        for s in active:
            for k in range(k_steps):
                # Per-step occupancy: a slot that hits EOS mid-window
                # stops counting here, so occupancy stays honest at large
                # steps_per_sync (the device still runs the dead steps —
                # that waste shows up as occupancy < 100%, as it should).
                self.stats["decode_slot_steps"] += 1
                s.seq_len += 1  # the input token is now in the cache
                done = self._append_token(s, int(tokens[s.slot_id, k]),
                                          float(logprobs[s.slot_id, k]))
                if done:
                    # Tokens sampled after EOS/limit in this window are
                    # discarded (their stale KV writes sit past seq_len in
                    # the freed tail blocks — never registered or read).
                    finished.append(s.request)
                    break
        return finished

    def _spec_round_gate(self, active: List["_Slot"]) -> List["_Slot"]:
        """Per-slot adaptive acceptance gate (``spec_min_acceptance``):
        tick each paused greedy slot's cooldown and return the greedy
        slots allowed to propose this round. A slot in cooldown rides the
        spec program masked to single-step (or the plain path, when every
        greedy slot is paused at once) — its batchmates keep speculating
        either way. ``spec_paused_rounds`` counts paused SLOT-rounds."""
        gate_on = self.cfg.spec_min_acceptance > 0.0
        out = []
        for s in active:
            if s.request.params.temperature != 0.0:
                continue
            sid = s.slot_id
            if gate_on and self._spec_slot_pause[sid] > 0:
                self._spec_slot_pause[sid] -= 1
                self.stats["spec_paused_rounds"] += 1
            else:
                out.append(s)
        return out

    def _spec_pick_k(self, parts: List["_Slot"]) -> int:
        """Draft length for this round, from the halving ladder
        (num_draft_tokens, /2, ..., 1): the smallest ladder member with
        one token of probe slack over the most optimistic participant's
        smoothed acceptance estimate. The slack is what lets the estimate
        climb back up — at the saturating k the estimate caps at k, and
        wanting k+1 selects the next rung. spec_adaptive=False pins the
        pre-ladder behavior (always the full draft)."""
        kmax = self.cfg.num_draft_tokens
        if not self.cfg.spec_adaptive:
            return kmax
        est = max(self._spec_slot_ewma[s.slot_id] for s in parts)
        want = min(kmax, int(np.ceil(est)) + 1)
        ladder = []
        kk = kmax
        while kk >= 1:
            ladder.append(kk)
            kk //= 2
        for kk in reversed(ladder):
            if kk >= want:
                return kk
        return kmax

    def _spec_note_slot(self, sid: int) -> None:
        """Close a slot's probe window when full: a window of mostly-
        rejected drafts pauses THAT slot for ``spec_cooldown`` rounds."""
        if (self.cfg.spec_min_acceptance > 0.0
                and self._spec_slot_prop[sid] >= self.cfg.spec_probe_window):
            rate = self._spec_slot_acc[sid] / self._spec_slot_prop[sid]
            if rate < self.cfg.spec_min_acceptance:
                self._spec_slot_pause[sid] = self.cfg.spec_cooldown
            self._spec_slot_prop[sid] = 0
            self._spec_slot_acc[sid] = 0

    def _spec_reset_slot(self, sid: int) -> None:
        self._spec_slot_prop[sid] = 0
        self._spec_slot_acc[sid] = 0
        self._spec_slot_pause[sid] = 0
        self._spec_slot_ewma[sid] = float(self.cfg.num_draft_tokens)

    def _spec_dispatch(self, active: List[_Slot], parts: List[_Slot],
                       k: int):
        """Dispatch the fused propose→verify→accept program (no sync).

        ``parts`` are the greedy slots allowed to propose this round
        (per-slot gate output); everyone else — sampling slots and greedy
        slots in cooldown — is masked to single-step inside the program.
        ``k`` is the ladder draft length picked for this round."""
        ec = self.cfg
        if self._state_cache is not None:
            # The spec path ships the mirrors directly (it uploads the
            # full token history anyway) and emits a variable number of
            # tokens per slot — the resident copies are stale wholesale
            # after this round.
            self._state_cache.mark_all_dirty()
        R = self._spec_rounds
        t_in = np.zeros((ec.max_seqs,), np.int32)
        seq_len = np.zeros((ec.max_seqs,), np.int32)
        spec_mask = np.zeros((ec.max_seqs,), np.bool_)
        for s in active:
            t_in[s.slot_id] = s.last_token
            seq_len[s.slot_id] = s.seq_len
        for s in parts:
            spec_mask[s.slot_id] = True
        # Multi-query attention takes the gather path (the Pallas paged
        # kernel is single-token); bound its window to the blocks the
        # whole spec window can touch, quantized pow2 so jit
        # specializations stay O(log).
        nblk = max(self.block_manager.blocks_needed(s.seq_len + R * (k + 1))
                   for s in active)
        width = 1
        while width < nblk:
            width *= 2
        width = min(width, ec.max_blocks_per_seq)
        lora_args = ()
        if self.adapter_pool is not None:
            lora_args = (jnp.asarray(self._adapter_ids),
                         self.adapter_pool.tree)
        self.cache, toks, lps, emit, prop, acc = self._spec_fn_for(k)(
            self.params, self.cache, jnp.asarray(self._spec_hist), jnp.asarray(t_in),
            jnp.asarray(seq_len), jnp.asarray(spec_mask),
            jnp.asarray(self._decode_block_tables()[:, :width]),
            jnp.asarray(self._slot_keys), jnp.asarray(self._gen_counts),
            jnp.asarray(self._temperature), jnp.asarray(self._top_k),
            jnp.asarray(self._top_p), *lora_args,
        )
        return ("spec", active, spec_mask, toks, lps, emit, prop, acc)

    def _spec_complete(self, pending) -> List[Request]:
        """Sync a dispatched spec round and walk its emissions. Per slot
        per round the device reports how many tokens were emitted (greedy:
        accepted prefix + bonus; sampling: exactly one); the host consumes
        them in order, stopping a slot at EOS/limit and discarding the
        rest of its window (same contract as multi-step decode)."""
        _, active, spec_mask, toks, lps, emit, prop, acc = pending
        R = self._spec_rounds
        toks = np.asarray(jax.device_get(toks))   # (S, R, k+1)
        lps = np.asarray(jax.device_get(lps))
        emit = np.asarray(jax.device_get(emit))   # (S, R)
        prop = np.asarray(jax.device_get(prop))
        acc = np.asarray(jax.device_get(acc))
        self.stats["decode_steps"] += R

        # Numeric guard over every EMITTED token (rejected draft
        # positions legitimately carry junk), before anything appends —
        # same no-garbage-survives-failover contract as plain decode.
        if self.cfg.guard_nonfinite:
            bad = [s.slot_id for s in active
                   if any(not np.isfinite(
                       lps[s.slot_id, r, :int(emit[s.slot_id, r])]).all()
                       for r in range(R))]
            if bad:
                self.stats["numeric_faults"] += 1
                raise NumericFault(
                    f"nonfinite speculative-decode output on slot(s) "
                    f"{bad}: the model is producing NaN/inf logits")

        finished = []
        for s in active:
            sid = s.slot_id
            # Only unmasked greedy slots actually proposed this round —
            # masked slots (sampling, or greedy in cooldown) ran single-
            # step and must not feed the acceptance windows.
            proposing = bool(spec_mask[sid])
            done = False
            for r in range(R):
                # Per-round occupancy (see _decode_complete): rounds after
                # a slot finishes mid-window don't count as occupied.
                self.stats["decode_slot_steps"] += 1
                if proposing:
                    self._spec_slot_prop[sid] += 1
                    self._spec_slot_acc[sid] += int(emit[sid, r]) - 1
                    # Smoothed accepted-drafts-per-round estimate for the
                    # draft-length ladder (rounds with no lookup hit pull
                    # it toward 0, as they should).
                    self._spec_slot_ewma[sid] += 0.2 * (
                        int(acc[sid, r]) - self._spec_slot_ewma[sid])
                    self.stats["spec_proposed"] += int(prop[sid, r])
                    self.stats["spec_accepted"] += int(acc[sid, r])
                for j in range(int(emit[sid, r])):
                    s.seq_len += 1
                    done = self._append_token(s, int(toks[sid, r, j]),
                                              float(lps[sid, r, j]))
                    if done:
                        finished.append(s.request)
                        break
                if done:
                    break
            if proposing and not done:
                self._spec_note_slot(sid)
        return finished

    def _append_token(self, slot: _Slot, token: int, logprob: float) -> bool:
        """Record a generated token; retire the slot when finished."""
        req = slot.request
        now = time.monotonic()
        if req.first_token_time is None:
            req.first_token_time = now
            self.telemetry.on_first_token(req)
        req.output_token_ids.append(token)
        req.output_logprobs.append(logprob)
        slot.last_token = token
        if self._spec_hist is not None:
            self._spec_hist[slot.slot_id, len(req.prompt_token_ids)
                            + len(req.output_token_ids) - 1] = token
        self._gen_counts[slot.slot_id] = len(req.output_token_ids)
        self.stats["generated_tokens"] += 1

        reason = None
        if req.cancel_requested:
            # Server-side early cancel (stop-string hit, disconnect):
            # finish as a normal stop so usage/latency accounting and
            # slot release follow the standard path.
            reason = "stop"
        elif token == self.cfg.eos_token_id or token in req.params.stop_token_ids:
            reason = "stop"
        elif len(req.output_token_ids) >= req.params.max_tokens:
            reason = "length"
        elif len(req.prompt_token_ids) + len(req.output_token_ids) >= self.cfg.max_model_len:
            reason = "length"
        if reason is not None:
            req.finish_reason = reason
            req.finish_time = now
            self.finished.append(req)
            self.telemetry.on_finished(req)
            self._release(slot)
            return True
        return False

    def _release_adapter(self, req: Request) -> None:
        """Drop the request's adapter-pool pin and reset it to unresolved
        (idempotent). Preemption and failover re-acquire at re-admission
        — the row may legitimately be LRU-evicted in between."""
        if self.adapter_pool is not None and req._adapter_slot > 0:
            self.adapter_pool.release(req._adapter_slot)
        req._adapter_slot = -1

    def _fail_waiting(self, req: Request, msg: str) -> None:
        """Finish a not-yet-admitted request as an error (unknown or
        corrupt adapter): strictly request-scoped — the engine, its
        slots, and the rest of the queue are untouched."""
        self.logger.warning("request %s failed at admission: %s",
                            req.request_id, msg)
        self._release_adapter(req)
        req.finish_reason = "error"
        req.finish_time = time.monotonic()
        self.finished.append(req)
        self.telemetry.on_finished(req)

    def _release(self, slot: _Slot, register: bool = True) -> None:
        if self.prefix_cache is not None and slot.request is not None:
            # Register the written full blocks for reuse (shared blocks get
            # their refcount dropped; the partial tail goes back to the
            # pool). A preempted mid-prefill slot has written only
            # next_pos tokens — caching past that would serve unwritten KV.
            # ``register=False`` (abort after a faulted step): the slot's
            # KV may never have been written at all, so drop shared refs
            # and free owned blocks WITHOUT registering any content keys —
            # an empty token chain does exactly that.
            req = slot.request
            n_written = slot.next_pos if slot.prefilling else slot.seq_len
            written = ((req.prompt_token_ids + req.output_token_ids)[:n_written]
                       if register else [])
            self.prefix_cache.release_sequence(written, slot.blocks,
                                               ns=req.adapter or None)
        else:
            self.block_manager.free(slot.blocks)
        if slot.request is not None:
            self._release_adapter(slot.request)
        slot.request = None
        slot.blocks = []
        slot.seq_len = 0
        slot.next_pos = 0
        slot.prefill_end = 0
        self._block_tables[slot.slot_id] = 0
        self._temperature[slot.slot_id] = 1.0
        self._top_k[slot.slot_id] = 0
        self._top_p[slot.slot_id] = 1.0
        self._slot_keys[slot.slot_id] = 0
        self._gen_counts[slot.slot_id] = 0
        self._adapter_ids[slot.slot_id] = 0
        self._spec_reset_slot(slot.slot_id)
        self._mark_state_dirty(slot.slot_id)

    # ------------------------------------------------------------------
    # Disaggregated prefill/decode handoff (serving/disagg.py)
    # ------------------------------------------------------------------
    def export_handoff(self, slot: _Slot) -> Optional[dict]:
        """Snapshot everything a decode replica needs to continue ``slot``'s
        request byte-identically, then release the slot locally.

        The KV leaves over the proven tier path (fetch_block_kv: device→
        host, staged through pinned_host where the backend has it) — only
        the blocks covering WRITTEN positions (0..seq_len-1) travel; the
        decode side allocates its own chain and restores into it. The
        snapshot carries the origin slot's actual rng key bytes: an
        unseeded request's key came from the origin engine's private rng
        split and cannot be re-derived elsewhere, and the decode program's
        fold_in(key, gen_count) stream must continue exactly where prefill
        sampling left it. Returns None (slot untouched) if any block fetch
        fails — the caller falls back to a re-prefill elsewhere.
        """
        req = slot.request
        n_blocks = self.block_manager.blocks_needed(slot.seq_len)
        payloads = []
        for b in slot.blocks[:n_blocks]:
            p = self.executor.fetch_block_kv(b)
            if p is None:
                return None
            payloads.append(p)
        snap = {
            "request": req,
            "payloads": payloads,
            "seq_len": slot.seq_len,
            "last_token": slot.last_token,
            "slot_key": self._slot_keys[slot.slot_id].copy(),
            "gen_count": int(self._gen_counts[slot.slot_id]),
            # Adaptive-spec controller state rides along so the adopting
            # engine's gate resumes mid-window instead of re-probing from
            # scratch (the token history itself is rebuilt from the
            # request's tokens on adopt). Additive dict of plain scalars:
            # serializes through the generic wire envelope unchanged.
            "spec": {
                "prop": int(self._spec_slot_prop[slot.slot_id]),
                "acc": int(self._spec_slot_acc[slot.slot_id]),
                "pause": int(self._spec_slot_pause[slot.slot_id]),
                "ewma": float(self._spec_slot_ewma[slot.slot_id]),
            },
        }
        self._release(slot)
        return snap

    def adopt_handoff(self, snap: dict) -> bool:
        """Admit a prefilled request whose KV arrives as host payloads
        (:meth:`export_handoff` counterpart): take a free slot, allocate a
        fresh block chain, scatter the payloads in via the tier-restore
        path, and seed the slot so the next decode step samples exactly
        the token the origin engine would have. Returns False (nothing
        consumed) when no slot or not enough blocks are free — the caller
        retries or degrades to a re-prefill."""
        slot = next((s for s in self.slots if s.free), None)
        if slot is None:
            return False
        req = snap["request"]
        # Re-pin the request's adapter on THIS engine's pool before
        # consuming anything: the origin pin died with the origin slot.
        # Busy pool or load failure → False, nothing consumed — the
        # caller retries or degrades to a re-prefill, where _admit's
        # resolution path owns failing the request properly.
        if req.adapter and req._adapter_slot < 0:
            if self.adapter_pool is None:
                return False
            try:
                row, _ = self.adapter_pool.acquire(req.adapter)
            except AdapterError:
                return False
            if row < 0:
                return False
            req._adapter_slot = row
        seq_len = snap["seq_len"]
        # +1: the first decode step writes KV at position seq_len.
        blocks = self._alloc(self.block_manager.blocks_needed(seq_len + 1))
        if blocks is None:
            return False
        # Closes the kv_handoff stall mark (note_readmitted); the origin
        # admission already stamped admitted_time, so queue-time samples
        # are not double counted.
        self.telemetry.on_admitted(req)
        slot.request = req
        slot.blocks = blocks
        slot.seq_len = seq_len
        slot.next_pos = seq_len
        slot.prefill_end = seq_len
        slot.last_token = snap["last_token"]
        row = np.zeros((self.cfg.max_blocks_per_seq,), np.int32)
        row[: len(blocks)] = blocks
        self._block_tables[slot.slot_id] = row
        self._temperature[slot.slot_id] = req.params.temperature
        self._top_k[slot.slot_id] = req.params.top_k
        self._top_p[slot.slot_id] = req.params.top_p
        self._slot_keys[slot.slot_id] = snap["slot_key"]
        self._gen_counts[slot.slot_id] = snap["gen_count"]
        self._adapter_ids[slot.slot_id] = max(req._adapter_slot, 0)
        self._mark_state_dirty(slot.slot_id)
        if self._spec_hist is not None:
            ctx = req.prompt_token_ids + req.output_token_ids
            self._spec_hist[slot.slot_id, : len(ctx)] = ctx
        spec = snap.get("spec")
        if spec:
            # Resume the per-slot adaptive gate where the origin left it
            # (.get: snapshots from engines predating the controller —
            # or with speculation off — restore to the fresh-slot state).
            self._spec_slot_prop[slot.slot_id] = int(spec.get("prop", 0))
            self._spec_slot_acc[slot.slot_id] = int(spec.get("acc", 0))
            self._spec_slot_pause[slot.slot_id] = int(spec.get("pause", 0))
            self._spec_slot_ewma[slot.slot_id] = float(
                spec.get("ewma", self.cfg.num_draft_tokens))
        for b, payload in zip(blocks, snap["payloads"]):
            self.executor.restore_block(b, payload)
        return True

    def abort_all(self, reason: str = "abort") -> List[Request]:
        """Fail every in-flight and queued request and free their slots.

        The server's step-failure recovery: after a faulted
        ``engine.step()`` the queues' consumers are gone, so leaving the
        requests in place would either hot-loop the same failing program
        (persistent faults) or burn whole decode windows generating
        tokens nobody reads (transient faults). Returns the aborted
        requests (their ``finish_reason`` is set to ``reason``).
        """
        aborted: List[Request] = []
        for slot in self.slots:
            if slot.request is not None:
                req = slot.request
                req.finish_reason = reason
                req.finish_time = time.monotonic()
                aborted.append(req)
                # register=False: the faulted step may never have written
                # this slot's KV — registering it in the prefix cache
                # would serve garbage to later cache hits.
                self._release(slot, register=False)
        while self.waiting:
            req = self.waiting.popleft()
            # A queue-head request may hold an adapter pin (resolution
            # happened, block allocation then broke the pass).
            self._release_adapter(req)
            req.finish_reason = reason
            req.finish_time = time.monotonic()
            aborted.append(req)
        for req in aborted:
            self.telemetry.on_finished(req)
        return aborted

    def _preempt_youngest(self, exclude: _Slot) -> bool:
        """Evict the most-recently-arrived sequence back to the queue."""
        candidates = [s for s in self.slots if not s.free and s is not exclude]
        if not candidates:
            return False
        victim = max(candidates, key=lambda s: s.request.arrival_time)
        req = victim.request
        req.num_preemptions += 1
        self.stats["preemptions"] += 1
        self.telemetry.on_preempted(req)
        self.waiting.appendleft(req)
        self._release(victim)
        self.logger.info("preempted %s (recompute on readmit)", req.request_id)
        return True

    # ------------------------------------------------------------------
    def _result(self, req: Request) -> GenerationResult:
        return GenerationResult(
            request_id=req.request_id,
            prompt_token_ids=req.prompt_token_ids,
            output_token_ids=req.output_token_ids,
            output_logprobs=req.output_logprobs,
            finish_reason=req.finish_reason or "abort",
            ttft_s=(req.first_token_time or req.arrival_time) - req.arrival_time,
            latency_s=(req.finish_time or time.monotonic()) - req.arrival_time,
        )
