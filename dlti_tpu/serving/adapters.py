"""Multi-LoRA serving: hot-loadable adapter catalog + batched HBM pool.

One merged-weights replica per fine-tune costs N full copies of the base
model for N tenants. S-LoRA / Punica showed the alternative: keep ONE
shared base resident and apply each request's low-rank adapter as a
gathered per-slot A/B einsum inside the same compiled step, so a batch
where every row wears a different adapter still runs as one program.
This module is the host side of that design:

* :class:`AdapterCatalog` — a process-global name → checkpoint-directory
  registry. Registration verifies the checkpoint through the digest
  store (``checkpoint/store.py``); a corrupt checkpoint is quarantined
  at registration (or at a later reload) and the name stays/becomes
  unknown, so routing layers 404 instead of the engine ever faulting.
  Hot-register closes the train → serve loop: a LoRA checkpoint written
  by the Trainer becomes servable with zero engine restart.
* :class:`AdapterPool` — a bounded per-engine device pool of stacked
  per-module A/B tensors: row 0 is the all-zero base adapter (the
  batched einsum then contributes exactly +0.0, so base requests are
  byte-identical to an adapter-free engine), rows 1..slots hold loaded
  adapters under refcounted LRU. ``acquire`` returns a row index the
  engine carries in its device-resident decode state; a miss loads from
  the verified store and scatters one row (no pool rebuild, no
  recompile).
* :func:`save_adapter` / :func:`extract_adapter_weights` — the adapter
  checkpoint format (nested numpy dicts, ``save_pytree``-compatible):
  ``{"meta": {"alpha"}, "weights": {<params paths>: {"lora_a",
  "lora_b"}}}``. Target modules are implicit in the tree structure and
  the rank in the shapes, so the format needs no sidecar metadata.

Metric names are a scrape contract (pinned in
``tests/test_bench_contract.py`` / ``tests/test_metric_naming.py``);
the pool registers as the ``lora_adapters`` memledger owner engine-side.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlti_tpu.checkpoint.store import (
    CheckpointCorruptError, load_pytree, quarantine_step, save_pytree,
)
from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
ADAPTER_METRIC_NAMES = (
    "dlti_adapter_loads_total",
    "dlti_adapter_evictions_total",
    "dlti_adapter_pool_hits_total",
    "dlti_adapter_pool_misses_total",
    "dlti_adapter_pool_slots",
    "dlti_adapter_pool_bytes",
)

# Module-level metrics (the prefix-cache pattern: defined here, the
# server registry registers them for /metrics, replicas aggregate into
# one series).
loads_total = Counter(
    ADAPTER_METRIC_NAMES[0],
    help="adapter checkpoints loaded from the store into the HBM pool")
evictions_total = Counter(
    ADAPTER_METRIC_NAMES[1],
    help="idle adapters LRU-evicted from the HBM pool")
pool_hits_total = Counter(
    ADAPTER_METRIC_NAMES[2],
    help="acquisitions served by an already-resident adapter")
pool_misses_total = Counter(
    ADAPTER_METRIC_NAMES[3],
    help="acquisitions that had to load from the checkpoint store")
pool_slots_gauge = Gauge(
    ADAPTER_METRIC_NAMES[4],
    help="adapter slots in the HBM pool (row 0, the base no-op, excluded)")
pool_bytes_gauge = Gauge(
    ADAPTER_METRIC_NAMES[5],
    help="bytes of the stacked A/B adapter pool on device")


class AdapterError(Exception):
    """Unknown, corrupt, or incompatible adapter.

    Always a *request*-scoped failure: the gateway/server map it to
    HTTP 404 at admission, the engine fails the one request that named
    it — it must never take the engine down.
    """


# ----------------------------------------------------------------------
# Checkpoint format
# ----------------------------------------------------------------------

def extract_adapter_weights(params: Any) -> Dict[str, Any]:
    """The LoRA factors of a trained params tree, at their params paths.

    Walks nested dicts and keeps every ``{"lora_a", "lora_b"}`` pair
    (the base ``kernel`` stays behind); the result is the ``weights``
    subtree of the adapter checkpoint format.
    """
    out: Dict[str, Any] = {}
    if not isinstance(params, dict):
        return out
    for k, v in params.items():
        if not isinstance(v, dict):
            continue
        if "lora_a" in v and "lora_b" in v:
            out[k] = {"lora_a": np.asarray(v["lora_a"]),
                      "lora_b": np.asarray(v["lora_b"])}
        else:
            sub = extract_adapter_weights(v)
            if sub:
                out[k] = sub
    return out


def save_adapter(directory: str, params: Any, alpha: float = 32.0) -> str:
    """Write an adapter checkpoint (digest-verified store format) from a
    trained params tree; returns the directory. Raises ``ValueError``
    when the tree holds no LoRA factors (nothing to serve)."""
    weights = extract_adapter_weights(params)
    if not weights:
        raise ValueError("params tree holds no lora_a/lora_b factors; "
                         "train with LoRAConfig.enabled first")
    return save_pytree(directory, {
        "meta": {"alpha": np.float32(alpha)},
        "weights": weights,
    }, path_class="adapter")


def _load_verified(name: str, directory: str) -> dict:
    """Load + digest-verify one adapter checkpoint; corrupt checkpoints
    are quarantined (``store.quarantine_step``) and surface as
    :class:`AdapterError` so the caller 404s instead of faulting."""
    try:
        tree = load_pytree(directory, verify=True)
    except CheckpointCorruptError as e:
        parent, base = os.path.split(os.path.normpath(directory))
        dst = quarantine_step(parent or ".", base,
                              reason=f"adapter {name!r}: {e}")
        raise AdapterError(
            f"adapter {name!r} checkpoint is corrupt"
            f"{' (quarantined to ' + dst + ')' if dst else ''}: {e}") from e
    except (OSError, ValueError) as e:
        raise AdapterError(
            f"adapter {name!r} unreadable at {directory}: {e}") from e
    if (not isinstance(tree, dict) or not isinstance(tree.get("weights"), dict)
            or not tree["weights"] or "meta" not in tree
            or "alpha" not in tree["meta"]):
        raise AdapterError(
            f"adapter {name!r} at {directory} is not an adapter checkpoint "
            "(expected {'meta': {'alpha'}, 'weights': {...}})")
    return tree


def _flatten_lora(weights: dict, path: Tuple[str, ...] = ()
                  ) -> Dict[Tuple[str, ...], dict]:
    out: Dict[Tuple[str, ...], dict] = {}
    for k, v in weights.items():
        if not isinstance(v, dict):
            continue
        if "lora_a" in v and "lora_b" in v:
            out[path + (k,)] = v
        else:
            out.update(_flatten_lora(v, path + (k,)))
    return out


# ----------------------------------------------------------------------
# Process-global catalog
# ----------------------------------------------------------------------

class AdapterCatalog:
    """Thread-safe name → verified-checkpoint-directory registry.

    Process-global (see :func:`get_catalog`) so every engine — replicas,
    disagg pools — resolves the same names without config threading; the
    per-engine :class:`AdapterPool` loads lazily from here at admission.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._dirs: Dict[str, str] = {}

    def register(self, name: str, directory: str) -> str:
        """Verify + register; returns the name. Raises
        :class:`AdapterError` on a bad name or a corrupt/unreadable
        checkpoint (corrupt ones are quarantined) — the name is then NOT
        registered, so routing keeps 404ing it."""
        if not name or not isinstance(name, str) or any(
                c in name for c in " \t\n/\\"):
            raise AdapterError(f"invalid adapter name {name!r}")
        directory = os.path.abspath(directory)
        _load_verified(name, directory)  # verify before the name exists
        with self._lock:
            self._dirs[name] = directory
        get_logger().info("adapter %r registered from %s", name, directory)
        return name

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._dirs.pop(name, None) is not None

    def directory(self, name: str) -> Optional[str]:
        with self._lock:
            return self._dirs.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._dirs)

    def clear(self) -> None:
        with self._lock:
            self._dirs.clear()

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._dirs


_CATALOG = AdapterCatalog()


def get_catalog() -> AdapterCatalog:
    return _CATALOG


def register_adapter(name: str, directory: str) -> str:
    """Hot-register an adapter checkpoint process-wide (every engine's
    pool can load it from the next admission on — no restart)."""
    return _CATALOG.register(name, directory)


def unregister_adapter(name: str) -> bool:
    return _CATALOG.unregister(name)


# ----------------------------------------------------------------------
# Device pool
# ----------------------------------------------------------------------

def _target_shapes(params: Any, targets: Sequence[str],
                   path: Tuple[str, ...] = ()
                   ) -> Dict[Tuple[str, ...], Tuple[int, int]]:
    """``{params path: (in_features, out_features)}`` for every target
    projection in the tree (int8 kernels keep the original shape in
    their ``q`` component)."""
    out: Dict[Tuple[str, ...], Tuple[int, int]] = {}
    if not isinstance(params, dict):
        return out
    for k, v in params.items():
        if not isinstance(v, dict):
            continue
        if k in targets and "kernel" in v:
            kern = v["kernel"]
            shape = kern["q"].shape if isinstance(kern, dict) else kern.shape
            out[path + (k,)] = (int(shape[0]), int(shape[1]))
        else:
            out.update(_target_shapes(v, targets, path + (k,)))
    return out


def plan_pool_bytes(model_cfg: Any, targets: Sequence[str], rank: int,
                    num_slots: int) -> int:
    """Analytic pool size (fp32 masters): ``(slots + 1) x sum over
    layers/targets of (in*r + r*out + 1) x 4`` — the number
    ``scripts/memory_plan.py`` cross-checks against the measured
    ``lora_adapters`` memledger owner."""
    h = model_cfg.hidden_size
    hd = model_cfg.resolved_head_dim
    m = model_cfg.intermediate_size
    dims = {
        "q_proj": (h, model_cfg.num_heads * hd),
        "k_proj": (h, model_cfg.num_kv_heads * hd),
        "v_proj": (h, model_cfg.num_kv_heads * hd),
        "o_proj": (model_cfg.num_heads * hd, h),
        "gate_proj": (h, m), "up_proj": (h, m), "down_proj": (m, h),
    }
    per_layer = 0
    for t in targets:
        if t not in dims:
            raise ValueError(f"unknown adapter target {t!r}")
        din, dout = dims[t]
        per_layer += din * rank + rank * dout + 1
    return (int(num_slots) + 1) * model_cfg.num_layers * per_layer * 4


class AdapterPool:
    """Bounded stacked A/B adapter pool resident on device.

    The pool tree mirrors the params tree at the target projections:
    each holds ``{"a": (P, in, r), "b": (P, r, out), "s": (P,)}`` with
    ``P = num_slots + 1`` — applied inside the model as a Flax
    ``adapters`` variable collection, gathered per batch row by adapter
    id. Row 0 is all-zero (base). Loads scatter ONE row in place (a
    jitted ``.at[i].set``), so a pool-miss never reshapes or recompiles
    the serving programs. Refcounted LRU: rows pinned by in-flight
    requests are never evicted; ``acquire`` on a full pinned pool
    returns ``(-1, False)`` and the engine defers admission (the same
    contract as KV-block exhaustion).
    """

    def __init__(self, params: Any, num_slots: int, rank: int,
                 targets: Sequence[str], device: Any = None,
                 mesh: Any = None, catalog: Optional[AdapterCatalog] = None):
        import jax
        import jax.numpy as jnp

        if num_slots < 1:
            raise ValueError("adapter pool needs at least 1 slot")
        if rank < 1:
            raise ValueError("adapter rank must be >= 1")
        self.num_slots = int(num_slots)
        self.rank = int(rank)
        self.targets = tuple(targets)
        self._catalog = catalog if catalog is not None else get_catalog()
        self._lock = threading.Lock()
        self._shapes = _target_shapes(params, self.targets)
        if not self._shapes:
            raise ValueError(
                f"no adapter targets {self.targets} found in the params "
                "tree — wrong target names for this model?")
        P = self.num_slots + 1
        tree: Dict[str, Any] = {}
        for path, (din, dout) in self._shapes.items():
            node = tree
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = {
                "a": np.zeros((P, din, self.rank), np.float32),
                "b": np.zeros((P, self.rank, dout), np.float32),
                "s": np.zeros((P,), np.float32),
            }
        self._device = device
        self._mesh = mesh
        self.tree = jax.tree_util.tree_map(self._place, tree)
        # One-row in-place scatter; the OLD pool buffers are NOT donated
        # (an in-flight async step may still be reading them).
        self._scatter = jax.jit(lambda pool, rows, i: jax.tree_util.tree_map(
            lambda p, r: p.at[i].set(r), pool, rows))
        del jnp
        # Slot bookkeeping: row 0 is the reserved base no-op.
        self._names: List[Optional[str]] = [None] * P
        self._refs = [0] * P
        self._last_used = [0] * P
        self._tick = 0
        self._by_name: Dict[str, int] = {}
        pool_slots_gauge.set(self.num_slots)
        pool_bytes_gauge.set(self.nbytes)

    def _place(self, x: np.ndarray):
        import jax
        import jax.numpy as jnp

        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(x, NamedSharding(self._mesh,
                                                   PartitionSpec()))
        if self._device is not None:
            return jax.device_put(x, self._device)
        return jnp.asarray(x)

    @property
    def nbytes(self) -> int:
        import jax

        return jax.tree_util.tree_reduce(
            lambda t, x: t + x.nbytes, self.tree, 0)

    def resident(self, name: str) -> bool:
        with self._lock:
            return name in self._by_name

    def loaded_names(self) -> List[str]:
        with self._lock:
            return sorted(self._by_name)

    # -- acquire / release ---------------------------------------------
    def acquire(self, name: str) -> Tuple[int, bool]:
        """Pin ``name`` into a pool row; returns ``(row, loaded)``.

        ``loaded`` is True when this call paid a checkpoint-store load
        (the engine charges it to the request's restore phase). Returns
        ``(-1, False)`` when every row is pinned by in-flight requests —
        the caller defers, FCFS. Raises :class:`AdapterError` for an
        unknown name or a checkpoint that fails verification at load
        time (then also quarantined + unregistered, so later requests
        404 at admission instead of retrying the load forever).
        """
        with self._lock:
            self._tick += 1
            idx = self._by_name.get(name)
            if idx is not None:
                self._refs[idx] += 1
                self._last_used[idx] = self._tick
                pool_hits_total.inc()
                return idx, False
            pool_misses_total.inc()
            directory = self._catalog.directory(name)
            if directory is None:
                raise AdapterError(f"unknown adapter {name!r} "
                                   "(register it first)")
            idx = self._free_slot()
            if idx is None:
                return -1, False
            try:
                ckpt = _load_verified(name, directory)
                rows = self._rows_from(name, ckpt)
            except AdapterError:
                # The registered checkpoint went bad on disk after
                # registration: drop the name so admission 404s.
                self._catalog.unregister(name)
                raise
            self.tree = self._scatter(self.tree, rows, idx)
            self._names[idx] = name
            self._refs[idx] = 1
            self._last_used[idx] = self._tick
            self._by_name[name] = idx
            loads_total.inc()
            return idx, True

    def release(self, idx: int) -> None:
        """Unpin one acquisition of row ``idx`` (0 / negative = no-op).
        The row stays resident for cache hits until LRU eviction needs
        it."""
        if idx <= 0:
            return
        with self._lock:
            if self._refs[idx] > 0:
                self._refs[idx] -= 1

    def _free_slot(self) -> Optional[int]:
        # Never-used rows first (they are already zero), then the
        # least-recently-used unpinned resident row.
        for i in range(1, self.num_slots + 1):
            if self._names[i] is None and self._refs[i] == 0:
                return i
        victim = None
        for i in range(1, self.num_slots + 1):
            if self._refs[i] == 0 and (
                    victim is None
                    or self._last_used[i] < self._last_used[victim]):
                victim = i
        if victim is None:
            return None
        evicted = self._names[victim]
        if evicted is not None:
            del self._by_name[evicted]
            self._names[victim] = None
            evictions_total.inc()
        return victim

    # -- row construction ----------------------------------------------
    def _rows_from(self, name: str, ckpt: dict) -> dict:
        """One pool row per target module: the adapter's A/B zero-padded
        from its rank r to the pool rank (float-exact: padded columns
        multiply padded zero rows), scale alpha/r per the merge
        convention; targets the adapter did not train get zero rows
        (exact no-op)."""
        alpha = float(np.asarray(ckpt["meta"]["alpha"]))
        flat = _flatten_lora(ckpt["weights"])
        unknown = sorted(set(flat) - set(self._shapes))
        if unknown:
            raise AdapterError(
                f"adapter {name!r} targets modules outside this pool "
                f"(targets={self.targets}): {['/'.join(p) for p in unknown]}")
        ranks = {int(np.asarray(w["lora_a"]).shape[-1]) for w in flat.values()}
        if len(ranks) != 1:
            raise AdapterError(
                f"adapter {name!r} has mixed ranks {sorted(ranks)}")
        r = ranks.pop()
        if not 1 <= r <= self.rank:
            raise AdapterError(
                f"adapter {name!r} rank {r} exceeds the pool rank "
                f"{self.rank}")
        rows: Dict[str, Any] = {}
        for path, (din, dout) in self._shapes.items():
            w = flat.get(path)
            a = np.zeros((din, self.rank), np.float32)
            b = np.zeros((self.rank, dout), np.float32)
            s = np.float32(0.0)
            if w is not None:
                la = np.asarray(w["lora_a"], np.float32)
                lb = np.asarray(w["lora_b"], np.float32)
                if la.shape != (din, r) or lb.shape != (r, dout):
                    raise AdapterError(
                        f"adapter {name!r} shape mismatch at "
                        f"{'/'.join(path)}: a{la.shape} b{lb.shape} vs "
                        f"module ({din}, {dout}) rank {r}")
                a[:, :r] = la
                b[:r, :] = lb
                s = np.float32(alpha / r)
            node = rows
            for k in path[:-1]:
                node = node.setdefault(k, {})
            node[path[-1]] = {"a": a, "b": b, "s": s}
        return rows
