"""Token sampling — jitted, batched, per-request parameters.

The reference's claimed serving stack (vLLM, ``README.md:10``) samples with
per-request temperature / top-k / top-p; this is the TPU-native equivalent.
One compiled function handles the whole decode batch: every request carries
its own knobs as array entries, so mixed greedy/sampling batches never
recompile.

Design notes (XLA-first):

* The vocab is fully sorted once per step (``lax.top_k`` over V) — O(V log V)
  on the VPU, negligible next to the decode matmuls — and top-k/top-p become
  rank/cumulative-probability masks in sorted space.
* ``temperature == 0`` selects greedy via ``jnp.where`` on the same path
  (no branch, no recompile).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@dataclass
class SamplingParams:
    """Per-request sampling knobs (OpenAI API semantics)."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled (full vocab)
    top_p: float = 1.0
    max_tokens: int = 128
    stop_token_ids: Sequence[int] = field(default_factory=tuple)
    # Per-request seed: fixes the request's own draw stream regardless of
    # what else shares the decode batch (engine folds it per emitted token).
    seed: Optional[int] = None
    # Whether the server should return logprobs in the API response (they
    # are always computed device-side; this is a response-shaping flag).
    logprobs: bool = False

    def greedy(self) -> bool:
        return self.temperature == 0.0


def sample_tokens(
    logits: jnp.ndarray,
    rng: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample one token per row.

    Args:
      logits: (batch, vocab) float32.
      rng: a single PRNG key (split per-row internally) or a batch of
        per-row keys of shape (batch, 2) — the engine passes per-request
        keys so ``SamplingParams.seed`` reproduces a request's draw stream
        independent of what else is in the batch.
      temperature: (batch,) float32; 0 => greedy (argmax).
      top_k: (batch,) int32; 0 => disabled.
      top_p: (batch,) float32; 1.0 => disabled.

    Returns:
      (tokens (batch,) int32, logprob of each sampled token (batch,) float32).
    """
    b, v = logits.shape
    sorted_logits, sorted_idx = jax.lax.top_k(logits, v)  # descending

    # Scale by temperature (guard 0 for the greedy rows).
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = sorted_logits / safe_t

    ranks = jnp.arange(v, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k > 0, top_k, v).astype(jnp.int32)[:, None]
    keep = ranks < k

    probs = jax.nn.softmax(scaled, axis=-1)
    # Keep tokens while cumulative prob *before* this token < top_p
    # (always keeps the head token).
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep &= cum_before < top_p[:, None]

    masked = jnp.where(keep, scaled, -jnp.inf)
    rngs = rng if rng.ndim == 2 else jax.random.split(rng, b)
    sampled_rank = jax.vmap(lambda r, lg: jax.random.categorical(r, lg))(rngs, masked)

    greedy_rank = jnp.zeros((b,), jnp.int32)  # sorted descending -> rank 0
    rank = jnp.where(temperature > 0, sampled_rank, greedy_rank)
    tokens = jnp.take_along_axis(sorted_idx, rank[:, None], axis=1)[:, 0]

    # Log-prob of the chosen token under the *unmasked, unscaled* distribution
    # (what the OpenAI API reports).
    logz = jax.nn.logsumexp(sorted_logits, axis=-1)
    chosen_logit = jnp.take_along_axis(sorted_logits, rank[:, None], axis=1)[:, 0]
    return tokens.astype(jnp.int32), chosen_logit - logz
