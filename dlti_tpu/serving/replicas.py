"""Data-parallel serving: independent engine replicas over device groups.

The reference claims vLLM serving with tensor parallelism
(``/root/reference/README.md:10``); vLLM scales *throughput* beyond one
TP group by running multiple engine replicas behind a dispatcher. This is
the TPU-native equivalent: the visible devices are partitioned into
``replicas`` groups of ``tensor`` chips, each group gets a fully
independent :class:`InferenceEngine` (its own sharded weights, KV pool,
scheduler, prefix cache), and requests are dispatched least-loaded.

Replication is deliberately *above* the engine rather than a mesh axis
inside it: batch rows of one jitted program sharded over a ``data`` axis
would lock every replica to the same program counter (one global decode
step), while independent engines prefill, decode and preempt on their own
schedules — the same reason vLLM runs one engine per data-parallel rank.
Within a replica, jit dispatch is async, so driving the replicas
round-robin from one host thread overlaps their device work.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import time
from typing import List, Optional, Sequence, Tuple

import jax

from dlti_tpu.config import LoRAConfig, ModelConfig, ParallelConfig
from dlti_tpu.serving.engine import (
    EngineConfig, GenerationResult, InferenceEngine, Request, SamplingParams,
)
from dlti_tpu.telemetry import RequestTelemetry
from dlti_tpu.utils.logging import get_logger

# Env override for the deterministic chaos hook (same "REPLICA:STEP"
# format as GatewayConfig.fault_inject_step): lets a chaos run kill a
# replica on a live server without a config edit.
FAULT_INJECT_ENV = "DLTI_GATEWAY_FAULT_INJECT"


_FAULT_MODES = ("raise", "nan-logits")


def _parse_fault_inject(spec: str) -> Optional[Tuple[int, int, str]]:
    """"REPLICA:STEP[:MODE]" -> (replica_idx, 1-based step count, mode);
    None if unset. MODE "raise" (default) raises :class:`ReplicaFault` in
    place of a device fault; "nan-logits" instead poisons the replica's
    params with NaN so the engine's REAL numeric guard
    (:class:`~dlti_tpu.serving.engine.NumericFault`) detects the garbage
    output and trips the same quarantine path."""
    spec = (spec or "").strip()
    if not spec:
        return None
    try:
        rep, _, rest = spec.partition(":")
        step, _, mode = rest.partition(":")
        mode = mode or "raise"
        if mode not in _FAULT_MODES:
            raise ValueError(mode)
        return int(rep), int(step), mode
    except ValueError:
        raise ValueError(
            f"fault_inject_step must be 'REPLICA:STEP[:MODE]' with MODE "
            f"in {_FAULT_MODES}, got {spec!r}")


class ReplicaFault(RuntimeError):
    """Raised by the fault-injection hook in place of a real device fault."""


class ReplicatedEngine:
    """N independent engine replicas (each optionally TP-sharded) behind a
    least-loaded dispatcher. API mirrors :class:`InferenceEngine`:
    ``submit`` / ``step`` / ``generate`` / ``has_work``.

    **Fault isolation & failover:** a replica whose ``step()`` raises is
    marked dead and excluded from dispatch; its in-flight and queued
    requests are resubmitted on surviving replicas (recompute-on-readmit,
    the preemption path's semantics) up to ``max_retries`` per request —
    one replica fault degrades capacity instead of erroring the fleet.
    Requests past the retry cap (or with no survivors left) finish with
    ``finish_reason="error"``. ``fault_inject_step`` (or the
    ``DLTI_GATEWAY_FAULT_INJECT`` env var), format ``"REPLICA:STEP"``,
    kills a replica deterministically for tests and chaos runs."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg: Optional[LoRAConfig] = None,
        *,
        replicas: int = 1,
        tensor: int = 1,
        devices: Optional[Sequence] = None,
        max_retries: int = 2,
        fault_inject_step: str = "",
        affinity_spill_threshold: int = 4,
        telemetry: Optional[RequestTelemetry] = None,
    ):
        devices = list(devices if devices is not None else jax.devices())
        if replicas < 1 or tensor < 1:
            raise ValueError(
                f"replicas ({replicas}) and tensor ({tensor}) must be >= 1")
        need = replicas * tensor
        if need > len(devices):
            raise ValueError(
                f"{replicas} replicas x tensor={tensor} needs {need} "
                f"devices, have {len(devices)}")
        from dlti_tpu.parallel.mesh import build_mesh

        # One shared request-telemetry instance: every replica observes
        # into the same TTFT/TPOT/queue-time histograms, so the fleet's
        # latency distributions aggregate without a merge step. An
        # injected instance extends the sharing across pools (the disagg
        # controller's prefill and decode fleets report as one).
        self.telemetry = telemetry if telemetry is not None \
            else RequestTelemetry()
        self.engines: List[InferenceEngine] = []
        for r in range(replicas):
            group = devices[r * tensor:(r + 1) * tensor]
            mesh = (build_mesh(ParallelConfig(tensor=tensor), devices=group)
                    if tensor > 1 else None)
            # Single-chip replicas (tensor=1) pin weights to their device
            # explicitly — engines would otherwise all initialize onto the
            # default device.
            rep_params = (params if mesh is not None
                          else jax.device_put(params, group[0]))
            rep_cfg = engine_cfg
            if engine_cfg.prefix_disk_dir:
                # Per-replica disk-tier namespace: one shared dir would
                # let replica A's budget eviction delete a block dir
                # replica B's index still points at.
                import dataclasses

                rep_cfg = dataclasses.replace(
                    engine_cfg, prefix_disk_dir=os.path.join(
                        engine_cfg.prefix_disk_dir, f"replica{r}"))
            self.engines.append(
                InferenceEngine(model_cfg, rep_params, rep_cfg, lora_cfg,
                                mesh=mesh, telemetry=self.telemetry))
        self._rr = 0
        # Own id namespace: each engine's req-N counter starts at 0, so
        # auto-ids from different replicas would collide in any id-keyed
        # consumer (server streams, generate()'s by_id map).
        self._req_counter = itertools.count()
        self.logger = get_logger()
        self.max_retries = max_retries
        self._dead: set = set()  # replica indices excluded from dispatch
        self._step_counts = [0] * replicas
        self._fault_inject = _parse_fault_inject(
            os.environ.get(FAULT_INJECT_ENV) or fault_inject_step)
        # Failover counters, read by the gateway's dlti_gateway_* metrics
        # (kept out of `stats` so the aggregated per-engine keys — a
        # /stats name contract — stay untouched).
        self.failover = {"retries": 0, "replica_faults": 0,
                         "failover_errors": 0}
        # Cache-affinity routing (the tiered-prefix-cache companion: a
        # warm cache is per-replica, so repeat sessions must LAND on it).
        # A submit carrying an affinity key routes by rendezvous hashing
        # over the live replicas — stable under replica death (only keys
        # sticky to the dead replica re-rank; everyone else stays warm) —
        # with load-aware spill: when the sticky target's backlog exceeds
        # its slots by more than affinity_spill_threshold, the request
        # goes least-loaded instead (latency beats cache warmth).
        self.affinity_spill_threshold = affinity_spill_threshold
        self.affinity = {"sticky": 0, "spill": 0}
        # Last-resort rescue hook (disagg): when THIS pool has no live
        # replicas left, a stranded request is offered to the callable
        # (returning True = rehomed elsewhere) before erroring — the
        # controller routes it to the other pool (degraded colocation).
        self.failover_fallback = None

    # ------------------------------------------------------------------
    def _load(self, eng: InferenceEngine) -> int:
        return len(eng.waiting) + eng.num_active

    def live_engines(self) -> List[InferenceEngine]:
        return [e for i, e in enumerate(self.engines) if i not in self._dead]

    @property
    def num_live(self) -> int:
        return len(self.engines) - len(self._dead)

    def _sticky_target(self, key: str,
                       live: List[InferenceEngine]) -> InferenceEngine:
        """Rendezvous (highest-random-weight) hashing: every live replica
        scores sha256(key:replica_index); the max wins. Removing a
        replica re-ranks only the keys it owned — the property that keeps
        the rest of the fleet's caches warm through a failover."""
        def score(eng: InferenceEngine) -> bytes:
            idx = self.engines.index(eng)
            return hashlib.sha256(f"{key}:{idx}".encode()).digest()

        return max(live, key=score)

    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "") -> Request:
        """Dispatch to the least-loaded live replica (round-robin
        tiebreak) — or, with an ``affinity_key``, to its sticky
        rendezvous-hash target unless that replica's backlog exceeds its
        decode slots by more than ``affinity_spill_threshold``.

        ``adapter`` names a registered LoRA adapter; the catalog is
        process-global, so any replica can resolve it (each replica pins
        it into its own pool at admission). On failover the adapter name
        rides the Request — the survivor re-acquires from its own pool.
        """
        live = self.live_engines()
        if not live:
            raise RuntimeError("all replicas dead (step faults); "
                               "engine cannot accept requests")
        eng = None
        if affinity_key:
            sticky = self._sticky_target(affinity_key, live)
            backlog = self._load(sticky) - sticky.cfg.max_seqs
            if backlog <= self.affinity_spill_threshold:
                eng = sticky
                self.affinity["sticky"] += 1
            else:
                self.affinity["spill"] += 1
        if eng is None:
            order = (live[self._rr % len(live):]
                     + live[:self._rr % len(live)])
            self._rr = (self._rr + 1) % len(live)
            eng = min(order, key=self._load)
        if request_id is None:
            request_id = f"rep-req-{next(self._req_counter)}"
        req = eng.submit(prompt_token_ids, params, request_id,
                         **({"adapter": adapter} if adapter else {}))
        req.replica = self.engines.index(eng)
        return req

    @property
    def has_work(self) -> bool:
        return any(e.has_work for e in self.engines)

    def step(self) -> List[Request]:
        """One scheduler iteration on every live replica that has work.

        jit dispatch is async, so each replica's device program launches
        before the next replica's host-side scheduling runs — the chips
        decode concurrently even though this is one Python loop.

        A replica whose step raises is failed over (see
        :meth:`_fail_replica`); the exception never escapes, so one
        replica fault can no longer orphan requests on healthy replicas
        mid-drain (the old ``generate()`` bug) or error the whole fleet.
        """
        finished: List[Request] = []
        for i, eng in enumerate(self.engines):
            if i in self._dead or not eng.has_work:
                continue
            try:
                self._step_counts[i] += 1
                if (self._fault_inject is not None
                        and self._fault_inject[0] == i
                        and self._step_counts[i] == self._fault_inject[1]):
                    if self._fault_inject[2] == "nan-logits":
                        # Poison the replica's params so this step's REAL
                        # forward emits NaN logits — the engine's numeric
                        # guard (not this hook) must catch it before any
                        # garbage token streams.
                        self._poison_params_nan(eng, i)
                    else:
                        raise ReplicaFault(
                            f"gateway.fault_inject_step: injected fault on "
                            f"replica {i} step {self._step_counts[i]}")
                finished.extend(eng.step())
            except Exception as e:  # noqa: BLE001 — isolate per replica
                finished.extend(self._fail_replica(i, e))
        return finished

    def _poison_params_nan(self, eng: InferenceEngine, idx: int) -> None:
        """nan-logits chaos: overwrite the first float param leaf of one
        replica with NaN (on that replica's own devices) — the honest
        silent-corruption simulation; detection is the engine guard's
        job."""
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(eng.params)
        for j, leaf in enumerate(leaves):
            if (hasattr(leaf, "dtype")
                    and jnp.issubdtype(leaf.dtype, jnp.inexact)):
                poisoned = jax.device_put(
                    jnp.full(leaf.shape, jnp.nan, leaf.dtype),
                    leaf.sharding)
                leaves[j] = poisoned
                break
        eng.params = jax.tree_util.tree_unflatten(treedef, leaves)
        self.logger.warning(
            "chaos: poisoned replica %d params with NaN (nan-logits "
            "fault injection)", idx)

    def _fail_replica(self, idx: int, exc: Exception) -> List[Request]:
        """Mark replica ``idx`` dead and fail its requests over.

        The faulted engine's device state is suspect, so nothing is
        salvaged from it: its slots are detached host-side (no block frees
        — the pool dies with the engine) and every stranded request is
        resubmitted least-loaded onto a survivor, where admission
        recomputes prompt + generated-so-far exactly like re-admission
        after preemption. Requests over ``max_retries`` (or with no
        survivors) finish as ``"error"`` and are returned so callers see
        them retire."""
        self._dead.add(idx)
        self.failover["replica_faults"] += 1
        eng = self.engines[idx]
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None:
            # Black box before failover rewrites the dead replica's
            # bookkeeping: which replica died, with what, holding what.
            rec.dump(reason="replica_fault", exc=exc, force=True,
                     extra={"replica": idx,
                            "in_flight": eng.num_active,
                            "queued": len(eng.waiting),
                            "survivors": self.num_live})
        self.logger.error(
            "replica %d step failed (%s: %s); failing over %d in-flight + "
            "%d queued request(s) to %d survivor(s)", idx, type(exc).__name__,
            exc, eng.num_active, len(eng.waiting), self.num_live)
        stranded: List[Request] = []
        for slot in eng.slots:
            if slot.request is not None and not slot.request.done:
                stranded.append(slot.request)
            # Detach host bookkeeping only: the dead engine's pool and KV
            # are abandoned wholesale, never reused.
            slot.request = None
            slot.blocks = []
            slot.seq_len = 0
            slot.next_pos = 0
            slot.prefill_end = 0
        stranded.extend(eng.waiting)
        eng.waiting.clear()

        errored: List[Request] = []
        live = self.live_engines()
        from dlti_tpu.telemetry.ledger import note_requeue

        for req in stranded:
            if not live or req.num_retries >= self.max_retries:
                if (not live and req.num_retries < self.max_retries
                        and self.failover_fallback is not None):
                    note_requeue(req, "failover")
                    if self.failover_fallback(req):
                        req.num_retries += 1
                        self.failover["retries"] += 1
                        continue
                req.finish_reason = "error"
                req.finish_time = time.monotonic()
                self.failover["failover_errors"] += 1
                self.telemetry.on_finished(req)
                # Visible in the finished ring so the server's event drain
                # (which walks slots + finished) delivers the error.
                eng.finished.append(req)
                errored.append(req)
                continue
            req.num_retries += 1
            self.failover["retries"] += 1
            # Critical-path attribution: the wait from here to
            # re-admission on the survivor books as "failover", not as
            # inflated prefill/decode (telemetry.ledger.note_requeue).
            note_requeue(req, "failover")
            target = min(live, key=self._load)
            target.resubmit(req)
            req.replica = self.engines.index(target)
        return errored

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        """Offline batch generation across all replicas. Per-replica step
        faults fail over inside :meth:`step`, so a single replica death
        mid-drain no longer orphans requests on healthy replicas."""
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        out = []
        for r in reqs:
            eng = self.engines[r.replica]
            out.append(eng._result(r))
        return out

    # -- InferenceEngine-compat surface (AsyncEngine / gateway) ---------
    def warmup_decode_ladder(self) -> None:
        for e in self.engines:
            e.warmup_decode_ladder()

    @property
    def cfg(self) -> EngineConfig:
        return self.engines[0].cfg

    @property
    def slots(self) -> list:
        return [s for e in self.engines for s in e.slots]

    @property
    def finished(self) -> List[Request]:
        return [r for e in self.engines for r in e.finished]

    @property
    def waiting(self) -> List[Request]:
        return [r for e in self.engines for r in e.waiting]

    @property
    def num_active(self) -> int:
        return sum(e.num_active for e in self.engines)

    @property
    def num_free_blocks(self) -> int:
        return sum(e.num_free_blocks for e in self.live_engines())

    def abort_all(self, reason: str = "abort") -> List[Request]:
        aborted: List[Request] = []
        for i, e in enumerate(self.engines):
            if i not in self._dead:
                aborted.extend(e.abort_all(reason=reason))
        return aborted

    @property
    def stats(self) -> dict:
        """Aggregated counters across replicas (per-replica under 'replicas')."""
        keys = self.engines[0].stats.keys()
        agg = {k: sum(e.stats[k] for e in self.engines) for k in keys}
        agg["replicas"] = [dict(e.stats) for e in self.engines]
        return agg
