"""Data-parallel serving: independent engine replicas over device groups.

The reference claims vLLM serving with tensor parallelism
(``/root/reference/README.md:10``); vLLM scales *throughput* beyond one
TP group by running multiple engine replicas behind a dispatcher. This is
the TPU-native equivalent: the visible devices are partitioned into
``replicas`` groups of ``tensor`` chips, each group gets a fully
independent :class:`InferenceEngine` (its own sharded weights, KV pool,
scheduler, prefix cache), and requests are dispatched least-loaded.

Replication is deliberately *above* the engine rather than a mesh axis
inside it: batch rows of one jitted program sharded over a ``data`` axis
would lock every replica to the same program counter (one global decode
step), while independent engines prefill, decode and preempt on their own
schedules — the same reason vLLM runs one engine per data-parallel rank.
Within a replica, jit dispatch is async, so driving the replicas
round-robin from one host thread overlaps their device work.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import time
from typing import List, Optional, Sequence, Tuple

import jax

from dlti_tpu.config import (
    LoRAConfig, ModelConfig, ParallelConfig, ReplicaLifecycleConfig,
)
from dlti_tpu.serving.engine import (
    EngineConfig, GenerationResult, InferenceEngine, Request, SamplingParams,
)
from dlti_tpu.serving.lifecycle import ReplicaLifecycle, canary_digest
from dlti_tpu.telemetry import RequestTelemetry
from dlti_tpu.utils.logging import get_logger

# Env override for the deterministic chaos hook (same "REPLICA:STEP"
# format as GatewayConfig.fault_inject_step): lets a chaos run kill a
# replica on a live server without a config edit.
FAULT_INJECT_ENV = "DLTI_GATEWAY_FAULT_INJECT"


_FAULT_MODES = ("raise", "nan-logits", "preempt")


def _parse_fault_inject(spec: str) -> Optional[Tuple[int, int, str]]:
    """"REPLICA:STEP[:MODE]" -> (replica_idx, 1-based step count, mode);
    None if unset. MODE "raise" (default) raises :class:`ReplicaFault` in
    place of a device fault; "nan-logits" instead poisons the replica's
    params with NaN so the engine's REAL numeric guard
    (:class:`~dlti_tpu.serving.engine.NumericFault`) detects the garbage
    output and trips the same quarantine path; "preempt" simulates a
    planned preemption notice — the replica drains via live KV migration
    to survivors (:meth:`ReplicatedEngine.drain_replica`) and enters the
    lifecycle quarantine instead of faulting."""
    spec = (spec or "").strip()
    if not spec:
        return None
    try:
        rep, _, rest = spec.partition(":")
        step, _, mode = rest.partition(":")
        mode = mode or "raise"
        if mode not in _FAULT_MODES:
            raise ValueError(mode)
        return int(rep), int(step), mode
    except ValueError:
        raise ValueError(
            f"fault_inject_step must be 'REPLICA:STEP[:MODE]' with MODE "
            f"in {_FAULT_MODES}, got {spec!r}")


class ReplicaFault(RuntimeError):
    """Raised by the fault-injection hook in place of a real device fault."""


class ReplicatedEngine:
    """N independent engine replicas (each optionally TP-sharded) behind a
    least-loaded dispatcher. API mirrors :class:`InferenceEngine`:
    ``submit`` / ``step`` / ``generate`` / ``has_work``.

    **Fault isolation & failover:** a replica whose ``step()`` raises is
    marked dead and excluded from dispatch; its in-flight and queued
    requests are resubmitted on surviving replicas (recompute-on-readmit,
    the preemption path's semantics) up to ``max_retries`` per request —
    one replica fault degrades capacity instead of erroring the fleet.
    Requests past the retry cap (or with no survivors left) finish with
    ``finish_reason="error"``. ``fault_inject_step`` (or the
    ``DLTI_GATEWAY_FAULT_INJECT`` env var), format ``"REPLICA:STEP"``,
    kills a replica deterministically for tests and chaos runs."""

    # Class-level defaults so `__new__`-built test skeletons (which skip
    # __init__) still have the deploy-controller surface.
    shadow_tap = None
    last_reload_ok: Optional[bool] = None

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg: Optional[LoRAConfig] = None,
        *,
        replicas: int = 1,
        tensor: int = 1,
        devices: Optional[Sequence] = None,
        max_retries: int = 2,
        fault_inject_step: str = "",
        affinity_spill_threshold: int = 4,
        telemetry: Optional[RequestTelemetry] = None,
        lifecycle_cfg: Optional[ReplicaLifecycleConfig] = None,
        lifecycle_clock=None,
    ):
        devices = list(devices if devices is not None else jax.devices())
        if replicas < 1 or tensor < 1:
            raise ValueError(
                f"replicas ({replicas}) and tensor ({tensor}) must be >= 1")
        need = replicas * tensor
        if need > len(devices):
            raise ValueError(
                f"{replicas} replicas x tensor={tensor} needs {need} "
                f"devices, have {len(devices)}")
        from dlti_tpu.parallel.mesh import build_mesh

        # One shared request-telemetry instance: every replica observes
        # into the same TTFT/TPOT/queue-time histograms, so the fleet's
        # latency distributions aggregate without a merge step. An
        # injected instance extends the sharing across pools (the disagg
        # controller's prefill and decode fleets report as one).
        self.telemetry = telemetry if telemetry is not None \
            else RequestTelemetry()
        self.engines: List[InferenceEngine] = []
        # Rebuild materials (lifecycle reinstates, rolling reloads): each
        # replica's device group / mesh / final engine config, plus the
        # model/lora configs, are enough to construct a replacement
        # engine from a host weight tree.
        self._model_cfg = model_cfg
        self._lora_cfg = lora_cfg
        self._groups: List[list] = []
        self._meshes: List[Optional[object]] = []
        self._rep_cfgs: List[EngineConfig] = []
        for r in range(replicas):
            group = devices[r * tensor:(r + 1) * tensor]
            mesh = (build_mesh(ParallelConfig(tensor=tensor), devices=group)
                    if tensor > 1 else None)
            # Single-chip replicas (tensor=1) pin weights to their device
            # explicitly — engines would otherwise all initialize onto the
            # default device.
            rep_params = (params if mesh is not None
                          else jax.device_put(params, group[0]))
            rep_cfg = engine_cfg
            if engine_cfg.prefix_disk_dir:
                # Per-replica disk-tier namespace: one shared dir would
                # let replica A's budget eviction delete a block dir
                # replica B's index still points at.
                import dataclasses

                rep_cfg = dataclasses.replace(
                    engine_cfg, prefix_disk_dir=os.path.join(
                        engine_cfg.prefix_disk_dir, f"replica{r}"))
            self._groups.append(group)
            self._meshes.append(mesh)
            self._rep_cfgs.append(rep_cfg)
            self.engines.append(
                InferenceEngine(model_cfg, rep_params, rep_cfg, lora_cfg,
                                mesh=mesh, telemetry=self.telemetry))
        self._rr = 0
        # Own id namespace: each engine's req-N counter starts at 0, so
        # auto-ids from different replicas would collide in any id-keyed
        # consumer (server streams, generate()'s by_id map).
        self._req_counter = itertools.count()
        self.logger = get_logger()
        self.max_retries = max_retries
        self._dead: set = set()  # replica indices excluded from dispatch
        self._step_counts = [0] * replicas
        self._fault_inject = _parse_fault_inject(
            os.environ.get(FAULT_INJECT_ENV) or fault_inject_step)
        # Failover counters, read by the gateway's dlti_gateway_* metrics
        # (kept out of `stats` so the aggregated per-engine keys — a
        # /stats name contract — stay untouched).
        self.failover = {"retries": 0, "replica_faults": 0,
                         "failover_errors": 0}
        # Cache-affinity routing (the tiered-prefix-cache companion: a
        # warm cache is per-replica, so repeat sessions must LAND on it).
        # A submit carrying an affinity key routes by rendezvous hashing
        # over the live replicas — stable under replica death (only keys
        # sticky to the dead replica re-rank; everyone else stays warm) —
        # with load-aware spill: when the sticky target's backlog exceeds
        # its slots by more than affinity_spill_threshold, the request
        # goes least-loaded instead (latency beats cache warmth).
        self.affinity_spill_threshold = affinity_spill_threshold
        self.affinity = {"sticky": 0, "spill": 0}
        # Last-resort rescue hook (disagg): when THIS pool has no live
        # replicas left, a stranded request is offered to the callable
        # (returning True = rehomed elsewhere) before erroring — the
        # controller routes it to the other pool (degraded colocation).
        self.failover_fallback = None
        # Replica lifecycle (serving.lifecycle): the state machine always
        # exists (it backs /health counts and the dlti_replica_state
        # gauge), but self-healing behavior — quarantine instead of
        # permanent death, probation probes, reinstates — only runs when
        # the config enables it; disabled, a faulted replica is marked
        # dead forever (the legacy contract the kill-drill tests pin).
        self.lifecycle_cfg = lifecycle_cfg if lifecycle_cfg is not None \
            else ReplicaLifecycleConfig()
        self._heal = self.lifecycle_cfg.enabled
        self.lifecycle = ReplicaLifecycle(
            self.lifecycle_cfg, replicas,
            clock=lifecycle_clock if lifecycle_clock is not None
            else time.monotonic)
        # Planned drains (rolling reload of a sole replica): dispatch
        # stops but the engine keeps stepping its in-flight work, unlike
        # _dead whose engines never step again.
        self._draining: set = set()
        self._warmed = False
        self._reload: Optional[dict] = None
        # Outcome of the most recent rolling reload (None until one ran):
        # the deployment controller polls this to learn whether its
        # promotion completed or aborted mid-roll.
        self.last_reload_ok: Optional[bool] = None
        # Shadow-traffic tap (serving.deploy): when set, every client
        # submit is offered to the callable as (prompt_token_ids, params,
        # live_request) AFTER dispatch — the tap mirrors a sampled
        # fraction onto a canary engine; its results never reach clients
        # and a tap failure never breaks a client submit.
        self.shadow_tap = None
        # Known-good weights for quarantine rebuilds: a host snapshot of
        # the boot tree (only paid when healing is on); a completed
        # rolling reload replaces it with the new tree.
        self._weights_host = None
        self._canary_digest: Optional[str] = None
        if self._heal:
            self._weights_host = jax.device_get(params)
            toks = self._run_canary(self.engines[0])
            if toks is not None:
                self._canary_digest = canary_digest(toks)
            else:
                self.logger.warning(
                    "lifecycle: canary digest could not be pinned at "
                    "construction; probes will gate on generation "
                    "success only")

    # ------------------------------------------------------------------
    def _load(self, eng: InferenceEngine) -> int:
        return len(eng.waiting) + eng.num_active

    def live_engines(self) -> List[InferenceEngine]:
        return [e for i, e in enumerate(self.engines)
                if i not in self._dead and i not in self._draining]

    @property
    def num_live(self) -> int:
        return len(self.engines) - len(self._dead | self._draining)

    def _sticky_target(self, key: str,
                       live: List[InferenceEngine]) -> InferenceEngine:
        """Rendezvous (highest-random-weight) hashing: every live replica
        scores sha256(key:replica_index); the max wins. Removing a
        replica re-ranks only the keys it owned — the property that keeps
        the rest of the fleet's caches warm through a failover."""
        def score(eng: InferenceEngine) -> bytes:
            idx = self.engines.index(eng)
            return hashlib.sha256(f"{key}:{idx}".encode()).digest()

        return max(live, key=score)

    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "", trace_id: str = "") -> Request:
        """Dispatch to the least-loaded live replica (round-robin
        tiebreak) — or, with an ``affinity_key``, to its sticky
        rendezvous-hash target unless that replica's backlog exceeds its
        decode slots by more than ``affinity_spill_threshold``.

        ``adapter`` names a registered LoRA adapter; the catalog is
        process-global, so any replica can resolve it (each replica pins
        it into its own pool at admission). On failover the adapter name
        rides the Request — the survivor re-acquires from its own pool.
        """
        live = self.live_engines()
        if not live:
            raise RuntimeError("all replicas dead (step faults); "
                               "engine cannot accept requests")
        eng = None
        if affinity_key:
            sticky = self._sticky_target(affinity_key, live)
            backlog = self._load(sticky) - sticky.cfg.max_seqs
            if backlog <= self.affinity_spill_threshold:
                eng = sticky
                self.affinity["sticky"] += 1
            else:
                self.affinity["spill"] += 1
        if eng is None:
            order = (live[self._rr % len(live):]
                     + live[:self._rr % len(live)])
            self._rr = (self._rr + 1) % len(live)
            eng = min(order, key=self._load)
        if request_id is None:
            request_id = f"rep-req-{next(self._req_counter)}"
        req = eng.submit(prompt_token_ids, params, request_id,
                         trace_id=trace_id,
                         **({"adapter": adapter} if adapter else {}))
        req.replica = self.engines.index(eng)
        tap = self.shadow_tap
        if tap is not None:
            try:
                tap(list(prompt_token_ids), params, req)
            except Exception:  # noqa: BLE001 — shadow never hurts clients
                self.logger.debug("shadow tap raised", exc_info=True)
        return req

    @property
    def has_work(self) -> bool:
        return any(e.has_work for e in self.engines)

    def step(self) -> List[Request]:
        """One scheduler iteration on every live replica that has work.

        jit dispatch is async, so each replica's device program launches
        before the next replica's host-side scheduling runs — the chips
        decode concurrently even though this is one Python loop.

        A replica whose step raises is failed over (see
        :meth:`_fail_replica`); the exception never escapes, so one
        replica fault can no longer orphan requests on healthy replicas
        mid-drain (the old ``generate()`` bug) or error the whole fleet.
        """
        finished: List[Request] = []
        for i, eng in enumerate(self.engines):
            if i in self._dead or not eng.has_work:
                continue
            try:
                self._step_counts[i] += 1
                if (self._fault_inject is not None
                        and self._fault_inject[0] == i
                        and self._step_counts[i] == self._fault_inject[1]):
                    if self._fault_inject[2] == "nan-logits":
                        # Poison the replica's params so this step's REAL
                        # forward emits NaN logits — the engine's numeric
                        # guard (not this hook) must catch it before any
                        # garbage token streams.
                        self._poison_params_nan(eng, i)
                    elif self._fault_inject[2] == "preempt":
                        # Planned preemption notice: drain via live KV
                        # migration (no fault dump — nothing is broken),
                        # then quarantine; the probe reinstates shortly.
                        self.logger.warning(
                            "chaos: preemption notice for replica %d at "
                            "step %d", i, self._step_counts[i])
                        finished.extend(self.drain_replica(i))
                        continue
                    else:
                        raise ReplicaFault(
                            f"gateway.fault_inject_step: injected fault on "
                            f"replica {i} step {self._step_counts[i]}")
                finished.extend(eng.step())
            except Exception as e:  # noqa: BLE001 — isolate per replica
                finished.extend(self._fail_replica(i, e))
        self._lifecycle_tick()
        return finished

    def _poison_params_nan(self, eng: InferenceEngine, idx: int) -> None:
        """nan-logits chaos: overwrite the first float param leaf of one
        replica with NaN (on that replica's own devices) — the honest
        silent-corruption simulation; detection is the engine guard's
        job."""
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(eng.params)
        for j, leaf in enumerate(leaves):
            if (hasattr(leaf, "dtype")
                    and jnp.issubdtype(leaf.dtype, jnp.inexact)):
                poisoned = jax.device_put(
                    jnp.full(leaf.shape, jnp.nan, leaf.dtype),
                    leaf.sharding)
                leaves[j] = poisoned
                break
        eng.params = jax.tree_util.tree_unflatten(treedef, leaves)
        self.logger.warning(
            "chaos: poisoned replica %d params with NaN (nan-logits "
            "fault injection)", idx)

    def _fail_replica(self, idx: int, exc: Exception) -> List[Request]:
        """Mark replica ``idx`` dead and fail its requests over.

        The faulted engine's device state is suspect, so nothing is
        salvaged from it: its slots are detached host-side (no block frees
        — the pool dies with the engine) and every stranded request is
        resubmitted least-loaded onto a survivor, where admission
        recomputes prompt + generated-so-far exactly like re-admission
        after preemption. Requests over ``max_retries`` (or with no
        survivors) finish as ``"error"`` and are returned so callers see
        them retire."""
        self._dead.add(idx)
        self._draining.discard(idx)
        # Lifecycle: with healing on this is a quarantine — the probe
        # loop rebuilds the engine from known-good weights and canaries
        # it back to live (unless the flap breaker evicts); with healing
        # off it is the legacy permanent death.
        if self._heal:
            self.lifecycle.on_fault(idx)
        else:
            self.lifecycle.mark_dead(idx)
        self.failover["replica_faults"] += 1
        eng = self.engines[idx]
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None:
            # Black box before failover rewrites the dead replica's
            # bookkeeping: which replica died, with what, holding what.
            rec.dump(reason="replica_fault", exc=exc, force=True,
                     extra={"replica": idx,
                            "in_flight": eng.num_active,
                            "queued": len(eng.waiting),
                            "survivors": self.num_live})
        self.logger.error(
            "replica %d step failed (%s: %s); failing over %d in-flight + "
            "%d queued request(s) to %d survivor(s)", idx, type(exc).__name__,
            exc, eng.num_active, len(eng.waiting), self.num_live)
        stranded: List[Request] = []
        for slot in eng.slots:
            if slot.request is not None and not slot.request.done:
                stranded.append(slot.request)
            # Detach host bookkeeping only: the dead engine's pool and KV
            # are abandoned wholesale, never reused.
            slot.request = None
            slot.blocks = []
            slot.seq_len = 0
            slot.next_pos = 0
            slot.prefill_end = 0
        stranded.extend(eng.waiting)
        eng.waiting.clear()

        errored: List[Request] = []
        live = self.live_engines()
        from dlti_tpu.telemetry.ledger import note_requeue

        for req in stranded:
            if not live or req.num_retries >= self.max_retries:
                if (not live and req.num_retries < self.max_retries
                        and self.failover_fallback is not None):
                    note_requeue(req, "failover")
                    if self.failover_fallback(req):
                        req.num_retries += 1
                        self.failover["retries"] += 1
                        continue
                req.finish_reason = "error"
                req.finish_time = time.monotonic()
                self.failover["failover_errors"] += 1
                self.telemetry.on_finished(req)
                # Visible in the finished ring so the server's event drain
                # (which walks slots + finished) delivers the error.
                eng.finished.append(req)
                errored.append(req)
                continue
            req.num_retries += 1
            self.failover["retries"] += 1
            # Critical-path attribution: the wait from here to
            # re-admission on the survivor books as "failover", not as
            # inflated prefill/decode (telemetry.ledger.note_requeue).
            note_requeue(req, "failover")
            target = min(live, key=self._load)
            target.resubmit(req)
            req.replica = self.engines.index(target)
        return errored

    # -- Replica lifecycle: drain/migrate, rebuild, canary, reload ------
    def _rehome(self, req: Request, eng: InferenceEngine,
                survivors: List[InferenceEngine], kind: str,
                ) -> List[Request]:
        """Failover-style resubmit of one request onto a survivor
        (recompute-on-readmit); errors it out past the retry cap or with
        no survivors (after offering the disagg rescue hook). Returns
        the request iff it errored."""
        from dlti_tpu.telemetry.ledger import note_requeue

        if not survivors or req.num_retries >= self.max_retries:
            if (not survivors and req.num_retries < self.max_retries
                    and self.failover_fallback is not None):
                note_requeue(req, kind)
                if self.failover_fallback(req):
                    req.num_retries += 1
                    self.failover["retries"] += 1
                    return []
            req.finish_reason = "error"
            req.finish_time = time.monotonic()
            self.failover["failover_errors"] += 1
            self.telemetry.on_finished(req)
            eng.finished.append(req)
            return [req]
        req.num_retries += 1
        self.failover["retries"] += 1
        note_requeue(req, kind)
        target = min(survivors, key=self._load)
        target.resubmit(req)
        req.replica = self.engines.index(target)
        return []

    def drain_replica(self, idx: int, *, kind: str = "preempt",
                      quarantine: bool = True) -> List[Request]:
        """Planned drain of one replica: move its in-flight decodes to
        survivors over the paged-KV handoff path (``export_handoff`` /
        ``adopt_handoff``) — generated-so-far tokens and the slot's rng
        stream survive byte-exactly, no re-prefill — falling back to a
        failover-style resubmit when handoff fails; queued and
        mid-prefill requests (nothing decodable to migrate) resubmit
        directly. With ``quarantine`` the replica then enters the
        lifecycle (healing on: quarantined → probe → live; healing off:
        dead); the rolling-reload driver passes ``quarantine=False`` and
        swaps weights itself. Returns the requests that errored out."""
        eng = self.engines[idx]
        self.lifecycle.begin_drain(idx)
        self._dead.add(idx)
        self._draining.discard(idx)
        survivors = self.live_engines()
        from dlti_tpu.telemetry.ledger import note_requeue

        migrated = fallbacks = 0
        errored: List[Request] = []
        for slot in list(eng.slots):
            req = slot.request
            if req is None or req.done:
                continue
            # The wall time from here to re-admission on the survivor
            # books as a requeue stall of this kind (the survivor's
            # adopt/admit closes the mark), not as inflated decode.
            note_requeue(req, kind)
            snap = None
            if survivors and not slot.prefilling:
                snap = eng.export_handoff(slot)
            if snap is not None:
                adopted = False
                for target in sorted(survivors, key=self._load):
                    if target.adopt_handoff(snap):
                        req.num_migrations += 1
                        req.replica = self.engines.index(target)
                        migrated += 1
                        adopted = True
                        break
                if adopted:
                    continue
                fallbacks += 1
            elif survivors and not slot.prefilling:
                fallbacks += 1
            # export_handoff leaves the slot intact on failure; release
            # it (the drained engine stays healthy — blocks go back to
            # its pool) and fail the request over.
            if slot.request is not None:
                eng._release(slot)
            errored.extend(self._rehome(req, eng, survivors, kind))
        stranded = list(eng.waiting)
        eng.waiting.clear()
        for req in stranded:
            errored.extend(self._rehome(req, eng, survivors, kind))
        if migrated:
            self.lifecycle.note_migration(migrated)
        if fallbacks:
            self.lifecycle.note_migration_fallback(fallbacks)
        self.logger.warning(
            "replica %d drained (%s): %d decode(s) migrated via KV "
            "handoff, %d re-prefill fallback(s), %d queued rehomed, %d "
            "errored", idx, kind, migrated, fallbacks, len(stranded),
            len(errored))
        if quarantine:
            if self._heal:
                self.lifecycle.on_fault(idx)
            else:
                self.lifecycle.mark_dead(idx)
        return errored

    def _rebuild_replica(self, idx: int, host_params=None) -> None:
        """Fresh engine for one replica from a host weight tree, on the
        replica's own device group. The fleet's SHARED telemetry is
        threaded through — a rebuilt replica keeps booking into the same
        histograms, and requests that later fail over again keep their
        ``stall_s`` phase attribution in ``request_breakdown()``."""
        host = host_params if host_params is not None else self._weights_host
        if host is None:
            raise RuntimeError(
                "no weights snapshot to rebuild from (lifecycle healing "
                "was disabled at construction)")
        old = self.engines[idx]
        mesh = self._meshes[idx]
        rep_params = (host if mesh is not None
                      else jax.device_put(host, self._groups[idx][0]))
        eng = InferenceEngine(self._model_cfg, rep_params,
                              self._rep_cfgs[idx], self._lora_cfg,
                              mesh=mesh, telemetry=self.telemetry)
        eng.prefill_only = old.prefill_only
        self.engines[idx] = eng
        if self._warmed and not eng.prefill_only:
            eng.warmup_decode_ladder()

    def _run_canary(self, eng: InferenceEngine) -> Optional[List[int]]:
        """Short greedy canary generation on one engine (only ever an
        engine carrying no live traffic: a rebuilt quarantined replica,
        or replica 0 at construction before any dispatch). Returns the
        emitted token ids, or None when generation fails — a NaN-poisoned
        replica trips the engine's numeric guard here, never in front of
        a client."""
        cfg = self.lifecycle_cfg
        vocab = max(2, self._model_cfg.vocab_size)
        prompt = [(i % min(97, vocab - 1)) + 1
                  for i in range(max(1, cfg.canary_prompt_tokens))]
        sp = SamplingParams(temperature=0.0,
                            max_tokens=max(1, cfg.canary_max_tokens))
        prev = eng.prefill_only
        eng.prefill_only = False
        try:
            req = eng.submit(prompt, sp,
                             f"canary-{next(self._req_counter)}")
            for _ in range(1000):
                if req.done:
                    break
                eng.step()
            if not req.done or req.finish_reason == "error":
                return None
            return list(req.output_token_ids)
        except Exception as e:  # noqa: BLE001 — a failed canary is a verdict
            self.logger.warning("canary generation failed: %s", e)
            return None
        finally:
            eng.prefill_only = prev

    def _probe_replica(self, idx: int) -> None:
        """Probation elapsed: rebuild the quarantined replica from
        known-good weights and gate reinstatement on the canary matching
        the pinned digest."""
        self.lifecycle.begin_probe(idx)
        toks = None
        try:
            self._rebuild_replica(idx)
            toks = self._run_canary(self.engines[idx])
        except Exception as e:  # noqa: BLE001 — a failed rebuild re-quarantines
            self.logger.error("replica %d rebuild/canary raised: %s", idx, e)
        ok = toks is not None and (
            self._canary_digest is None
            or canary_digest(toks) == self._canary_digest)
        if self.lifecycle.on_probe_result(idx, ok) == "live":
            self._dead.discard(idx)

    def request_reload(self, weights_provider, *, verify=None) -> bool:
        """Enqueue a rolling weight reload (thread-safe: one GIL-atomic
        attribute write; the roll itself runs on the stepper thread).
        ``weights_provider()`` is called once there and must return a
        host param tree with the boot tree's structure — the server's
        /v1/reload handler wraps a verified checkpoint-store load.
        ``verify()``, when given, is re-run immediately before EVERY
        per-replica swap (not just at the initial load): an export whose
        bytes rot mid-roll aborts the roll before the next replica
        touches it, instead of canary-failing halfway through. Returns
        False if a roll is already in progress."""
        if self._reload is not None:
            return False
        self._reload = {"provider": weights_provider, "host": None,
                        "queue": None, "digest": None, "verify": verify}
        return True

    def _reload_tick(self) -> None:
        """One rolling-reload action per step: drain-via-migration one
        replica, swap in the new weights, canary, reinstate — clients on
        other replicas never notice. The first upgraded replica pins the
        new canary digest with a determinism double-run; a canary failure
        aborts the roll (the failed replica re-quarantines and heals back
        onto the PREVIOUS weights — the fleet stays consistent)."""
        st = self._reload
        if st["host"] is None:
            try:
                st["host"] = st["provider"]()
            except Exception as e:  # noqa: BLE001 — bad checkpoint aborts roll
                self.logger.error(
                    "rolling reload aborted: weights provider failed: %s", e)
                self.last_reload_ok = False
                self._reload = None
                return
            st["queue"] = [i for i in range(len(self.engines))
                           if self.lifecycle.state(i) != "evicted"]
            self.logger.info("rolling reload: %d replica(s) queued",
                             len(st["queue"]))
        if not st["queue"]:
            self._weights_host = st["host"]
            if st["digest"] is not None:
                self._canary_digest = st["digest"]
            self.last_reload_ok = True
            self._reload = None
            self.logger.info("rolling reload complete")
            return
        idx = st["queue"][0]
        if st.get("verify") is not None:
            # Re-verify the export bytes before EVERY swap, not just the
            # initial provider load — a reload source corrupted mid-roll
            # (disk fault, concurrent overwrite) aborts here, before the
            # next replica is drained, instead of burning a drain +
            # rebuild on weights the canary would reject anyway. The
            # replicas already swapped keep the verified tree they loaded.
            ok_verify = False
            try:
                ok_verify = bool(st["verify"]())
            except Exception as e:  # noqa: BLE001 — verify fault = fail
                self.logger.error("reload re-verify raised: %s", e)
            if not ok_verify:
                self.logger.error(
                    "rolling reload aborted: export failed re-verification "
                    "before replica %d swap; fleet keeps serving (%d "
                    "replica(s) already on new weights stay)", idx,
                    len(self.engines) - len(st["queue"]))
                self.last_reload_ok = False
                self._reload = None
                return
        eng = self.engines[idx]
        others = [e for i, e in enumerate(self.engines)
                  if i != idx and i not in self._dead
                  and i not in self._draining]
        if others:
            self.drain_replica(idx, quarantine=False)
        else:
            # Sole live replica: no migration target. Lame-duck it (stop
            # dispatch, keep stepping) and wait for in-flight work to
            # finish before swapping; the gateway queues/sheds meanwhile.
            if idx not in self._draining and idx not in self._dead:
                self.lifecycle.begin_drain(idx)
                self._draining.add(idx)
            if eng.has_work:
                return
            self._draining.discard(idx)
            self._dead.add(idx)
        toks = None
        try:
            self._rebuild_replica(idx, host_params=st["host"])
            toks = self._run_canary(self.engines[idx])
        except Exception as e:  # noqa: BLE001 — failed swap handled below
            self.logger.error("replica %d reload rebuild failed: %s", idx, e)
        ok = toks is not None
        if ok and st["digest"] is None:
            # First replica on the new weights: nothing to compare
            # against, so gate on determinism (two identical greedy
            # runs) and pin the digest the rest of the roll checks.
            ok = self._run_canary(self.engines[idx]) == toks
            if ok:
                st["digest"] = canary_digest(toks)
        elif ok:
            ok = canary_digest(toks) == st["digest"]
        st["queue"].pop(0)
        if self.lifecycle.on_probe_result(idx, ok) == "live":
            self._dead.discard(idx)
        if not ok:
            self.logger.error(
                "rolling reload aborted: replica %d failed canary on new "
                "weights; fleet stays on previous weights", idx)
            self.last_reload_ok = False
            self._reload = None

    def _lifecycle_tick(self) -> None:
        """End-of-step lifecycle work, at most one heavy action per tick
        (bounded step latency): advance a rolling reload, else probe one
        quarantined replica whose probation elapsed. Runs on the stepper
        thread — the only thread allowed to touch slots/engines."""
        if self._reload is not None:
            self._reload_tick()
            return
        if not self._heal:
            return
        due = self.lifecycle.due_probes()
        if due:
            self._probe_replica(due[0])

    @property
    def lifecycle_pending(self) -> bool:
        """True when the stepper must keep ticking without client work —
        a reload is rolling or a quarantined replica awaits its probe.
        The server's AsyncEngine polls instead of parking on its event
        when this is set."""
        if self._reload is not None:
            return True
        if not self._heal:
            return False
        return any(s in ("quarantined", "probing")
                   for s in self.lifecycle.states().values())

    def lifecycle_counts(self) -> dict:
        """/health summary: ``quarantined`` replicas are healing (probe
        pending/running) and expected back; ``dead`` ones (flap-evicted,
        or faulted with healing off) are gone for good."""
        c = self.lifecycle.counts()
        return {"live": c["live"],
                "quarantined": c["quarantined"] + c["probing"],
                "draining": c["draining"],
                "dead": c["evicted"]}

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        """Offline batch generation across all replicas. Per-replica step
        faults fail over inside :meth:`step`, so a single replica death
        mid-drain no longer orphans requests on healthy replicas."""
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        out = []
        for r in reqs:
            eng = self.engines[r.replica]
            out.append(eng._result(r))
        return out

    # -- InferenceEngine-compat surface (AsyncEngine / gateway) ---------
    def warmup_decode_ladder(self) -> None:
        self._warmed = True  # rebuilt replicas re-warm before reinstating
        for e in self.engines:
            e.warmup_decode_ladder()

    @property
    def cfg(self) -> EngineConfig:
        return self.engines[0].cfg

    @property
    def slots(self) -> list:
        return [s for e in self.engines for s in e.slots]

    @property
    def finished(self) -> List[Request]:
        return [r for e in self.engines for r in e.finished]

    @property
    def waiting(self) -> List[Request]:
        return [r for e in self.engines for r in e.waiting]

    @property
    def num_active(self) -> int:
        return sum(e.num_active for e in self.engines)

    @property
    def num_free_blocks(self) -> int:
        return sum(e.num_free_blocks for e in self.live_engines())

    def abort_all(self, reason: str = "abort") -> List[Request]:
        aborted: List[Request] = []
        for i, e in enumerate(self.engines):
            if i not in self._dead:
                aborted.extend(e.abort_all(reason=reason))
        return aborted

    @property
    def stats(self) -> dict:
        """Aggregated counters across replicas (per-replica under 'replicas')."""
        keys = self.engines[0].stats.keys()
        agg = {k: sum(e.stats[k] for e in self.engines) for k in keys}
        agg["replicas"] = [dict(e.stats) for e in self.engines]
        return agg
