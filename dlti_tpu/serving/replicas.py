"""Data-parallel serving: independent engine replicas over device groups.

The reference claims vLLM serving with tensor parallelism
(``/root/reference/README.md:10``); vLLM scales *throughput* beyond one
TP group by running multiple engine replicas behind a dispatcher. This is
the TPU-native equivalent: the visible devices are partitioned into
``replicas`` groups of ``tensor`` chips, each group gets a fully
independent :class:`InferenceEngine` (its own sharded weights, KV pool,
scheduler, prefix cache), and requests are dispatched least-loaded.

Replication is deliberately *above* the engine rather than a mesh axis
inside it: batch rows of one jitted program sharded over a ``data`` axis
would lock every replica to the same program counter (one global decode
step), while independent engines prefill, decode and preempt on their own
schedules — the same reason vLLM runs one engine per data-parallel rank.
Within a replica, jit dispatch is async, so driving the replicas
round-robin from one host thread overlaps their device work.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence

import jax

from dlti_tpu.config import LoRAConfig, ModelConfig, ParallelConfig
from dlti_tpu.serving.engine import (
    EngineConfig, GenerationResult, InferenceEngine, Request, SamplingParams,
)
from dlti_tpu.telemetry import RequestTelemetry


class ReplicatedEngine:
    """N independent engine replicas (each optionally TP-sharded) behind a
    least-loaded dispatcher. API mirrors :class:`InferenceEngine`:
    ``submit`` / ``step`` / ``generate`` / ``has_work``."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg: Optional[LoRAConfig] = None,
        *,
        replicas: int = 1,
        tensor: int = 1,
        devices: Optional[Sequence] = None,
    ):
        devices = list(devices if devices is not None else jax.devices())
        if replicas < 1 or tensor < 1:
            raise ValueError(
                f"replicas ({replicas}) and tensor ({tensor}) must be >= 1")
        need = replicas * tensor
        if need > len(devices):
            raise ValueError(
                f"{replicas} replicas x tensor={tensor} needs {need} "
                f"devices, have {len(devices)}")
        from dlti_tpu.parallel.mesh import build_mesh

        # One shared request-telemetry instance: every replica observes
        # into the same TTFT/TPOT/queue-time histograms, so the fleet's
        # latency distributions aggregate without a merge step.
        self.telemetry = RequestTelemetry()
        self.engines: List[InferenceEngine] = []
        for r in range(replicas):
            group = devices[r * tensor:(r + 1) * tensor]
            mesh = (build_mesh(ParallelConfig(tensor=tensor), devices=group)
                    if tensor > 1 else None)
            # Single-chip replicas (tensor=1) pin weights to their device
            # explicitly — engines would otherwise all initialize onto the
            # default device.
            rep_params = (params if mesh is not None
                          else jax.device_put(params, group[0]))
            self.engines.append(
                InferenceEngine(model_cfg, rep_params, engine_cfg, lora_cfg,
                                mesh=mesh, telemetry=self.telemetry))
        self._rr = 0
        # Own id namespace: each engine's req-N counter starts at 0, so
        # auto-ids from different replicas would collide in any id-keyed
        # consumer (server streams, generate()'s by_id map).
        self._req_counter = itertools.count()

    # ------------------------------------------------------------------
    def _load(self, eng: InferenceEngine) -> int:
        return len(eng.waiting) + eng.num_active

    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None) -> Request:
        """Dispatch to the least-loaded replica (round-robin tiebreak)."""
        order = (self.engines[self._rr:] + self.engines[:self._rr])
        self._rr = (self._rr + 1) % len(self.engines)
        eng = min(order, key=self._load)
        if request_id is None:
            request_id = f"rep-req-{next(self._req_counter)}"
        req = eng.submit(prompt_token_ids, params, request_id)
        req.replica = self.engines.index(eng)
        return req

    @property
    def has_work(self) -> bool:
        return any(e.has_work for e in self.engines)

    def step(self) -> List[Request]:
        """One scheduler iteration on every replica that has work.

        jit dispatch is async, so each replica's device program launches
        before the next replica's host-side scheduling runs — the chips
        decode concurrently even though this is one Python loop.
        """
        finished: List[Request] = []
        for eng in self.engines:
            if eng.has_work:
                finished.extend(eng.step())
        return finished

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        """Offline batch generation across all replicas."""
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        out = []
        for r in reqs:
            eng = self.engines[r.replica]
            out.append(eng._result(r))
        return out

    @property
    def stats(self) -> dict:
        """Aggregated counters across replicas (per-replica under 'replicas')."""
        keys = self.engines[0].stats.keys()
        agg = {k: sum(e.stats[k] for e in self.engines) for k in keys}
        agg["replicas"] = [dict(e.stats) for e in self.engines]
        return agg
