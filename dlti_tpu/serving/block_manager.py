"""Host-side KV block allocator.

The bookkeeping half of the paged cache (device half:
``dlti_tpu.ops.kv_cache``) — the role vLLM's C++/Python BlockManager plays in
the stack the reference claims but doesn't ship (``README.md:10``).

When the native runtime library has been built (``native/``), allocation is
delegated to the C++ core via ctypes; otherwise a pure-Python free-list is
used. Both implement the same contract and are covered by the same tests.

Physical block 0 is reserved as a trash block: inactive decode slots write
their (ignored) K/V there, so the compiled decode step never needs a branch
on slot liveness.
"""

from __future__ import annotations

from typing import List, Optional

from dlti_tpu.utils.native import load_native_runtime


class BlockManager:
    """Free-list allocator over ``num_blocks`` physical KV blocks."""

    TRASH_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._native = load_native_runtime()
        if self._native is not None:
            self._handle = self._native.dlti_allocator_create(num_blocks)
            # Older prebuilt libraries predate the checked-free ABI; they
            # keep the legacy (unguarded) free path.
            self._checked_free = hasattr(self._native,
                                         "dlti_allocator_free_checked")
        else:
            self._handle = None
            # Block 0 reserved; LIFO free list for cache locality.
            self._free: List[int] = list(range(num_blocks - 1, 0, -1))
            # O(1) double-free guard: the set of live (handed-out) blocks.
            # A double free would silently put one block on the free list
            # twice — two sequences then share a "private" block and decode
            # state corrupts with no error anywhere near the cause.
            self._allocated: set = set()

    def __del__(self):
        if getattr(self, "_native", None) is not None and self._handle:
            self._native.dlti_allocator_destroy(self._handle)
            self._handle = None

    # ------------------------------------------------------------------
    @property
    def num_free(self) -> int:
        if self._native is not None:
            return self._native.dlti_allocator_num_free(self._handle)
        return len(self._free)

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return self.num_free >= n

    def allocate(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks; returns None (allocating nothing) if they
        don't all fit — admission is all-or-nothing."""
        if n == 0:
            return []
        if self._native is not None:
            import ctypes

            out = (ctypes.c_int32 * n)()
            ok = self._native.dlti_allocator_allocate(self._handle, n, out)
            return list(out) if ok else None
        if len(self._free) < n:
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        """Return ``blocks`` to the pool. Raises on an invalid id or a
        double free (all-or-nothing: a rejected call frees none), instead
        of silently corrupting the pool into handing one block to two
        sequences."""
        if not blocks:
            return
        if self._native is not None:
            import ctypes

            arr = (ctypes.c_int32 * len(blocks))(*blocks)
            if self._checked_free:
                ok = self._native.dlti_allocator_free_checked(
                    self._handle, len(blocks), arr)
                if not ok:
                    raise ValueError(
                        f"invalid or double free in {blocks} (native "
                        "allocator rejected the batch; no block was freed)")
            else:
                self._native.dlti_allocator_free(self._handle, len(blocks), arr)
            return
        # Validate the whole batch first (including intra-batch
        # duplicates) so a raise frees nothing.
        seen: set = set()
        for b in blocks:
            if b == self.TRASH_BLOCK or b <= 0 or b >= self.num_blocks:
                raise ValueError(f"freeing invalid block {b}")
            if b not in self._allocated or b in seen:
                raise ValueError(
                    f"double free of block {b} (not currently allocated); "
                    "freeing it again would hand the same block to two "
                    "sequences and silently corrupt their KV")
            seen.add(b)
        for b in blocks:
            self._allocated.discard(b)
            self._free.append(b)
