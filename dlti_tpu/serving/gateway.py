"""Admission gateway: the scheduling front-end between HTTP and engine(s).

vLLM-style engines pair continuous batching with an admission layer for
production traffic; without one this server admitted unboundedly — every
request dispatched immediately, nothing shed load, an interactive user and
a batch job were indistinguishable, and a replica fault errored the fleet.
This module is that missing layer:

* **Bounded admission queue** — configurable max queued requests and max
  queued prompt tokens; overflow is rejected with HTTP 429 + ``Retry-After``
  instead of growing without limit.
* **Per-tenant token-bucket rate limiting** — tenant identity from the
  ``X-Tenant`` header (or a stable digest of ``Authorization``), the
  default tenant otherwise; refusals carry a deficit-derived Retry-After.
* **Priority + deadline scheduling** — strict ``interactive`` > ``batch``
  classes, weighted fair dequeue across tenants *within* a class (stride
  scheduling: pick the queued tenant with the least virtual time, advance
  it by 1/weight). Requests whose deadline expires while still queued are
  shed *before* prefill with a 503; expiry mid-decode flips
  ``cancel_requested`` so they stop burning decode slots.
* **Graceful drain** — :meth:`AdmissionGateway.drain` (wired to SIGTERM in
  ``server.serve``) rejects new admissions with 503, lets queued and
  in-flight requests finish, then the server exits.
* **Failover visibility** — replica fault/retry counters from
  :class:`~dlti_tpu.serving.replicas.ReplicatedEngine` ride out through
  the same ``dlti_gateway_*`` metrics block.

The gateway holds requests in its own per-(priority, tenant) queues and
dispatches into the engine only while the engine can admit promptly (free
slot headroom), so ordering decisions happen here — not in the engine's
FCFS deque. Everything reports through the PR 1 ``MetricsRegistry``
(``dlti_gateway_*`` series on ``/metrics``) and the lifecycle tracer
(``gateway/queued`` spans, shed/reject instants).
"""

from __future__ import annotations

import collections
import hashlib
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlti_tpu.config import GatewayConfig
from dlti_tpu.serving.sampling import SamplingParams
from dlti_tpu.telemetry.distributed_trace import mint_trace_id
from dlti_tpu.utils.logging import get_logger

# Strict class order: every queued interactive request dequeues before any
# batch request (fairness applies across tenants within a class).
PRIORITIES = ("interactive", "batch")

# Name-stability contract for the /metrics exposition (schema-guarded by
# tests/test_bench_contract.py, like the dlti_<stat> names before them).
GATEWAY_METRIC_NAMES = (
    "dlti_gateway_queue_depth",
    "dlti_gateway_queued_tokens",
    "dlti_gateway_inflight",
    "dlti_gateway_replicas_alive",
    "dlti_gateway_admitted_total",
    "dlti_gateway_rejected_total",
    "dlti_gateway_shed_total",
    "dlti_gateway_retries_total",
    "dlti_gateway_replica_faults_total",
    # Cache-affinity routing (ReplicatedEngine): requests routed to their
    # sticky rendezvous-hash replica vs spilled least-loaded because the
    # sticky target's backlog exceeded the spill threshold.
    "dlti_gateway_affinity_sticky_total",
    "dlti_gateway_affinity_spill_total",
)


class AdmissionError(RuntimeError):
    """Synchronous admission refusal: maps to one HTTP error response."""

    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.retry_after = retry_after


class GatewayRequest:
    """Request facade handed to the HTTP handler at admission time.

    Mirrors the :class:`~dlti_tpu.serving.engine.Request` surface the
    server reads (id, prompt ids, params, outputs, cancel flag) and binds
    to the real engine request when the dispatcher admits it — so handlers
    block on the event queue the moment the gateway accepts, whether the
    request is queued or running. ``cancel_requested`` set while still
    queued makes the dispatcher discard the entry without ever prefilling.
    """

    def __init__(self, request_id: str, prompt_token_ids: List[int],
                 params: SamplingParams):
        self.request_id = request_id
        self.prompt_token_ids = list(prompt_token_ids)
        self.params = params
        # Distributed-trace context, minted HERE — at admission — so the
        # gateway's own spans and every downstream process leg share one
        # id. Dispatch passes it into the engine submit chain (never
        # assigned after the fact: a fleet stepper may serialize the
        # FT_SUBMIT descriptor the instant the mirror lands).
        self.trace_id = mint_trace_id()
        self._req = None
        self._cancel = False

    def bind(self, req) -> None:
        self._req = req
        if self._cancel:
            req.cancel_requested = True

    @property
    def output_token_ids(self) -> list:
        return self._req.output_token_ids if self._req is not None else []

    @property
    def done(self) -> bool:
        return self._req is not None and self._req.done

    @property
    def cancel_requested(self) -> bool:
        if self._req is not None:
            return self._req.cancel_requested
        return self._cancel

    @cancel_requested.setter
    def cancel_requested(self, value: bool) -> None:
        self._cancel = bool(value)
        if self._req is not None:
            self._req.cancel_requested = bool(value)


@dataclass
class _Pending:
    """One gateway-queued admission."""

    handle: GatewayRequest
    q: "queue.Queue"
    tenant: str
    priority: str
    deadline: Optional[float]  # absolute monotonic, None = none
    # Session/prefix stickiness key for cache-affinity replica routing
    # (None = least-loaded dispatch, the legacy behavior).
    affinity_key: Optional[str] = None
    # Resolved LoRA adapter name ("" = base model) — X-Adapter header,
    # else the tenant's --gateway-adapter-map entry.
    adapter: str = ""
    enqueue_t: float = field(default_factory=time.monotonic)


class _TokenBucket:
    """Classic token bucket; caller holds the gateway lock."""

    __slots__ = ("tokens", "stamp")

    def __init__(self, burst: float):
        self.tokens = burst
        self.stamp = time.monotonic()

    def take(self, rate: float, burst: float) -> Optional[float]:
        """Consume one token; returns None on success, else seconds until
        one accrues (the Retry-After)."""
        now = time.monotonic()
        self.tokens = min(burst, self.tokens + (now - self.stamp) * rate)
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        return max(0.001, (1.0 - self.tokens) / rate)


def tenant_from_headers(headers, default: str = "default") -> str:
    """``X-Tenant`` wins; else a stable digest of the Authorization
    credential (so per-key limits work without a tenant registry); else
    the default tenant."""
    tenant = headers.get("X-Tenant") if headers is not None else None
    if tenant:
        return tenant.strip()
    auth = headers.get("Authorization") if headers is not None else None
    if auth:
        return "auth-" + hashlib.sha256(auth.encode()).hexdigest()[:12]
    return default


def affinity_key_from(headers, prompt_token_ids,
                      prefix_tokens: int = 32, adapter: str = "") -> str:
    """Session/prefix key for cache-affinity replica routing.

    ``X-Session`` wins (a chat client naming its conversation); else a
    stable digest of the prompt's first ``prefix_tokens`` token ids — so
    even session-less clients sharing a system prompt land on the replica
    whose prefix cache already holds it.

    ``adapter`` is mixed into BOTH branches: prefix-cache block chains
    are namespaced per adapter (the same prompt under different adapters
    produces different KV), so routing two adapters' identical prompts
    to one replica's cache would never hit anyway — better to land each
    adapter where ITS blocks (and its pool row) already live. Empty
    adapter keeps the legacy keys byte-identical."""
    sess = headers.get("X-Session") if headers is not None else None
    tag = f"@{adapter}" if adapter else ""
    if sess:
        return "sess-" + sess.strip() + tag
    ids = list(prompt_token_ids[:max(1, prefix_tokens)])
    return "pfx-" + hashlib.sha256(
        (repr(ids) + tag).encode()).hexdigest()[:16]


def parse_adapter_map(spec: str) -> Dict[str, str]:
    """"tenantA:ad1,tenantB:ad2" -> {"tenantA": "ad1", ...}: tenant →
    adapter routing for requests that carry no ``X-Adapter`` header."""
    out: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, ad = part.partition(":")
        if not sep or not name.strip() or not ad.strip():
            raise ValueError(f"bad adapter mapping {part!r} "
                             "(expected tenant:adapter)")
        out[name.strip()] = ad.strip()
    return out


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """"tenantA:4,tenantB:1" -> {"tenantA": 4.0, "tenantB": 1.0}."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        try:
            weight = float(w) if w else 1.0
        except ValueError:
            raise ValueError(f"bad tenant weight {part!r} "
                             f"(expected name:weight)")
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0: {part!r}")
        out[name.strip()] = weight
    return out


class AdmissionGateway:
    """Bounded, rate-limited, priority/deadline-scheduled admission in
    front of an :class:`~dlti_tpu.serving.server.AsyncEngine`."""

    def __init__(self, async_engine, cfg: GatewayConfig, registry=None):
        self.async_engine = async_engine
        self.cfg = cfg
        self.logger = get_logger()
        self._tracer = async_engine.engine.telemetry.tracer
        self._weights = parse_tenant_weights(cfg.tenant_weights)
        self._adapter_map = parse_adapter_map(
            getattr(cfg, "adapter_map", ""))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # Per-class, per-tenant FIFO queues + stride-scheduling state.
        self._queues: Dict[str, Dict[str, collections.deque]] = {
            p: {} for p in PRIORITIES}
        self._vtime: Dict[str, float] = {}
        self._vfloor = 0.0
        self._buckets: Dict[str, _TokenBucket] = {}
        self._queued_requests = 0
        self._queued_tokens = 0
        self._inflight: List[_Pending] = []
        self._draining = False
        self._drain_t0: Optional[float] = None
        self._stop = False

        # Metrics: labeled counters are first-class registry objects; live
        # gauges + the engine-owned failover counters ride a scalar source
        # (same pattern as the engine stats — no lock on the hot path).
        self._m_admitted = self._m_rejected = self._m_shed = None
        if registry is not None:
            self._m_admitted = registry.counter(
                "dlti_gateway_admitted_total",
                help="requests admitted through the gateway")
            self._m_rejected = registry.counter(
                "dlti_gateway_rejected_total",
                help="admissions refused (reason + priority labels)")
            self._m_shed = registry.counter(
                "dlti_gateway_shed_total",
                help="queued requests shed at deadline expiry before "
                     "prefill (priority label)")
            registry.add_scalar_source(
                self._scalars,
                gauge_keys=("gateway_queue_depth", "gateway_queued_tokens",
                            "gateway_inflight", "gateway_replicas_alive"),
                prefix="dlti_")

        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dlti-gateway-dispatch")
        self._thread.start()

    # -- observability --------------------------------------------------
    def _scalars(self) -> dict:
        eng = self.async_engine.engine
        fail = getattr(eng, "failover", None) or {}
        aff = getattr(eng, "affinity", None) or {}
        with self._lock:
            depth, toks, infl = (self._queued_requests, self._queued_tokens,
                                 len(self._inflight))
        return {
            "gateway_queue_depth": depth,
            "gateway_queued_tokens": toks,
            "gateway_inflight": infl,
            "gateway_replicas_alive": getattr(eng, "num_live", 1),
            "gateway_retries_total": fail.get("retries", 0),
            "gateway_replica_faults_total": fail.get("replica_faults", 0),
            "gateway_affinity_sticky_total": aff.get("sticky", 0),
            "gateway_affinity_spill_total": aff.get("spill", 0),
        }

    @property
    def draining(self) -> bool:
        return self._draining

    def _retry_after_s(self) -> float:
        """Static backoff, floored by the fleet supervisor's respawn
        backoff when one is pending — a client that honors the header
        retries when capacity is actually expected back, instead of
        landing on the next refusal."""
        backoff = getattr(self.async_engine.engine,
                          "respawn_retry_after_s", 0.0)
        return max(self.cfg.retry_after_s, backoff)

    def adapter_for(self, tenant: str) -> str:
        """The tenant's configured LoRA adapter (``adapter_map``); ""
        routes to the base model. An ``X-Adapter`` header overrides."""
        return self._adapter_map.get(tenant, "")

    # -- admission ------------------------------------------------------
    def submit(self, prompt_token_ids, params: SamplingParams,
               request_id: str, *, tenant: Optional[str] = None,
               priority: str = "interactive",
               deadline_s: float = 0.0,
               affinity_key: Optional[str] = None,
               adapter: str = "",
               ) -> Tuple[GatewayRequest, queue.Queue]:
        """Admit or refuse synchronously. Returns ``(handle, event_queue)``
        — same event protocol as ``AsyncEngine.submit`` plus the terminal
        ``("reject", status, message)`` for post-admission sheds. Raises
        :class:`AdmissionError` on refusal (429 bounds/rate, 503 drain,
        404 unknown adapter)."""
        tenant = tenant or self.cfg.default_tenant
        if priority not in PRIORITIES:
            raise AdmissionError(
                400, f"priority must be one of {PRIORITIES}, got {priority!r}")
        if not adapter:
            adapter = self._adapter_map.get(tenant, "")
        if adapter:
            # Routing-time validation against the process-global catalog:
            # an unknown adapter is the CLIENT's error (404 here) — it
            # must never reach the engine, whose only recourse would be
            # failing the request after it burned a queue slot.
            from dlti_tpu.serving.adapters import get_catalog

            if adapter not in get_catalog():
                self._reject("unknown_adapter", priority, tenant=tenant)
                raise AdmissionError(
                    404, f"unknown adapter {adapter!r}: register it via "
                         f"POST /v1/adapters first")
        n_tokens = len(prompt_token_ids)
        with self._cond:
            if self._draining or self._stop:
                self._reject("draining", priority)
                # Retry-After derived from the expected drain time: the
                # remaining SIGTERM grace window (a retrying client that
                # honors it lands on the replacement process, not on the
                # next refusal), floored at the static backoff.
                retry_after = self._retry_after_s()
                if self._drain_t0 is not None:
                    remaining = self.cfg.drain_grace_s - (
                        time.monotonic() - self._drain_t0)
                    retry_after = max(retry_after, remaining)
                raise AdmissionError(
                    503, "server is draining; not accepting new requests",
                    retry_after=retry_after)
            if self.cfg.rate_limit_rps > 0:
                burst = (self.cfg.rate_limit_burst
                         or max(1.0, 2.0 * self.cfg.rate_limit_rps))
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    bucket = self._buckets[tenant] = _TokenBucket(burst)
                wait = bucket.take(self.cfg.rate_limit_rps, burst)
                if wait is not None:
                    self._reject("rate_limited", priority, tenant=tenant)
                    raise AdmissionError(
                        429, f"tenant {tenant!r} over rate limit "
                             f"({self.cfg.rate_limit_rps:g} req/s)",
                        retry_after=wait)
            if self._queued_requests + 1 > self.cfg.max_queued_requests:
                self._reject("queue_full", priority)
                raise AdmissionError(
                    429, f"admission queue full "
                         f"({self.cfg.max_queued_requests} requests)",
                    retry_after=self._retry_after_s())
            if (self.cfg.max_queued_tokens > 0
                    and self._queued_tokens + n_tokens
                    > self.cfg.max_queued_tokens):
                self._reject("queue_full", priority)
                raise AdmissionError(
                    429, f"admission queue full "
                         f"({self.cfg.max_queued_tokens} queued prompt "
                         f"tokens)",
                    retry_after=self._retry_after_s())

            handle = GatewayRequest(request_id, prompt_token_ids, params)
            entry = _Pending(
                handle=handle, q=queue.Queue(), tenant=tenant,
                priority=priority,
                deadline=(time.monotonic() + deadline_s
                          if deadline_s and deadline_s > 0 else None),
                affinity_key=affinity_key, adapter=adapter)
            dq = self._queues[priority].setdefault(tenant, collections.deque())
            if not dq:
                # (Re)activating tenant: sync its virtual time to the
                # floor so an idle spell doesn't bank unbounded credit.
                self._vtime[tenant] = max(self._vtime.get(tenant, 0.0),
                                          self._vfloor)
            dq.append(entry)
            self._queued_requests += 1
            self._queued_tokens += n_tokens
            if self._m_admitted is not None:
                self._m_admitted.labels(tenant=tenant, priority=priority).inc()
            self._tracer.instant("gateway/enqueued", cat="gateway",
                                 id=request_id, trace=handle.trace_id,
                                 tenant=tenant, priority=priority)
            self._cond.notify()
        return handle, entry.q

    def _reject(self, reason: str, priority: str = "interactive",
                **labels) -> None:
        # Priority rides every refusal so per-class availability SLIs
        # (telemetry.slo) can difference admitted/rejected/shed per class.
        if self._m_rejected is not None:
            self._m_rejected.labels(reason=reason, priority=priority).inc()
        self._tracer.instant("gateway/rejected", cat="gateway",
                             reason=reason, priority=priority, **labels)

    # -- scheduling -----------------------------------------------------
    def _engine_room(self) -> int:
        """Free decode-slot headroom across live replicas, minus what is
        already waiting in engine queues: dispatch keeps the engine's FCFS
        deque near-empty so ordering stays a gateway decision."""
        eng = self.async_engine.engine
        engines = (eng.live_engines() if hasattr(eng, "live_engines")
                   else [eng])
        return sum(e.cfg.max_seqs - e.num_active - len(e.waiting)
                   for e in engines)

    def _pop_next_locked(self) -> Optional[_Pending]:
        for prio in PRIORITIES:
            by_tenant = self._queues[prio]
            ready = [t for t, dq in by_tenant.items() if dq]
            if not ready:
                continue
            # Stride scheduling: least virtual time wins; advancing by
            # 1/weight gives weight-proportional dequeue share.
            t = min(ready, key=lambda t: (self._vtime.get(t, 0.0), t))
            self._vtime[t] = (self._vtime.get(t, 0.0)
                              + 1.0 / self._weights.get(t, 1.0))
            self._vfloor = self._vtime[t]
            entry = by_tenant[t].popleft()
            self._queued_requests -= 1
            self._queued_tokens -= len(entry.handle.prompt_token_ids)
            return entry
        return None

    def _shed_expired_locked(self) -> None:
        """Deadline enforcement: queued past-deadline entries are shed
        before prefill (503 to the waiting handler); in-flight ones get
        ``cancel_requested`` so the engine releases their slot within one
        decode window."""
        now = time.monotonic()
        for prio in PRIORITIES:
            for tenant, dq in self._queues[prio].items():
                expired = [e for e in dq
                           if e.deadline is not None and e.deadline <= now]
                for e in expired:
                    dq.remove(e)
                    self._queued_requests -= 1
                    self._queued_tokens -= len(e.handle.prompt_token_ids)
                    if self._m_shed is not None:
                        self._m_shed.labels(priority=prio).inc()
                    self._tracer.instant(
                        "gateway/shed", cat="gateway",
                        id=e.handle.request_id, tenant=tenant, queued_s=round(
                            now - e.enqueue_t, 4))
                    e.q.put(("reject", 503,
                             "deadline expired while queued (shed before "
                             "prefill)", self.cfg.retry_after_s))
        alive = []
        for e in self._inflight:
            if e.handle.done:
                continue
            if e.deadline is not None and e.deadline <= now:
                e.handle.cancel_requested = True
                continue
            alive.append(e)
        self._inflight = alive

    def _run(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                self._shed_expired_locked()
                entry = None
                if self._queued_requests > 0 and self._engine_room() > 0:
                    entry = self._pop_next_locked()
                if entry is None:
                    # Deadlines and slot churn are time-driven, so the
                    # wait is bounded even with no submit notifications.
                    self._cond.wait(timeout=0.005)
                    continue
            if entry.handle.cancel_requested:
                # Cancelled while queued (client disconnect / timeout):
                # never reaches the engine.
                entry.q.put(("done", "stop"))
                continue
            try:
                # affinity_key rides as a kwarg only when set, so engine
                # facades predating it keep working with affinity off.
                kw = ({"affinity_key": entry.affinity_key}
                      if entry.affinity_key else {})
                if entry.adapter:
                    kw["adapter"] = entry.adapter
                req, _ = self.async_engine.submit(
                    entry.handle.prompt_token_ids, entry.handle.params,
                    entry.handle.request_id, q=entry.q,
                    trace_id=entry.handle.trace_id, **kw)
            except Exception as e:  # engine parked / all replicas dead
                self._reject("engine_unavailable", entry.priority)
                entry.q.put(("reject", 503, f"{type(e).__name__}: {e}"))
                continue
            req.tenant = entry.tenant
            req.priority = entry.priority
            req.deadline = entry.deadline
            # Critical-path t0 (telemetry.ledger): the client's latency
            # clock started at gateway admission, not engine submit — the
            # request's phase breakdown must sum from here.
            req.gateway_enqueue_time = entry.enqueue_t
            entry.handle.bind(req)
            now = time.monotonic()
            self._tracer.complete("gateway/queued", entry.enqueue_t, now,
                                  cat="gateway", id=entry.handle.request_id,
                                  trace=entry.handle.trace_id,
                                  tenant=entry.tenant,
                                  priority=entry.priority)
            with self._cond:
                self._inflight.append(entry)

    # -- drain / shutdown ----------------------------------------------
    def drain(self) -> None:
        """Stop admitting (new submits get 503); queued and in-flight
        requests run to completion. ``/health`` reports ``draining``."""
        with self._cond:
            self._draining = True
            if self._drain_t0 is None:
                self._drain_t0 = time.monotonic()
            self._cond.notify()
        self.logger.info("gateway draining: refusing new admissions")

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until queue + in-flight are empty and the engine has no
        work (True), or the grace period lapses (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                idle = (self._queued_requests == 0
                        and not any(not e.handle.done for e in self._inflight))
            if idle and not self.async_engine.engine.has_work:
                return True
            time.sleep(0.01)
        return False

    def shutdown(self) -> None:
        with self._cond:
            self._stop = True
            for prio in PRIORITIES:
                for dq in self._queues[prio].values():
                    while dq:
                        dq.popleft().q.put(("error", "server shutting down"))
            self._queued_requests = 0
            self._queued_tokens = 0
            self._cond.notify()
        self._thread.join(timeout=5)
