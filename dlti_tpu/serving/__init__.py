"""TPU-native serving engine.

The leg the reference README claims — "High-throughput serving with vLLM and
tensor parallelism" (``README.md:10``), ``vllm==0.6.0`` pinned at
``requirements.txt:18`` — but never implements (SURVEY.md §0). Built here
from scratch for TPU:

* :mod:`dlti_tpu.ops.kv_cache` — paged block KV cache (device ops)
* :mod:`dlti_tpu.serving.block_manager` — host-side block allocator
  (C++ core via ctypes when built, pure-Python fallback)
* :mod:`dlti_tpu.serving.sampling` — jitted sampling (greedy / temperature /
  top-k / top-p)
* :mod:`dlti_tpu.serving.engine` — continuous-batching inference engine:
  bucketed prefill + single-token batched decode, one compiled program each
* :mod:`dlti_tpu.serving.gateway` — admission gateway: bounded queues,
  per-tenant rate limits, priority/deadline scheduling, graceful drain
* :mod:`dlti_tpu.serving.replicas` — data-parallel engine replicas with
  fault isolation and retry-capped failover
* :mod:`dlti_tpu.serving.disagg` — prefill/decode disaggregation: split
  engine pools with paged-KV handoff and phase-aware routing
* :mod:`dlti_tpu.serving.wire` / :mod:`dlti_tpu.serving.worker` /
  :mod:`dlti_tpu.serving.fleet` — multi-process fleet: length-prefixed
  digest-verified TCP protocol, engine worker processes, and a
  spawning/healing supervisor behind the ReplicatedEngine facade
* :mod:`dlti_tpu.serving.server` — OpenAI-compatible HTTP server
* :mod:`dlti_tpu.serving.deploy` — continuous delivery: checkpoint-watching
  deploy controller with shadow-traffic canary and autonomous
  promote/rollback
"""

from dlti_tpu.serving.block_manager import BlockManager  # noqa: F401
from dlti_tpu.serving.sampling import SamplingParams, sample_tokens  # noqa: F401
from dlti_tpu.serving.engine import (  # noqa: F401
    EngineConfig,
    GenerationResult,
    InferenceEngine,
    NumericFault,
    Request,
)
from dlti_tpu.serving.replicas import ReplicatedEngine  # noqa: F401
from dlti_tpu.serving.disagg import DisaggController  # noqa: F401
from dlti_tpu.serving.fleet import (  # noqa: F401
    FleetSupervisor,
    make_subprocess_spawner,
)
from dlti_tpu.serving.deploy import DeploymentController  # noqa: F401
from dlti_tpu.serving.gateway import (  # noqa: F401
    AdmissionError,
    AdmissionGateway,
    GatewayRequest,
)
from dlti_tpu.serving.server import (  # noqa: F401
    ServerConfig,
    make_server,
    serve,
)
