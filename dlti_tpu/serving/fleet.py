"""Multi-process serving fleet: a supervisor over N engine worker processes.

PRs 12–15 built disaggregation, self-healing, KV migration and rolling
reloads as a same-process simulation (``ReplicatedEngine`` "replicas"
share a GIL, a host, and a failure domain). This module is the real
distribution layer: :class:`FleetSupervisor` spawns N
``scripts/engine_worker.py`` processes, drives them over the TCP wire
protocol (``serving.wire``), and presents the exact
``ReplicatedEngine``-compatible facade (submit / step / generate / stats
/ failover / affinity / lifecycle) the gateway and HTTP server already
speak — ``serve.py --fleet-workers N`` serves multi-process traffic with
no changes above this layer.

Design constraints inherited from the stack above:

* **Thread safety.** ``AsyncEngine.submit`` runs concurrently with
  ``step()`` (submit holds the server lock; the stepper thread does not),
  and the engine contract is that ``submit`` must be GIL-atomic. So
  :meth:`FleetSupervisor.submit` does NO socket I/O — it appends the
  mirror request to a local deque; the stepper thread dispatches it over
  the wire at the next :meth:`step`. Every socket lives on the stepper
  thread (plus the constructor and ``close()``, which run before/after
  the stepper exists).

* **Mirror requests.** The supervisor keeps a host-side mirror
  ``Request`` per in-flight client request; FT_STEP replies carry
  per-request token/logprob deltas which are appended to the mirrors, so
  ``AsyncEngine._drain_events`` (which walks ``slots`` + ``finished``)
  streams tokens unchanged. Failover resubmits and drain fallbacks are
  serialized FROM the mirror — it always holds everything streamed so
  far.

* **Self-healing = respawn.** Where ``ReplicatedEngine`` rebuilds a
  quarantined replica's engine in place, the fleet's unit of healing is
  the process: a faulted/SIGKILL'd worker is killed, its in-flight work
  failed over to survivors, and a replacement process spawned after an
  exponential backoff (the elastic launcher's heartbeat/respawn pattern).
  The replacement is canary-gated through the PR 15 lifecycle state
  machine before taking dispatch, exactly like an in-process reinstate.

Byte-identity with the single-process engine holds because every worker
builds identical weights from the shared spec (PRNGKey(0) preset init or
the same exported checkpoint), per-request sampling is batch-composition
independent, and cross-process migration ships the ``export_handoff``
snapshot as a verbatim binary envelope (``wire.pack_handoff``) — the
adopting process continues the rng stream byte-exactly.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import subprocess
import sys
import time
from collections import deque
from types import SimpleNamespace
from typing import Callable, List, Optional, Sequence, Set, Tuple

from dlti_tpu.config import FleetConfig, ReplicaLifecycleConfig
from dlti_tpu.serving import wire
from dlti_tpu.serving.engine import (
    EngineConfig, GenerationResult, Request, SamplingParams,
)
from dlti_tpu.serving.lifecycle import ReplicaLifecycle, canary_digest
from dlti_tpu.telemetry import RequestTelemetry
from dlti_tpu.telemetry.distributed_trace import TraceFederator, mint_trace_id
from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
FLEET_METRIC_NAMES = (
    "dlti_fleet_workers_alive",
    "dlti_fleet_respawns_total",
)
workers_alive_gauge = Gauge(
    FLEET_METRIC_NAMES[0],
    help="fleet worker processes currently live (taking dispatch)")
respawns_total = Counter(
    FLEET_METRIC_NAMES[1],
    help="worker processes respawned after a fault or kill")

# Per-worker federated series exposed through fleet_scalars() as
# dlti_fleet_w{idx}_{key}: the counter keys must sum across workers to
# the fleet-level dlti_{key} totals (loadgen asserts this), the gauge
# keys are point-in-time per-process state.
WORKER_COUNTER_KEYS = ("requests", "generated_tokens", "prefill_tokens",
                       "preemptions", "decode_steps")
WORKER_GAUGE_KEYS = ("up", "active", "waiting", "free_blocks")


class _WorkerHandle:
    """Supervisor-side bookkeeping for one worker process + connection.

    Doubles as the ``live_engines()`` element the gateway's headroom
    arithmetic reads (``cfg.max_seqs - num_active - len(waiting)``), so
    it exposes ``cfg`` / ``num_active`` / ``waiting`` with the last
    reported gauges.
    """

    def __init__(self, idx: int, cfg: EngineConfig, fleet_cfg: FleetConfig):
        self.idx = idx
        self.cfg = cfg
        self.generation = 0
        self.handle = None           # spawner handle (process)
        self.sock = None             # connected wire socket
        self.pid: Optional[int] = None
        self.owned: Set[str] = set()  # request ids dispatched to this worker
        # Last reported gauges (FT_STEP / FT_HEALTH replies).
        self.active = 0
        self.waiting_count = 0
        self.free_blocks = 0
        self.stats: dict = {}        # current process's engine counters
        self.stats_carry: dict = {}  # accumulated at death: keeps per-worker
        self.metrics: dict = {}      # totals monotonic across respawns
        self.last_health = 0.0
        # Respawn machinery (elastic-launcher pattern).
        self.restarts_left = fleet_cfg.restart_budget
        self.backoff = fleet_cfg.respawn_backoff_s
        self.pending_respawn = False  # waiting out the backoff
        self.starting = False         # spawned, awaiting port + handshake
        self.next_respawn_t = 0.0
        self.spawn_deadline = 0.0

    @property
    def num_active(self) -> int:
        return self.active

    @property
    def waiting(self) -> tuple:
        # len()-compatible stand-in for the engine's waiting deque.
        return tuple(range(self.waiting_count))

    def totals(self) -> dict:
        keys = set(self.stats_carry) | set(self.stats)
        return {k: self.stats_carry.get(k, 0) + self.stats.get(k, 0)
                for k in keys}


class _SubprocessHandle:
    """One spawned engine-worker process + its port file."""

    def __init__(self, proc: subprocess.Popen, port_file: str,
                 generation: int):
        self.proc = proc
        self.pid = proc.pid
        self._port_file = port_file
        self._generation = generation

    def port(self) -> Optional[int]:
        """The worker's published port once it is ready to serve (the
        port file is written atomically AFTER engine build + warmup, and
        carries the generation so a stale file from the previous
        incarnation is never trusted)."""
        try:
            with open(self._port_file, encoding="utf-8") as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        if info.get("generation") != self._generation:
            return None
        return int(info["port"])

    def poll(self):
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None):
        return self.proc.wait(timeout=timeout)

    def terminate(self) -> None:
        self.proc.terminate()

    def kill(self) -> None:
        self.proc.kill()


def make_subprocess_spawner(spec: dict, runtime_dir: str, *,
                            host: str = "127.0.0.1",
                            python: str = sys.executable,
                            extra_env: Optional[dict] = None,
                            ) -> Callable[[int, int], _SubprocessHandle]:
    """Build the default spawner: launches ``scripts/engine_worker.py``
    with the shared build ``spec`` (written once to ``runtime_dir``) and
    a per-(worker, generation) port file. Worker stdout/stderr go to
    per-incarnation log files in ``runtime_dir``. The spawner signature
    ``(idx, generation) -> handle`` is also the test seam — unit tests
    inject thread-based fakes instead of real processes."""
    os.makedirs(runtime_dir, exist_ok=True)
    spec_path = os.path.join(runtime_dir, "worker_spec.json")
    durable_io.write_json_atomic(spec_path, spec, path_class="fleet_runtime")
    script = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "..", "scripts", "engine_worker.py")
    script = os.path.abspath(script)

    def spawn(idx: int, generation: int) -> _SubprocessHandle:
        port_file = os.path.join(runtime_dir,
                                 f"worker{idx}.g{generation}.port")
        try:
            os.unlink(port_file)
        except OSError:
            pass
        env = dict(os.environ)
        env["DLTI_PROCESS_ID"] = str(idx)
        env["DLTI_GENERATION"] = str(generation)
        if extra_env:
            env.update(extra_env)
        log_path = os.path.join(runtime_dir, f"worker{idx}.g{generation}.log")
        log_f = open(log_path, "ab")  # noqa: SIM115 — outlives this scope
        proc = subprocess.Popen(
            [python, script, "--spec", spec_path, "--host", host,
             "--port-file", port_file, "--worker-id", str(idx),
             "--generation", str(generation)],
            stdout=log_f, stderr=subprocess.STDOUT, env=env)
        log_f.close()  # the child holds its own fd
        return _SubprocessHandle(proc, port_file, generation)

    return spawn


class FleetSupervisor:
    """N worker processes behind a ``ReplicatedEngine``-compatible facade.

    ``engine_cfg`` is the config every worker runs (used locally only for
    headroom arithmetic — the workers build their engines from the
    spawner's spec, which must agree). ``spawner(idx, generation)``
    launches one worker process; :func:`make_subprocess_spawner` is the
    real one, tests inject fakes.
    """

    # Class-level defaults so `__new__`-built test skeletons (which skip
    # __init__) still have the deploy-controller surface.
    shadow_tap = None
    last_reload_ok: Optional[bool] = None
    trace: Optional[TraceFederator] = None

    def __init__(
        self,
        engine_cfg: EngineConfig,
        *,
        workers: int = 2,
        spawner: Callable[[int, int], object],
        fleet_cfg: Optional[FleetConfig] = None,
        lifecycle_cfg: Optional[ReplicaLifecycleConfig] = None,
        max_retries: int = 2,
        affinity_spill_threshold: int = 4,
        telemetry: Optional[RequestTelemetry] = None,
        canary_vocab: int = 32000,
    ):
        if workers < 1:
            raise ValueError(f"workers ({workers}) must be >= 1")
        self._engine_cfg = engine_cfg
        self.fleet_cfg = fleet_cfg if fleet_cfg is not None else FleetConfig()
        self._spawner = spawner
        self.logger = get_logger()
        self.telemetry = telemetry if telemetry is not None \
            else RequestTelemetry()
        self.max_retries = max_retries
        self.affinity_spill_threshold = affinity_spill_threshold
        self.canary_vocab = canary_vocab
        # Same counter contracts as ReplicatedEngine (gateway metrics
        # read these names directly off the engine facade).
        self.failover = {"retries": 0, "replica_faults": 0,
                         "failover_errors": 0}
        self.affinity = {"sticky": 0, "spill": 0}
        self.failover_fallback = None
        self.lifecycle_cfg = lifecycle_cfg if lifecycle_cfg is not None \
            else ReplicaLifecycleConfig(enabled=True)
        self._heal = self.lifecycle_cfg.enabled
        self.lifecycle = ReplicaLifecycle(self.lifecycle_cfg, workers)
        self._req_counter = itertools.count()
        self._rr = 0
        self._dead: Set[int] = set()
        self._draining: Set[int] = set()
        # Client-facing mirrors: request_id -> mirror Request. Pending
        # submits wait here for the stepper thread to dispatch them
        # (submit() must not touch sockets — see module docstring).
        self._mirror: dict = {}
        self._pending_submits: deque = deque()  # (req, affinity_key)
        self._cancel_sent: Set[str] = set()
        self._finished: deque = deque(maxlen=256)
        self._reload: Optional[dict] = None
        self._reload_tree = None  # post-reload weights for respawned workers
        self._canary_digest: Optional[str] = None
        # Outcome of the most recent rolling reload (None until one ran);
        # polled by the deployment controller (serving.deploy).
        self.last_reload_ok: Optional[bool] = None
        # Shadow-traffic tap (serving.deploy): same contract as
        # ReplicatedEngine.shadow_tap — called (prompt, params, mirror
        # request) on every client submit, exception-isolated.
        self.shadow_tap = None
        self._respawns = 0
        self._closed = False
        # Distributed tracing (telemetry.distributed_trace): per-worker
        # clock-offset estimators fed from every RPC round trip, plus the
        # merged ring the workers' shipped span tails land in (rebased
        # onto this process's clock). /debug/trace reads it through the
        # facade; flight dumps persist the offsets for postmortem --all.
        self.trace = TraceFederator()
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None:
            rec.add_context_source(
                lambda: {"clock_offsets": self.trace.offsets()})

        self._workers = [_WorkerHandle(i, engine_cfg, self.fleet_cfg)
                         for i in range(workers)]
        # Boot: spawn everyone first (engine builds run concurrently in
        # the children), then handshake each in turn.
        for w in self._workers:
            w.handle = self._spawner(w.idx, w.generation)
            w.starting = True
            w.spawn_deadline = (time.monotonic()
                                + self.fleet_cfg.startup_timeout_s)
        try:
            for w in self._workers:
                self._await_ready(w)
        except Exception:
            self.close()
            raise
        if self._heal:
            toks = None
            try:
                toks = self._wire_canary(self._workers[0])
            except (wire.WireError, OSError) as e:
                self.logger.warning("fleet: boot canary rpc failed: %s", e)
            if toks is not None:
                self._canary_digest = canary_digest(toks)
            else:
                self.logger.warning(
                    "fleet: canary digest could not be pinned at "
                    "construction; probes will gate on generation "
                    "success only")
        self._update_alive_gauge()

    # -- wire plumbing (stepper thread only) ----------------------------
    def _rpc(self, w: _WorkerHandle, ftype: int, obj) -> dict:
        return wire.request_reply(w.sock, ftype, obj,
                                  max_frame_bytes=self.fleet_cfg
                                  .max_frame_bytes)

    def _clock_obj(self, w: _WorkerHandle) -> dict:
        """Downlink payload: this supervisor's current offset estimate
        for ``w``'s clock, which the worker notes into its flight-dump
        context (postmortem --all rebases per-worker dump span tails
        with exactly this value)."""
        est = self.trace.estimator(w.idx)
        if not est.samples:
            return {}
        return {"clock_offset": est.offset,
                "clock_uncertainty": est.uncertainty}

    def _rpc_timed(self, w: _WorkerHandle, ftype: int, obj) -> dict:
        """RPC + trace federation: the send/receive timestamps around the
        round trip feed the worker's NTP-style clock-offset estimator
        (the reply's "time" is the worker's monotonic clock mid-serve),
        and any shipped span tail is rebased and merged."""
        t0 = time.monotonic()
        reply = self._rpc(w, ftype, obj)
        t1 = time.monotonic()
        if isinstance(reply, dict) and "time" in reply:
            self.trace.source(w.idx, pid=w.pid,
                              label=f"worker{w.idx} gen{w.generation}")
            self.trace.observe_rpc(w.idx, t0, t1, reply.get("time"))
            if reply.get("spans") or reply.get("spans_dropped"):
                self.trace.ingest(
                    w.idx, reply.get("spans") or (),
                    remote_dropped=int(reply.get("spans_dropped") or 0))
        return reply

    def _connect(self, w: _WorkerHandle, port: int,
                 timeout_s: float) -> None:
        sock = wire.connect_with_retry(self.fleet_cfg.host, port,
                                       timeout_s=timeout_s)
        sock.settimeout(self.fleet_cfg.rpc_timeout_s)
        w.sock = sock

    def _await_ready(self, w: _WorkerHandle) -> None:
        """Block until ``w``'s process publishes its port and answers a
        health frame (boot path; respawns use the non-blocking
        :meth:`_respawn_tick` instead)."""
        deadline = w.spawn_deadline
        while True:
            if w.handle.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {w.idx} exited with code "
                    f"{w.handle.poll()} before serving")
            port = w.handle.port()
            if port is not None:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet worker {w.idx} did not publish a port within "
                    f"{self.fleet_cfg.startup_timeout_s}s")
            time.sleep(0.05)
        self._connect(w, port, max(1.0, deadline - time.monotonic()))
        reply = self._rpc_timed(w, wire.FT_HEALTH, {})
        self._apply_health(w, reply)
        w.starting = False
        self.logger.info("fleet worker %d (gen %d, pid %s) ready on port %d",
                         w.idx, w.generation, w.pid, port)

    def _close_sock(self, w: _WorkerHandle) -> None:
        if w.sock is not None:
            try:
                w.sock.close()
            except OSError:
                pass
            w.sock = None

    def _kill_proc(self, w: _WorkerHandle) -> None:
        if w.handle is None:
            return
        try:
            if w.handle.poll() is None:
                w.handle.kill()
                w.handle.wait(timeout=5.0)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def _carry_stats(self, w: _WorkerHandle) -> None:
        """Fold the dying process's counters into the carry so per-worker
        totals stay monotonic across respawns (federation depends on
        this: the sum over workers must equal what clients saw)."""
        for k, v in w.stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.stats_carry[k] = w.stats_carry.get(k, 0) + v
        w.stats = {}
        w.active = w.waiting_count = w.free_blocks = 0

    def _update_alive_gauge(self) -> None:
        workers_alive_gauge.set(self.num_live)

    # -- routing & submission -------------------------------------------
    def _load(self, w: _WorkerHandle) -> int:
        return len(w.owned)

    def _live_for_dispatch(self) -> List[_WorkerHandle]:
        return [w for w in self._workers
                if w.idx not in self._dead and w.idx not in self._draining
                and w.sock is not None]

    def live_engines(self) -> List[_WorkerHandle]:
        return self._live_for_dispatch()

    def live_workers(self) -> List[_WorkerHandle]:
        return self._live_for_dispatch()

    @property
    def num_live(self) -> int:
        return len(self._live_for_dispatch())

    def _reviving(self) -> bool:
        """True while some worker is between death and reinstatement —
        the window where new work should queue rather than hard-fail."""
        return any(w.pending_respawn or w.starting for w in self._workers)

    def _sticky_target(self, key: str,
                       live: List[_WorkerHandle]) -> _WorkerHandle:
        def score(w: _WorkerHandle) -> bytes:
            return hashlib.sha256(f"{key}:{w.idx}".encode()).digest()

        return max(live, key=score)

    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "", trace_id: str = "") -> Request:
        """Create the client-facing mirror request and queue it for the
        stepper thread to dispatch (no socket I/O here — this runs
        concurrently with step()).

        ``trace_id`` carries an upstream-minted trace context (the
        gateway's); "" mints one here, BEFORE the mirror is queued — the
        stepper may serialize the FT_SUBMIT descriptor at any moment
        after the append, so the id must already be final."""
        if not self._live_for_dispatch() and not self._reviving():
            raise RuntimeError("all fleet workers dead; "
                               "engine cannot accept requests")
        if params is None:
            params = SamplingParams()
        if request_id is None:
            request_id = f"fleet-req-{next(self._req_counter)}"
        req = Request(request_id=request_id,
                      prompt_token_ids=list(prompt_token_ids),
                      params=params, arrival_time=time.monotonic(),
                      trace_id=trace_id or mint_trace_id())
        req.adapter = adapter
        self.telemetry.on_submitted(req)
        self._mirror[request_id] = req
        self._pending_submits.append((req, affinity_key))
        tap = self.shadow_tap
        if tap is not None:
            try:
                tap(list(prompt_token_ids), params, req)
            except Exception:  # noqa: BLE001 — shadow never hurts clients
                self.logger.debug("shadow tap raised", exc_info=True)
        return req

    def _route(self, affinity_key: Optional[str],
               live: List[_WorkerHandle]) -> _WorkerHandle:
        if affinity_key:
            sticky = self._sticky_target(affinity_key, live)
            backlog = self._load(sticky) - sticky.cfg.max_seqs
            if backlog <= self.affinity_spill_threshold:
                self.affinity["sticky"] += 1
                return sticky
            self.affinity["spill"] += 1
        order = live[self._rr % len(live):] + live[:self._rr % len(live)]
        self._rr = (self._rr + 1) % len(live)
        return min(order, key=self._load)

    def _finish_error(self, req: Request) -> Request:
        req.finish_reason = "error"
        req.finish_time = time.monotonic()
        self.failover["failover_errors"] += 1
        self.telemetry.on_finished(req)
        self._mirror.pop(req.request_id, None)
        self._finished.append(req)
        return req

    def _dispatch_pending(self) -> List[Request]:
        errored: List[Request] = []
        while self._pending_submits:
            live = self._live_for_dispatch()
            if not live:
                if self._reviving():
                    return errored  # hold the queue for the respawn
                req, _ = self._pending_submits.popleft()
                errored.append(self._finish_error(req))
                continue
            req, affinity_key = self._pending_submits.popleft()
            target = self._route(affinity_key, live)
            desc = wire.request_to_wire(req)
            dispatched = False
            while not dispatched:
                try:
                    self._rpc(target, wire.FT_SUBMIT,
                              {"request": desc, "resubmit": False})
                except (wire.WireError, OSError) as e:
                    errored.extend(self._fail_worker(target, e))
                    live = self._live_for_dispatch()
                    if not live:
                        if self._reviving():
                            self._pending_submits.appendleft(
                                (req, affinity_key))
                        else:
                            errored.append(self._finish_error(req))
                        return errored
                    target = min(live, key=self._load)
                    continue
                dispatched = True
            target.owned.add(req.request_id)
            target.waiting_count += 1
            req.replica = target.idx
        return errored

    # -- stepping --------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return bool(self._mirror) or bool(self._pending_submits)

    def step(self) -> List[Request]:
        """One supervision round: dispatch queued submits, step every
        worker holding work (piggybacking cancels and collecting token
        deltas), heartbeat idle workers, then run one lifecycle action
        (reload roll / respawn / probe). Worker faults never escape —
        they fail over exactly like a replica fault."""
        finished: List[Request] = []
        finished.extend(self._dispatch_pending())
        now = time.monotonic()
        for w in self._workers:
            if w.sock is None or w.idx in self._dead:
                continue
            try:
                if w.owned:
                    cancels = [rid for rid in w.owned
                               if rid in self._mirror
                               and self._mirror[rid].cancel_requested
                               and rid not in self._cancel_sent]
                    reply = self._rpc_timed(
                        w, wire.FT_STEP,
                        {"cancels": cancels, **self._clock_obj(w)})
                    self._cancel_sent.update(cancels)
                    w.last_health = now
                    finished.extend(self._apply_step_reply(w, reply))
                elif now - w.last_health >= self.fleet_cfg.health_interval_s:
                    self._apply_health(w, self._rpc_timed(
                        w, wire.FT_HEALTH, self._clock_obj(w)))
            except (wire.WireError, OSError) as e:
                finished.extend(self._fail_worker(w, e))
        self._lifecycle_tick()
        return finished

    def _apply_gauges(self, w: _WorkerHandle, reply: dict) -> None:
        w.active = int(reply.get("active", w.active))
        w.waiting_count = int(reply.get("waiting", w.waiting_count))
        w.free_blocks = int(reply.get("free_blocks", w.free_blocks))
        if isinstance(reply.get("stats"), dict):
            w.stats = reply["stats"]

    def _apply_health(self, w: _WorkerHandle, reply: dict) -> None:
        self._apply_gauges(w, reply)
        w.pid = reply.get("pid", w.pid)
        if isinstance(reply.get("metrics"), dict):
            w.metrics = reply["metrics"]
        w.last_health = time.monotonic()

    def _apply_step_reply(self, w: _WorkerHandle,
                          reply: dict) -> List[Request]:
        self._apply_gauges(w, reply)
        finished: List[Request] = []
        now = time.monotonic()
        for ev in reply.get("events") or ():
            rid = ev["id"]
            req = self._mirror.get(rid)
            if req is None:
                # Canary traffic, or a request already errored out by a
                # racing failover — nothing to mirror.
                w.owned.discard(rid)
                continue
            if ev["tokens"]:
                if req.first_token_time is None:
                    req.first_token_time = now
                    self.telemetry.on_first_token(req)
                req.output_token_ids.extend(ev["tokens"])
                req.output_logprobs.extend(ev["logprobs"])
            req.num_preemptions = ev.get("preemptions",
                                         req.num_preemptions)
            if "finish_reason" in ev:
                req.finish_reason = ev["finish_reason"]
                req.finish_time = now
                self.telemetry.on_finished(req)
                self._mirror.pop(rid, None)
                self._cancel_sent.discard(rid)
                w.owned.discard(rid)
                self._finished.append(req)
                finished.append(req)
        return finished

    # -- failure handling -------------------------------------------------
    def _fail_worker(self, w: _WorkerHandle, exc: Exception) -> List[Request]:
        """A worker's process or connection died (or spoke garbage): mark
        it dead, fail its in-flight work over to survivors, and — with
        healing on and restart budget left — schedule a respawn."""
        if w.idx in self._dead and w.sock is None:
            return []  # already torn down (re-entry via nested failover)
        self._dead.add(w.idx)
        self._draining.discard(w.idx)
        self.failover["replica_faults"] += 1
        if self._heal:
            self.lifecycle.on_fault(w.idx)
        else:
            self.lifecycle.mark_dead(w.idx)
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None:
            rec.dump(reason="worker_fault", exc=exc, force=True,
                     extra={"worker": w.idx, "generation": w.generation,
                            "pid": w.pid, "in_flight": len(w.owned),
                            "survivors": self.num_live,
                            "clock_offsets": self.trace.offsets()})
        self.logger.error(
            "fleet worker %d (gen %d, pid %s) failed (%s: %s); failing "
            "over %d request(s) to %d survivor(s)", w.idx, w.generation,
            w.pid, type(exc).__name__, exc, len(w.owned), self.num_live)
        self._carry_stats(w)
        self._close_sock(w)
        self._kill_proc(w)
        stranded = [self._mirror[rid] for rid in sorted(w.owned)
                    if rid in self._mirror]
        w.owned.clear()
        errored: List[Request] = []
        for req in stranded:
            errored.extend(self._rehome(req, kind="failover"))
        if (self._heal and self.lifecycle.state(w.idx) != "evicted"
                and w.restarts_left > 0):
            w.restarts_left -= 1
            w.pending_respawn = True
            w.next_respawn_t = time.monotonic() + w.backoff
            self.logger.warning(
                "fleet worker %d respawn scheduled in %.1fs "
                "(%d restart(s) left)", w.idx, w.backoff, w.restarts_left)
            w.backoff = min(w.backoff * 2,
                            self.fleet_cfg.respawn_backoff_max_s)
        else:
            self.lifecycle.mark_dead(w.idx)
        self._update_alive_gauge()
        return errored

    def _rehome(self, req: Request, *, kind: str) -> List[Request]:
        """Failover-style resubmit of one mirror request onto a survivor
        (recompute-on-readmit from the mirror's tokens); errors it out
        past the retry cap or with no survivors. Returns the request iff
        it errored."""
        from dlti_tpu.telemetry.ledger import note_requeue

        while True:
            live = self._live_for_dispatch()
            if not live and self._reviving() \
                    and req.num_retries < self.max_retries:
                # Total-outage window with a respawn pending: requeue as
                # a pending submit rather than erroring the request.
                req.num_retries += 1
                self.failover["retries"] += 1
                note_requeue(req, kind)
                self._pending_submits.append((req, None))
                return []
            if not live or req.num_retries >= self.max_retries:
                if (not live and req.num_retries < self.max_retries
                        and self.failover_fallback is not None):
                    note_requeue(req, kind)
                    if self.failover_fallback(req):
                        req.num_retries += 1
                        self.failover["retries"] += 1
                        return []
                return [self._finish_error(req)]
            req.num_retries += 1
            self.failover["retries"] += 1
            note_requeue(req, kind)
            target = min(live, key=self._load)
            try:
                self._rpc(target, wire.FT_SUBMIT,
                          {"request": wire.request_to_wire(req),
                           "resubmit": True})
            except (wire.WireError, OSError) as e:
                self._fail_worker(target, e)
                continue
            target.owned.add(req.request_id)
            target.waiting_count += 1
            req.replica = target.idx
            return []

    # -- drain / migration ------------------------------------------------
    def drain_replica(self, idx: int, *, kind: str = "preempt",
                      quarantine: bool = True) -> List[Request]:
        """Planned drain of one worker: its in-flight decodes migrate to
        survivors as verbatim handoff envelopes (FT_DRAIN exports them,
        FT_ADOPT hands the SAME bytes to the adopter — byte-exact
        continuation), with failover-resubmit fallback; queued and
        mid-prefill work resubmits from the mirror. With ``quarantine``
        the worker then enters the lifecycle (its process stays up; a
        canary probe over the live connection reinstates it)."""
        w = self._workers[idx]
        if w.sock is None:
            return []
        self.lifecycle.begin_drain(idx)
        self._dead.add(idx)
        self._draining.discard(idx)
        drain_t0 = time.monotonic()
        try:
            reply = self._rpc(w, wire.FT_DRAIN, {})
        except (wire.WireError, OSError) as e:
            self._dead.discard(idx)  # let _fail_worker do full accounting
            return self._fail_worker(w, e)
        from dlti_tpu.telemetry.ledger import note_requeue

        migrated = fallbacks = 0
        errored: List[Request] = []
        for env in reply.get("handoffs") or ():
            try:
                rid = wire.unpack_handoff(env)["request"].request_id
            except wire.WireError:
                continue  # worker-side bug; nothing safe to do with it
            req = self._mirror.get(rid)
            w.owned.discard(rid)
            if req is not None:
                note_requeue(req, kind)
            adopted = False
            for target in sorted(self._live_for_dispatch(), key=self._load):
                try:
                    r = self._rpc(target, wire.FT_ADOPT, {"envelope": env})
                except (wire.WireError, OSError) as e:
                    self._fail_worker(target, e)
                    continue
                if r.get("adopted"):
                    adopted = True
                    migrated += 1
                    target.owned.add(rid)
                    if req is not None:
                        req.num_migrations += 1
                        req.replica = target.idx
                        # Same span name the disagg controller emits for
                        # its staging window: export → cross-process
                        # adopt, on the supervisor clock (exact — both
                        # endpoints are local RPC returns).
                        self.telemetry.tracer.complete(
                            "engine/kv_handoff", drain_t0, time.monotonic(),
                            cat="engine", id=rid, trace=req.trace_id,
                            src=idx, dst=target.idx, kind=kind)
                    break
            if not adopted:
                fallbacks += 1
                if req is not None:
                    errored.extend(self._rehome(req, kind=kind))
        for desc in reply.get("resubmits") or ():
            rid = desc.get("request_id")
            req = self._mirror.get(rid)
            w.owned.discard(rid)
            if req is not None:
                errored.extend(self._rehome(req, kind=kind))
        w.owned.clear()
        w.active = w.waiting_count = 0
        if migrated:
            self.lifecycle.note_migration(migrated)
        if fallbacks:
            self.lifecycle.note_migration_fallback(fallbacks)
        self.logger.warning(
            "fleet worker %d drained (%s): %d decode(s) migrated via KV "
            "handoff envelope, %d fallback(s), %d errored", idx, kind,
            migrated, fallbacks, len(errored))
        if quarantine:
            if self._heal:
                self.lifecycle.on_fault(idx)
            else:
                self.lifecycle.mark_dead(idx)
        self._update_alive_gauge()
        return errored

    # -- canary / probe / respawn ----------------------------------------
    def _wire_canary(self, w: _WorkerHandle) -> Optional[List[int]]:
        """Short greedy canary generation driven over the wire (only on a
        worker carrying no client traffic). Returns token ids, or None
        when generation itself fails; wire errors propagate — the caller
        decides between reschedule and failover."""
        cfg = self.lifecycle_cfg
        vocab = max(2, self.canary_vocab)
        prompt = [(i % min(97, vocab - 1)) + 1
                  for i in range(max(1, cfg.canary_prompt_tokens))]
        sp = SamplingParams(temperature=0.0,
                            max_tokens=max(1, cfg.canary_max_tokens))
        rid = f"fleet-canary-{next(self._req_counter)}"
        req = Request(request_id=rid, prompt_token_ids=prompt, params=sp,
                      arrival_time=time.monotonic())
        self._rpc(w, wire.FT_SUBMIT,
                  {"request": wire.request_to_wire(req), "resubmit": False})
        toks: List[int] = []
        for _ in range(1000):
            reply = self._rpc(w, wire.FT_STEP, {"cancels": []})
            for ev in reply.get("events") or ():
                if ev["id"] != rid:
                    continue
                toks.extend(ev["tokens"])
                if "finish_reason" in ev:
                    if ev["finish_reason"] == "error":
                        return None
                    return toks
            if not reply.get("has_work"):
                # Engine went idle without finishing the canary: verdict.
                return None
        return None

    def _canary_ok(self, w: _WorkerHandle,
                   digest: Optional[str]) -> bool:
        toks = self._wire_canary(w)
        return toks is not None and (digest is None
                                     or canary_digest(toks) == digest)

    def _probe_worker(self, w: _WorkerHandle) -> None:
        """Probation elapsed for a drained-but-alive worker: canary over
        the existing connection gates reinstatement."""
        self.lifecycle.begin_probe(w.idx)
        try:
            ok = self._canary_ok(w, self._canary_digest)
        except (wire.WireError, OSError) as e:
            # The idle process died under quarantine — full failover
            # accounting (it owns nothing, so this just schedules the
            # respawn).
            self._fail_worker(w, e)
            return
        if self.lifecycle.on_probe_result(w.idx, ok) == "live":
            self._dead.discard(w.idx)
            self._update_alive_gauge()

    def _respawn_tick(self, now: float) -> None:
        for w in self._workers:
            if w.starting:
                self._poll_starting(w, now)
            elif w.pending_respawn and now >= w.next_respawn_t:
                self._launch_respawn(w, now)

    def _launch_respawn(self, w: _WorkerHandle, now: float) -> None:
        w.pending_respawn = False
        w.generation += 1
        try:
            w.handle = self._spawner(w.idx, w.generation)
        except Exception as e:  # noqa: BLE001 — spawner failure reschedules
            self.logger.error("fleet worker %d respawn spawn failed: %s",
                              w.idx, e)
            self._reschedule_or_evict(w, now)
            return
        w.starting = True
        w.spawn_deadline = now + self.fleet_cfg.startup_timeout_s
        self.logger.info("fleet worker %d respawning (gen %d, pid %s)",
                         w.idx, w.generation, w.handle.pid)

    def _reschedule_or_evict(self, w: _WorkerHandle, now: float) -> None:
        w.starting = False
        self._close_sock(w)
        self._kill_proc(w)
        if w.restarts_left > 0 and self.lifecycle.state(w.idx) != "evicted":
            w.restarts_left -= 1
            w.pending_respawn = True
            w.next_respawn_t = now + w.backoff
            w.backoff = min(w.backoff * 2,
                            self.fleet_cfg.respawn_backoff_max_s)
            return
        self.lifecycle.evict(w.idx)
        self.logger.error("fleet worker %d evicted: restart budget "
                          "exhausted", w.idx)

    def _poll_starting(self, w: _WorkerHandle, now: float) -> None:
        """Non-blocking respawn progression: exit/timeout reschedules;
        a published port leads to connect → (optional reload) → canary →
        reinstate through the lifecycle machine."""
        if w.handle.poll() is not None:
            self.logger.error(
                "fleet worker %d (gen %d) exited with code %s during "
                "startup", w.idx, w.generation, w.handle.poll())
            self._reschedule_or_evict(w, now)
            return
        if now > w.spawn_deadline:
            self.logger.error("fleet worker %d (gen %d) startup timed out",
                              w.idx, w.generation)
            self._reschedule_or_evict(w, now)
            return
        port = w.handle.port()
        if port is None:
            return  # still building its engine
        try:
            self._connect(w, port, timeout_s=5.0)
            self._apply_health(w, self._rpc(w, wire.FT_HEALTH, {}))
            if self._reload_tree is not None:
                # The fleet completed a rolling reload after this spec
                # was written: bring the replacement onto the current
                # weights before the canary judges it.
                self._rpc(w, wire.FT_RELOAD, {"params": self._reload_tree})
            if self._heal:
                self.lifecycle.begin_probe(w.idx)
                ok = self._canary_ok(w, self._canary_digest)
                if self.lifecycle.on_probe_result(w.idx, ok) != "live":
                    self.logger.error(
                        "fleet worker %d (gen %d) respawn canary failed",
                        w.idx, w.generation)
                    self._reschedule_or_evict(w, now)
                    return
        except (wire.WireError, OSError) as e:
            self.logger.error(
                "fleet worker %d (gen %d) respawn handshake failed: %s",
                w.idx, w.generation, e)
            self._reschedule_or_evict(w, now)
            return
        w.starting = False
        w.backoff = self.fleet_cfg.respawn_backoff_s
        w.pid = w.handle.pid
        self._dead.discard(w.idx)
        self._respawns += 1
        respawns_total.inc()
        self._update_alive_gauge()
        self.logger.warning(
            "fleet worker %d respawned (gen %d, pid %s) and reinstated",
            w.idx, w.generation, w.pid)

    # -- rolling reload ----------------------------------------------------
    def request_reload(self, weights_provider, *, verify=None) -> bool:
        """Enqueue a rolling weight reload (thread-safe: one GIL-atomic
        attribute write; the roll runs on the stepper thread). The
        provider must return a host param tree; it is converted to plain
        numpy dicts and shipped to each worker over FT_RELOAD after a
        drain-via-migration. ``verify()``, when given, re-runs before
        every per-worker swap (the mid-roll corruption abort —
        see :meth:`ReplicatedEngine.request_reload`). Returns False if a
        roll is in progress."""
        if self._reload is not None:
            return False
        self._reload = {"provider": weights_provider, "tree": None,
                        "queue": None, "digest": None, "verify": verify}
        return True

    @staticmethod
    def _tree_to_wire(tree):
        """Host param tree -> nested plain dicts of numpy arrays (the
        only tree shape the wire serializer carries)."""
        import numpy as np

        if hasattr(tree, "items"):
            return {str(k): FleetSupervisor._tree_to_wire(v)
                    for k, v in tree.items()}
        import jax

        return np.asarray(jax.device_get(tree))

    def _reload_tick(self) -> None:
        """One rolling-reload action per step: drain one worker via KV
        migration, swap its weights over the wire, canary, reinstate.
        The first upgraded worker pins the new digest with a determinism
        double-run; a canary failure aborts the roll (that worker is
        killed and respawns onto the OLD weights — the fleet stays
        consistent)."""
        st = self._reload
        if st["tree"] is None:
            try:
                st["tree"] = self._tree_to_wire(st["provider"]())
            except Exception as e:  # noqa: BLE001 — bad checkpoint aborts
                self.logger.error(
                    "fleet rolling reload aborted: weights provider "
                    "failed: %s", e)
                self.last_reload_ok = False
                self._reload = None
                return
            st["queue"] = [w.idx for w in self._workers
                           if self.lifecycle.state(w.idx) != "evicted"
                           and not w.pending_respawn and not w.starting
                           and w.sock is not None]
            self.logger.info("fleet rolling reload: %d worker(s) queued",
                             len(st["queue"]))
        if not st["queue"]:
            if st["digest"] is not None:
                self._canary_digest = st["digest"]
            self._reload_tree = st["tree"]
            self.last_reload_ok = True
            self._reload = None
            self.logger.info("fleet rolling reload complete")
            return
        idx = st["queue"][0]
        if st.get("verify") is not None:
            # Mid-roll re-verification (same contract as ReplicatedEngine:
            # the export's bytes must still verify before EVERY swap).
            ok_verify = False
            try:
                ok_verify = bool(st["verify"]())
            except Exception as e:  # noqa: BLE001 — verify fault = fail
                self.logger.error("fleet reload re-verify raised: %s", e)
            if not ok_verify:
                self.logger.error(
                    "fleet rolling reload aborted: export failed "
                    "re-verification before worker %d swap", idx)
                self.last_reload_ok = False
                self._reload = None
                return
        w = self._workers[idx]
        others = [v for v in self._live_for_dispatch() if v.idx != idx]
        if others:
            self.drain_replica(idx, kind="reload", quarantine=False)
        else:
            # Sole live worker: lame-duck it (stop dispatch, keep
            # stepping) until its in-flight work finishes; the gateway
            # queues/sheds meanwhile.
            if idx not in self._draining and idx not in self._dead:
                self.lifecycle.begin_drain(idx)
                self._draining.add(idx)
            if w.owned:
                return
            self._draining.discard(idx)
            self._dead.add(idx)
        ok = False
        try:
            self._rpc(w, wire.FT_RELOAD, {"params": st["tree"]})
            toks = self._wire_canary(w)
            ok = toks is not None
            if ok and st["digest"] is None:
                # First worker on the new weights: gate on determinism
                # (two identical greedy runs) and pin the roll's digest.
                ok = self._wire_canary(w) == toks
                if ok:
                    st["digest"] = canary_digest(toks)
            elif ok:
                ok = canary_digest(toks) == st["digest"]
        except (wire.WireError, OSError) as e:
            self.logger.error("fleet worker %d reload rpc failed: %s",
                              idx, e)
            st["queue"].pop(0)
            self.last_reload_ok = False
            self._reload = None
            self._dead.discard(idx)
            self._fail_worker(w, e)
            return
        st["queue"].pop(0)
        if self.lifecycle.on_probe_result(idx, ok) == "live":
            self._dead.discard(idx)
            self._update_alive_gauge()
        if not ok:
            self.logger.error(
                "fleet rolling reload aborted: worker %d failed canary on "
                "new weights; fleet stays on previous weights", idx)
            self.last_reload_ok = False
            self._reload = None
            # The inconsistent worker is torn down; it respawns onto the
            # boot/previous weights and canaries back in.
            self._dead.discard(idx)
            self._fail_worker(w, RuntimeError("reload canary failed"))

    # -- lifecycle tick ----------------------------------------------------
    def _lifecycle_tick(self) -> None:
        if self._reload is not None:
            self._reload_tick()
            return
        if not self._heal:
            return
        now = time.monotonic()
        self._respawn_tick(now)
        for idx in self.lifecycle.due_probes():
            w = self._workers[idx]
            if (w.sock is None or w.pending_respawn or w.starting):
                continue  # the respawn path owns this worker
            self._probe_worker(w)
            break  # at most one heavy action per tick

    @property
    def lifecycle_pending(self) -> bool:
        """True when the stepper must keep ticking without client work:
        queued submits, a rolling reload, a pending/in-flight respawn, or
        a quarantined worker awaiting its probe."""
        if self._pending_submits or self._reload is not None:
            return True
        if any(w.pending_respawn or w.starting for w in self._workers):
            return True
        if not self._heal:
            return False
        return any(s in ("quarantined", "probing")
                   for s in self.lifecycle.states().values())

    def lifecycle_counts(self) -> dict:
        c = self.lifecycle.counts()
        return {"live": c["live"],
                "quarantined": c["quarantined"] + c["probing"],
                "draining": c["draining"],
                "dead": c["evicted"]}

    def worker_states(self) -> dict:
        """Per-worker liveness for /health: the lifecycle state with the
        respawn machinery overlaid (``respawning`` = a replacement
        process is scheduled or starting; ``dead`` = evicted/budget
        exhausted)."""
        out = {}
        for w in self._workers:
            s = self.lifecycle.state(w.idx)
            if s == "evicted":
                label = "dead"
            elif w.pending_respawn or w.starting:
                label = "respawning"
            elif s in ("quarantined", "probing"):
                label = "quarantined"
            elif s == "draining" or w.idx in self._draining:
                label = "draining"
            else:
                label = "live"
            out[str(w.idx)] = label
        return out

    @property
    def respawn_retry_after_s(self) -> float:
        """Backoff-derived Retry-After hint: how long until the next
        scheduled respawn attempt (0 when none is pending — a starting
        worker is imminent, so advertise a short wait)."""
        now = time.monotonic()
        pending = [w.next_respawn_t - now for w in self._workers
                   if w.pending_respawn]
        if pending:
            return max(0.0, min(pending))
        if any(w.starting for w in self._workers):
            return 1.0
        return 0.0

    # -- metrics federation ------------------------------------------------
    def fleet_scalars(self) -> dict:
        """Flat snapshot for the server registry (the ``pool_scalars``
        pattern): fleet-level gauges plus per-worker federated series
        (``fleet_w{i}_{key}``) whose counter keys sum to the fleet
        totals — the equality loadgen's federation check asserts."""
        out = {"fleet_workers": float(len(self._workers)),
               "fleet_workers_live": float(self.num_live),
               "fleet_respawns": float(self._respawns)}
        for w in self._workers:
            totals = w.totals()
            for k in WORKER_COUNTER_KEYS:
                out[f"fleet_w{w.idx}_{k}"] = totals.get(k, 0)
            out[f"fleet_w{w.idx}_up"] = float(
                w.idx not in self._dead and w.sock is not None)
            out[f"fleet_w{w.idx}_active"] = float(w.active)
            out[f"fleet_w{w.idx}_waiting"] = float(w.waiting_count)
            out[f"fleet_w{w.idx}_free_blocks"] = float(w.free_blocks)
        return out

    @property
    def fleet_gauge_keys(self) -> tuple:
        keys = ["fleet_workers", "fleet_workers_live"]
        for w in self._workers:
            keys.extend(f"fleet_w{w.idx}_{k}" for k in WORKER_GAUGE_KEYS)
        return tuple(keys)

    # -- InferenceEngine-compat surface ------------------------------------
    @property
    def cfg(self) -> EngineConfig:
        return self._engine_cfg

    @property
    def slots(self) -> list:
        # Mirror requests presented slot-shaped for AsyncEngine's event
        # drain (it only reads slot.request).
        return [SimpleNamespace(request=r) for r in self._mirror.values()]

    @property
    def finished(self) -> List[Request]:
        return list(self._finished)

    @property
    def waiting(self) -> List[Request]:
        return [req for req, _ in self._pending_submits]

    @property
    def num_active(self) -> int:
        return sum(w.active for w in self._live_for_dispatch())

    @property
    def num_free_blocks(self) -> int:
        return sum(w.free_blocks for w in self._live_for_dispatch())

    def abort_all(self, reason: str = "abort") -> List[Request]:
        for w in self._workers:
            if w.sock is None:
                continue
            try:
                reply = self._rpc(w, wire.FT_ABORT, {"reason": reason})
                self._apply_gauges(w, reply)
            except (wire.WireError, OSError) as e:
                self._fail_worker(w, e)
            w.owned.clear()
        aborted: List[Request] = []
        self._pending_submits.clear()  # pending reqs are mirrored too
        now = time.monotonic()
        for req in list(self._mirror.values()):
            if req.done:
                continue
            req.finish_reason = reason
            req.finish_time = now
            self.telemetry.on_finished(req)
            self._finished.append(req)
            aborted.append(req)
        self._mirror.clear()
        self._cancel_sent.clear()
        return aborted

    @property
    def stats(self) -> dict:
        """Aggregated counters across workers, carry-corrected so totals
        stay monotonic across respawns (per-worker under 'replicas')."""
        per_worker = [w.totals() for w in self._workers]
        keys: Set[str] = set()
        for t in per_worker:
            keys.update(t)
        agg = {k: sum(t.get(k, 0) for t in per_worker) for k in keys}
        agg["replicas"] = per_worker
        return agg

    def warmup_decode_ladder(self) -> None:
        # Workers warm their own decode ladders at startup (spec
        # "warmup"); by construction time they already answered health.
        return None

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        out = []
        for r in reqs:
            out.append(GenerationResult(
                request_id=r.request_id,
                prompt_token_ids=r.prompt_token_ids,
                output_token_ids=r.output_token_ids,
                output_logprobs=r.output_logprobs,
                finish_reason=r.finish_reason or "abort",
                ttft_s=((r.first_token_time or r.arrival_time)
                        - r.arrival_time),
                latency_s=((r.finish_time or time.monotonic())
                           - r.arrival_time),
            ))
        return out

    # -- teardown ----------------------------------------------------------
    def close(self) -> None:
        """Shut every worker down (clean FT_SHUTDOWN, then the
        terminate/kill ladder). Safe to call twice; runs on whatever
        thread owns the supervisor after the stepper stopped."""
        if self._closed:
            return
        self._closed = True
        for w in self._workers:
            if w.sock is not None:
                try:
                    w.sock.settimeout(2.0)
                    self._rpc(w, wire.FT_SHUTDOWN, {})
                except (wire.WireError, OSError):
                    pass
                self._close_sock(w)
            if w.handle is None:
                continue
            try:
                if w.handle.poll() is None:
                    w.handle.terminate()
                    try:
                        w.handle.wait(timeout=self.fleet_cfg.term_grace_s)
                    except Exception:  # noqa: BLE001 — escalate to kill
                        w.handle.kill()
                        w.handle.wait(timeout=5.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._update_alive_gauge()

    shutdown = close
