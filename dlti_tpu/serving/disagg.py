"""Prefill/decode disaggregation: split engine pools with paged-KV handoff.

Chunked prefill (``EngineConfig.max_prefill_tokens_per_step``) bounds how
long one prompt can stall the step loop, but every prefill chunk still
steals a decode step from all co-resident slots — a long-document request
landing on a chat replica inflates every neighbour's TPOT p99. The
disaggregation literature (DistServe, Splitwise) removes the interference
structurally: prefill and decode run in *separate pools*, each batching
for its own regime, and a finished prefill's KV state migrates to a
decode replica.

:class:`DisaggController` is that split, built on the proven pieces:

* **Pools** are two :class:`~dlti_tpu.serving.replicas.ReplicatedEngine`
  fleets sharing one :class:`~dlti_tpu.telemetry.RequestTelemetry`.
  Prefill engines run with ``prefill_only=True`` (admission + chunked
  prefill, never a decode dispatch — and never a decode-ladder warmup);
  decode engines are full engines, so they can re-prefill on failover.
* **Handoff** rides the prefix-tier transport: the origin engine's
  ``export_handoff`` fetches each written block device→host
  (``EngineExecutor.fetch_block_kv``, staged through ``pinned_host``
  where the backend has it), and the target's ``adopt_handoff`` scatters
  the payloads back with the jitted ``.at[block].set`` restore. The
  snapshot carries the sampled first token plus the origin slot's actual
  rng key bytes, so the decode replica's ``fold_in(key, gen_count)``
  stream continues exactly where prefill sampling left it — outputs are
  byte-identical with disaggregation on or off.
* **Phase accounting**: the staged wait opens a ``kv_handoff`` stall mark
  (``telemetry.ledger.note_requeue``) closed by the decode-side
  admission, so ``request_breakdown()`` books the migration as its own
  phase and ``/debug/slow`` timelines show the handoff leg.
* **Failover**: each pool keeps ReplicatedEngine's retry-capped
  failover. A dead prefill replica's requests re-prefill on surviving
  prefill replicas (or, pool extinct, colocate onto decode replicas via
  the ``failover_fallback`` hook); a dead decode replica's requests
  re-admit from their staged handoff snapshot when one exists, else
  re-prefill on a surviving decode replica.
* **Backpressure**: staged snapshots per decode replica are bounded
  (``handoff_queue_depth``); a full pool leaves finished prefills in
  their slots, which shrinks the gateway's dispatch room — load sheds at
  admission, host memory stays bounded. Staged payload bytes register
  with each decode engine's memory ledger under ``kv_handoff_staging``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence

from dlti_tpu.serving.engine import (
    EngineConfig, GenerationResult, InferenceEngine, Request, SamplingParams,
)
from dlti_tpu.serving.replicas import (
    FAULT_INJECT_ENV, ReplicatedEngine, _parse_fault_inject,
)
from dlti_tpu.telemetry import RequestTelemetry
from dlti_tpu.telemetry.ledger import note_requeue
from dlti_tpu.telemetry.registry import Histogram
from dlti_tpu.utils.logging import get_logger

# Name-stability contracts for the /metrics exposition (pinned in
# tests/test_bench_contract.py, walked by tests/test_metric_naming.py).
POOL_METRIC_NAMES = (
    "dlti_pool_prefill_replicas_alive",
    "dlti_pool_decode_replicas_alive",
    "dlti_pool_prefill_waiting",
    "dlti_pool_decode_waiting",
    "dlti_pool_prefill_active",
    "dlti_pool_decode_active",
)
KV_HANDOFF_METRIC_NAMES = (
    "dlti_kv_handoff_total",
    "dlti_kv_handoff_bytes_total",
    "dlti_kv_handoff_staged",
    "dlti_kv_handoff_fallbacks_total",
    "dlti_kv_handoff_sheds_total",
    "dlti_kv_handoff_seconds",
)

# Module-level histogram (the watchdog/flight-counter pattern: the server
# registry registers it for /metrics): prefill-finish → decode-adoption
# latency per migrated request.
handoff_seconds = Histogram(
    "dlti_kv_handoff_seconds",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
    help="prefill→decode KV handoff latency (harvest to adoption)")

_POOLS = ("prefill", "decode")


def _parse_pool_fault(spec: str) -> Dict[str, str]:
    """"POOL:REPLICA:STEP[:MODE]" -> {pool: "REPLICA:STEP[:MODE]"}; empty
    dict when unset. Validates eagerly (construction time beats step
    time for a config typo)."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    pool, _, rest = spec.partition(":")
    if pool not in _POOLS:
        raise ValueError(
            f"disagg fault_inject_step must be 'POOL:REPLICA:STEP[:MODE]' "
            f"with POOL in {_POOLS}, got {spec!r}")
    _parse_fault_inject(rest)  # raises on a malformed remainder
    return {pool: rest}


def _payload_nbytes(payloads: List[dict]) -> int:
    return sum(int(arr.nbytes) for blk in payloads
               for layer in blk.values() for arr in layer.values())


class _Staged:
    """One harvested prefill waiting for a decode slot."""

    __slots__ = ("snap", "t0", "holder")

    def __init__(self, snap: dict, t0: float):
        self.snap = snap
        self.t0 = t0
        # Fake slot so AsyncEngine._drain_events (which walks
        # controller.slots by .request) streams the first token while the
        # request is in transit between pools.
        self.holder = SimpleNamespace(request=snap["request"])


class DisaggController:
    """Prefill pool + decode pool behind one engine-compatible facade.

    API mirrors :class:`~dlti_tpu.serving.replicas.ReplicatedEngine`
    (``submit`` / ``step`` / ``generate`` / ``has_work`` / stats surface),
    so the AsyncEngine stepper, the admission gateway, and the metrics
    registry drive it unchanged. ``step()`` is one controller iteration:
    prefill pool steps, finished prefills are harvested into per-decode-
    replica staging queues, staged snapshots inject into free decode
    slots, decode pool steps.
    """

    def __init__(
        self,
        model_cfg,
        params,
        engine_cfg: EngineConfig = EngineConfig(),
        lora_cfg=None,
        *,
        prefill_replicas: int = 1,
        decode_replicas: int = 1,
        tensor: int = 1,
        devices: Optional[Sequence] = None,
        max_retries: int = 2,
        fault_inject_step: str = "",
        handoff_queue_depth: int = 8,
        handoff_deadline_s: float = 0.0,
        affinity_spill_threshold: int = 4,
        lifecycle_cfg=None,
    ):
        import jax

        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError(
                f"prefill_replicas ({prefill_replicas}) and decode_replicas "
                f"({decode_replicas}) must be >= 1")
        devices = list(devices if devices is not None else jax.devices())
        need = (prefill_replicas + decode_replicas) * tensor
        if need > len(devices):
            raise ValueError(
                f"disagg needs {need} devices ({prefill_replicas} prefill + "
                f"{decode_replicas} decode replicas x tensor={tensor}), "
                f"have {len(devices)}")
        self.logger = get_logger()
        self.telemetry = RequestTelemetry()
        self._tracer = self.telemetry.tracer
        faults = _parse_pool_fault(
            os.environ.get(FAULT_INJECT_ENV) or fault_inject_step)
        # The env var is pool-scoped here; hide it from the inner
        # ReplicatedEngines (their parser rejects the POOL: prefix) and
        # route the remainder to the right pool via the explicit kwarg.
        env_saved = os.environ.pop(FAULT_INJECT_ENV, None)
        try:
            split = prefill_replicas * tensor
            self.prefill = ReplicatedEngine(
                model_cfg, params, engine_cfg, lora_cfg,
                replicas=prefill_replicas, tensor=tensor,
                devices=devices[:split], max_retries=max_retries,
                fault_inject_step=faults.get("prefill", ""),
                affinity_spill_threshold=affinity_spill_threshold,
                telemetry=self.telemetry, lifecycle_cfg=lifecycle_cfg)
            self.decode = ReplicatedEngine(
                model_cfg, params, engine_cfg, lora_cfg,
                replicas=decode_replicas, tensor=tensor,
                devices=devices[split:split + decode_replicas * tensor],
                max_retries=max_retries,
                fault_inject_step=faults.get("decode", ""),
                affinity_spill_threshold=affinity_spill_threshold,
                telemetry=self.telemetry, lifecycle_cfg=lifecycle_cfg)
        finally:
            if env_saved is not None:
                os.environ[FAULT_INJECT_ENV] = env_saved
        for eng in self.prefill.engines:
            eng.prefill_only = True
        # Pool-extinction rescue (degraded colocation): with no prefill
        # replica left, stranded prompts re-prefill on a decode replica
        # (full engines); with no decode replica left, a live prefill
        # engine flips colocated and decodes everything itself.
        self.prefill.failover_fallback = self._rescue_to_decode
        self.decode.failover_fallback = self._rescue_to_prefill
        self.max_retries = max_retries
        self.handoff_queue_depth = max(1, handoff_queue_depth)
        self.handoff_deadline_s = handoff_deadline_s
        # Per-decode-replica staging queues (index-aligned with
        # decode.engines). Host-side only; bounded; visible to the memory
        # ledger below.
        self._staging: List[deque] = [deque()
                                      for _ in self.decode.engines]
        self._rr = 0
        self.handoff = {"completed": 0, "bytes": 0, "fallbacks": 0,
                        "sheds": 0}
        # Concurrent pool stepping (opt-in via start()): a prefill-pool
        # thread overlaps long prefills with decode dispatch.
        self._prefill_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        for di, eng in enumerate(self.decode.engines):
            # Host-staged payloads are numpy (post device_get), so the
            # HBM ledger attributes them 0 device bytes — the owner still
            # appears in every snapshot, and on backends where staging
            # pins device-visible host memory the bytes show up here.
            eng.memledger.register(
                "kv_handoff_staging",
                lambda q=self._staging[di]: [s.snap["payloads"] for s in q])

    # -- routing --------------------------------------------------------
    def submit(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               affinity_key: Optional[str] = None,
               adapter: str = "", trace_id: str = "") -> Request:
        """Admit into the prefill pool (least-loaded / affinity routing is
        ReplicatedEngine's); with the prefill pool extinct, degrade to
        colocated admission on the decode pool rather than refusing.

        ``adapter`` rides the Request through the KV handoff: the prefill
        engine pins it from its own pool, ``export_handoff``'s release
        drops that pin, and ``adopt_handoff`` re-acquires on the decode
        replica's pool (adoption defers while that pool is pinned full).
        """
        try:
            return self.prefill.submit(prompt_token_ids, params,
                                       request_id, affinity_key,
                                       adapter=adapter, trace_id=trace_id)
        except RuntimeError:
            if self.decode.num_live == 0:
                raise
            self.logger.warning(
                "prefill pool has no live replicas; admitting colocated "
                "on the decode pool")
            return self.decode.submit(prompt_token_ids, params,
                                      request_id, affinity_key,
                                      adapter=adapter, trace_id=trace_id)

    def _rescue_to_decode(self, req: Request) -> bool:
        live = self.decode.live_engines()
        if not live:
            return False
        target = min(live, key=self.decode._load)
        target.resubmit(req)
        return True

    def _rescue_to_prefill(self, req: Request) -> bool:
        live = self.prefill.live_engines()
        if not live:
            return False
        eng = min(live, key=self.prefill._load)
        if eng.prefill_only:
            # No decode replica left anywhere: this engine must carry its
            # requests end-to-end from now on (colocated mode).
            eng.prefill_only = False
            self.logger.warning(
                "decode pool has no live replicas; prefill replica %d now "
                "runs colocated", self.prefill.engines.index(eng))
        eng.resubmit(req)
        return True

    # -- the controller loop --------------------------------------------
    def step(self) -> List[Request]:
        """One controller iteration. Sequential by default (deterministic:
        the byte-identity contract's test mode, and correct anywhere).
        After :meth:`start`, the prefill pool steps on its own thread and
        ``step()`` covers only inject + decode — the host no longer blocks
        a decode dispatch on a long prefill's result, which is where the
        decode-TPOT win under mixed load comes from."""
        finished: List[Request] = []
        if self._prefill_thread is None:
            finished.extend(self.prefill.step())
            self._harvest()
        finished.extend(self._inject())
        finished.extend(self.decode.step())
        return finished

    def start(self) -> None:
        """Start concurrent pool stepping: a daemon thread runs the
        prefill pool (step + harvest) while the caller's stepper drives
        ``step()`` for inject + decode. Safe against the existing
        threading contract: ``submit`` already races ``step`` in the
        server (HTTP handler threads vs the AsyncEngine stepper), and the
        staging handoff crosses threads on deque append/popleft only."""
        if self._prefill_thread is not None:
            return
        self._stop.clear()
        self._prefill_thread = threading.Thread(
            target=self._prefill_loop, name="disagg-prefill", daemon=True)
        self._prefill_thread.start()

    def stop(self) -> None:
        t = self._prefill_thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._prefill_thread = None

    def _prefill_loop(self) -> None:
        while not self._stop.is_set():
            if self.prefill.has_work:
                try:
                    self.prefill.step()
                    self._harvest()
                except Exception:  # noqa: BLE001 — a pool-wide fault
                    # must not kill the thread silently mid-serve; the
                    # per-replica failover inside step() already absorbed
                    # per-replica faults, so this is last-resort.
                    self.logger.exception("disagg prefill loop error")
                    self._stop.wait(0.05)
            else:
                self._stop.wait(0.001)

    def _harvest(self) -> None:
        """Move finished prefills off their prefill slots into staging.

        A slot is harvestable once it is occupied, done prefilling, and
        its request still wants more tokens (a one-token request finished
        on the prefill engine already). When every staging queue is full
        the slot simply stays occupied — that is the backpressure that
        shrinks gateway dispatch room.
        """
        for pi, eng in enumerate(self.prefill.engines):
            if pi in self.prefill._dead or not eng.prefill_only:
                continue
            for slot in eng.slots:
                req = slot.request
                if (req is None or slot.prefilling or req.done
                        or slot.last_token is None):
                    continue
                di = self._pick_decode_replica()
                if di is None:
                    return  # every queue full: leave slots occupied
                t0 = time.monotonic()
                # The staged wait books as the kv_handoff phase; the mark
                # closes at decode-side admission (adopt or re-prefill).
                note_requeue(req, "kv_handoff")
                snap = eng.export_handoff(slot)
                if snap is None:
                    # Block fetch failed (best-effort transport): release
                    # the slot and re-prefill on the decode side — the
                    # client sees latency, never an error.
                    self.handoff["fallbacks"] += 1
                    eng._release(slot)
                    self.decode.engines[di].resubmit(req)
                    continue
                self.handoff["bytes"] += _payload_nbytes(snap["payloads"])
                self._staging[di].append(_Staged(snap, t0))

    def _pick_decode_replica(self) -> Optional[int]:
        """Least-loaded live decode replica with staging room (round-robin
        tiebreak), counting staged snapshots as load."""
        best, best_load = None, None
        n = len(self.decode.engines)
        for k in range(n):
            i = (self._rr + k) % n
            if i in self.decode._dead:
                continue
            if len(self._staging[i]) >= self.handoff_queue_depth:
                continue
            load = (self.decode._load(self.decode.engines[i])
                    + len(self._staging[i]))
            if best_load is None or load < best_load:
                best, best_load = i, load
        if best is not None:
            self._rr = (best + 1) % n
        return best

    def _inject(self) -> List[Request]:
        """Drain staging queues into free decode slots; honor cancels,
        deadlines, and decode-replica death while staged."""
        finished: List[Request] = []
        now = time.monotonic()
        for di, q in enumerate(self._staging):
            dead = di in self.decode._dead
            while q:
                staged = q[0]
                req = staged.snap["request"]
                if req.cancel_requested:
                    q.popleft()
                    self.handoff["sheds"] += 1
                    req.finish_reason = "stop"
                    req.finish_time = now
                    self._finish_ring(di).append(req)
                    self.telemetry.on_finished(req)
                    finished.append(req)
                    continue
                if dead:
                    # The decode replica died with this snapshot staged:
                    # re-admit from the snapshot on a survivor (adopt), or
                    # re-prefill there when adoption can't take it now.
                    q.popleft()
                    self._reroute(staged)
                    continue
                if (self.handoff_deadline_s > 0
                        and now - staged.t0 > self.handoff_deadline_s):
                    # Staged too long (slot famine on this replica):
                    # degrade to a re-prefill instead of waiting forever.
                    q.popleft()
                    self.handoff["sheds"] += 1
                    self.decode.engines[di].resubmit(req)
                    continue
                eng = self.decode.engines[di]
                if not eng.adopt_handoff(staged.snap):
                    break  # no slot/blocks free — retry next step
                q.popleft()
                dt = time.monotonic() - staged.t0
                self.handoff["completed"] += 1
                handoff_seconds.observe(dt)
                self._tracer.complete(
                    "engine/kv_handoff", staged.t0, staged.t0 + dt,
                    cat="engine", id=req.request_id,
                    trace=req.trace_id, decode_replica=di)
                req.replica = (len(self.prefill.engines) + di)
        return finished

    def _reroute(self, staged: "_Staged") -> None:
        req = staged.snap["request"]
        for di in range(len(self.decode.engines)):
            if di in self.decode._dead:
                continue
            if len(self._staging[di]) < self.handoff_queue_depth:
                self._staging[di].append(staged)
                return
        # Nowhere to stage: re-prefill least-loaded (live decode replica,
        # else the prefill-pool rescue path errors it out properly).
        live = self.decode.live_engines()
        if live:
            self.handoff["fallbacks"] += 1
            min(live, key=self.decode._load).resubmit(req)
            return
        if not self._rescue_to_prefill(req):
            req.finish_reason = "error"
            req.finish_time = time.monotonic()
            self._finish_ring(0).append(req)
            self.telemetry.on_finished(req)

    def _finish_ring(self, di: int):
        return self.decode.engines[di].finished

    # -- engine-compatible surface --------------------------------------
    @property
    def has_work(self) -> bool:
        return (self.prefill.has_work or self.decode.has_work
                or any(self._staging))

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[GenerationResult]:
        """Offline batch generation across both pools."""
        reqs = [self.submit(p, params) for p in prompts]
        while self.has_work:
            self.step()
        eng = self.decode.engines[0]
        return [eng._result(r) for r in reqs]

    def live_engines(self) -> List[InferenceEngine]:
        """Live PREFILL engines — the admission side: the gateway's
        dispatch room must track where new prompts land. With the
        prefill pool extinct, the decode pool (degraded colocation) is
        the admission side."""
        live = self.prefill.live_engines()
        return live if live else self.decode.live_engines()

    @property
    def num_live(self) -> int:
        return self.prefill.num_live + self.decode.num_live

    # -- replica lifecycle (pool-aware) ---------------------------------
    @property
    def lifecycle_pending(self) -> bool:
        return self.prefill.lifecycle_pending or self.decode.lifecycle_pending

    def lifecycle_counts(self) -> dict:
        """/health summary aggregated across both pools."""
        pc, dc = self.prefill.lifecycle_counts(), self.decode.lifecycle_counts()
        return {k: pc[k] + dc[k] for k in pc}

    def request_reload(self, weights_provider) -> bool:
        """Rolling weight reload across BOTH pools (prefill first — a
        mixed-version window between the pools is unavoidable mid-roll;
        each pool stays internally consistent)."""
        ok_p = self.prefill.request_reload(weights_provider)
        ok_d = self.decode.request_reload(weights_provider)
        return ok_p and ok_d

    @property
    def failover(self) -> dict:
        pf, df = self.prefill.failover, self.decode.failover
        return {k: pf[k] + df[k] for k in pf}

    @property
    def affinity(self) -> dict:
        pa, da = self.prefill.affinity, self.decode.affinity
        return {k: pa[k] + da[k] for k in pa}

    def warmup_decode_ladder(self) -> None:
        # Decode pool only: prefill-only engines never dispatch decode,
        # so warming their ladder would burn startup time compiling
        # programs that cannot run.
        self.decode.warmup_decode_ladder()

    @property
    def cfg(self) -> EngineConfig:
        return self.decode.engines[0].cfg

    @property
    def slots(self) -> list:
        staged = [s.holder for q in self._staging for s in q]
        return self.prefill.slots + staged + self.decode.slots

    @property
    def finished(self) -> List[Request]:
        return self.prefill.finished + self.decode.finished

    @property
    def waiting(self) -> List[Request]:
        return self.prefill.waiting + self.decode.waiting

    @property
    def num_active(self) -> int:
        return (self.prefill.num_active + self.decode.num_active
                + sum(len(q) for q in self._staging))

    @property
    def num_free_blocks(self) -> int:
        return self.prefill.num_free_blocks + self.decode.num_free_blocks

    def abort_all(self, reason: str = "abort") -> List[Request]:
        aborted = self.prefill.abort_all(reason=reason)
        for q in self._staging:
            while q:
                req = q.popleft().snap["request"]
                req.finish_reason = reason
                req.finish_time = time.monotonic()
                self.telemetry.on_finished(req)
                aborted.append(req)
        aborted.extend(self.decode.abort_all(reason=reason))
        return aborted

    @property
    def stats(self) -> dict:
        """Aggregated counters across both pools, with per-pool detail
        under "pools" and the handoff counters under "kv_handoff"."""
        ps, ds = self.prefill.stats, self.decode.stats
        agg = {k: ps[k] + ds[k] for k in ps if k != "replicas"}
        agg["pools"] = {"prefill": ps, "decode": ds}
        agg["kv_handoff"] = {**self.handoff,
                             "staged": sum(len(q) for q in self._staging)}
        return agg

    def pool_scalars(self) -> dict:
        """Scalar source for the metrics registry (``dlti_pool_*`` /
        ``dlti_kv_handoff_*`` series; server.build_registry wires it)."""
        return {
            "pool_prefill_replicas_alive": self.prefill.num_live,
            "pool_decode_replicas_alive": self.decode.num_live,
            "pool_prefill_waiting": len(self.prefill.waiting),
            "pool_decode_waiting": len(self.decode.waiting),
            "pool_prefill_active": self.prefill.num_active,
            "pool_decode_active": self.decode.num_active,
            "kv_handoff_total": self.handoff["completed"],
            "kv_handoff_bytes_total": self.handoff["bytes"],
            "kv_handoff_staged": sum(len(q) for q in self._staging),
            "kv_handoff_fallbacks_total": self.handoff["fallbacks"],
            "kv_handoff_sheds_total": self.handoff["sheds"],
        }


# Gauge keys for pool_scalars (point-in-time values; the rest expose as
# counters). server.build_registry passes these to add_scalar_source.
POOL_GAUGE_KEYS = (
    "pool_prefill_replicas_alive", "pool_decode_replicas_alive",
    "pool_prefill_waiting", "pool_decode_waiting",
    "pool_prefill_active", "pool_decode_active", "kv_handoff_staged",
)
