"""Device-resident decode-state cache — the serving half of the
host-latency-hiding layer.

The engine used to re-upload its *entire* per-slot decode state — block
tables (rebuilt as a fresh numpy array in ``_decode_block_tables``), slot
keys, gen counts, temperature/top-k/top-p — via ``jnp.asarray`` on every
decode dispatch, even though a typical step dirties only a handful of slots
(an admission, a retirement, a block-table row growing by one). This class
keeps those six arrays as persistent device arrays and maintains them
*incrementally*, vLLM-style (Kwon et al., SOSP 2023: incremental scheduler
state is what keeps decode host overhead flat as batch size grows):

* The engine marks a slot dirty at admission, release (retire / preempt /
  abort), block-table growth, and prefill completion. :meth:`sync` then
  scatters just the dirty rows into the device arrays (one fused jitted
  update, row count padded to a power of two so the compile surface stays
  O(log max_seqs)) — ``_decode_block_tables``'s full rebuild becomes an
  in-place row update.
* A **clean step uploads nothing**: every decode dispatch between
  scheduling events reuses the resident arrays as-is (asserted in tier-1:
  ``tests/test_host_overlap.py``).
* Gen counts advance **on device**: after a K-step window the cache bumps
  the resident counts by K (matching the host mirror's per-token append
  for every slot that survived the window; a slot that finished mid-window
  was released, which marks it dirty). No host→device traffic for the one
  mirror that changes every single step.
* Prefilling slots' block-table rows are masked to the trash block at
  upload time (same invariant as the legacy rebuild): a decode program can
  never scribble on KV a partially-prefilled slot has written.

The speculative path keeps the legacy re-upload (it ships the full token
history anyway); a spec round calls :meth:`mark_all_dirty` so the next
plain dispatch resynchronizes. Outputs are byte-identical to the re-upload
path — for every *active* slot the resident rows equal the host mirrors at
each dispatch (equivalence-tested, including across preemption and
re-admission).

Updates deliberately do **not** donate the old arrays: they are KB-scale,
and the previous window's program may still hold them as in-flight
(non-donated) operands.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Mirror names in the decode programs' argument order (after ids/positions).
_FIELDS = ("block_tables", "slot_keys", "gen_counts",
           "temperature", "top_k", "top_p")


class DecodeStateCache:
    """Persistent device twins of the engine's per-slot host mirrors."""

    def __init__(self, num_slots: int, device=None, mesh=None,
                 stats: Optional[dict] = None,
                 extra_fields: Sequence[str] = ()):
        # Optional extra per-slot mirrors (e.g. the multi-LoRA path's
        # "adapter_ids") ride APPENDED after the base six, so the
        # positional invariants below — block_tables at index 0 (masked
        # for prefilling rows), gen_counts at index 2 (bumped on device)
        # — hold regardless.
        self._fields = _FIELDS + tuple(extra_fields)
        self._num_slots = num_slots
        self._device = device
        self._mesh = mesh
        self._dev: Optional[Tuple[jax.Array, ...]] = None
        self._dirty: set = set()
        self._all_dirty = True
        # Counters surfaced through the engine's stats dict (and so the
        # /metrics scalar source): upload syncs, rows shipped, clean syncs.
        self.stats = stats if stats is not None else {}
        for k in ("decode_state_uploads", "decode_state_rows",
                  "decode_state_clean_syncs"):
            self.stats.setdefault(k, 0)
        # One jitted updater; XLA specializes per padded row count.
        self._update = jax.jit(self._apply_rows)
        self._bump = jax.jit(lambda cnt, k: cnt + k)

    # ------------------------------------------------------------------
    @staticmethod
    def _apply_rows(dev, idx, rows):
        return tuple(a.at[idx].set(r) for a, r in zip(dev, rows))

    def _place(self, x: np.ndarray) -> jax.Array:
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(x, NamedSharding(self._mesh, P()))
        if self._device is not None:
            return jax.device_put(x, self._device)
        return jnp.asarray(x)

    # -- dirty tracking (engine-side scheduling events) -----------------
    def mark_dirty(self, slot_id: int) -> None:
        self._dirty.add(slot_id)

    def mark_all_dirty(self) -> None:
        """Resident state is stale wholesale (a spec round ran, or the
        legacy path was used); re-upload everything at the next sync."""
        self._all_dirty = True

    # ------------------------------------------------------------------
    def sync(self, mirrors: Dict[str, np.ndarray],
             masked_rows: Sequence[int] = ()) -> Tuple[jax.Array, ...]:
        """Bring the device arrays up to date with the host ``mirrors``
        and return them in decode-program argument order.

        ``masked_rows``: slot ids whose block-table row must read as the
        trash block (partially-prefilled slots).
        """
        masked = set(masked_rows)
        if self._dev is None or self._all_dirty:
            host = [np.asarray(mirrors[f]) for f in self._fields]
            if masked:
                bt = host[0].copy()
                bt[sorted(masked)] = 0
                host[0] = bt
            self._dev = tuple(self._place(h) for h in host)
            self.stats["decode_state_uploads"] += 1
            self.stats["decode_state_rows"] += self._num_slots
            self._all_dirty = False
            self._dirty.clear()
        elif self._dirty:
            idx = sorted(self._dirty)
            n = len(idx)
            npad = 1
            while npad < n:
                npad *= 2
            npad = min(npad, self._num_slots)
            # Pad with a repeat of the first dirty row: duplicate scatter
            # indices carry identical values, so the .set is well-defined.
            idx_arr = np.full((npad,), idx[0], np.int32)
            idx_arr[:n] = idx
            rows: List[np.ndarray] = []
            for f in self._fields:
                r = np.ascontiguousarray(np.asarray(mirrors[f])[idx_arr])
                if f == "block_tables" and masked:
                    for j, sid in enumerate(idx_arr):
                        if int(sid) in masked:
                            r[j] = 0
                rows.append(r)
            self._dev = self._update(self._dev, jnp.asarray(idx_arr),
                                     tuple(jnp.asarray(r) for r in rows))
            self.stats["decode_state_uploads"] += 1
            self.stats["decode_state_rows"] += n
            self._dirty.clear()
        else:
            self.stats["decode_state_clean_syncs"] += 1
        return self._dev

    def bump_gen_counts(self, k: int) -> None:
        """Advance the resident gen counts by ``k`` decode steps — on
        device, mirroring the host appends for every slot that survives
        the window (finished slots were released → marked dirty)."""
        if self._dev is None or k <= 0:
            return
        dev = list(self._dev)
        dev[2] = self._bump(dev[2], np.int32(k))
        self._dev = tuple(dev)
