"""Lower tiers of the hierarchical prefix-KV cache: host RAM and disk.

The HBM prefix cache (:mod:`dlti_tpu.serving.prefix_cache`) used to
*discard* evicted blocks — a returning chat session whose system prompt
fell out of the pool re-prefilled it from scratch. This module is the
memory hierarchy below HBM:

* **Host tier** — a bounded LRU of evicted blocks' KV payloads in host
  RAM (numpy arrays, fetched device→host at eviction time, staged
  through ``pinned_host`` where the backend exposes it — the same path
  the ZeRO-3 offload machinery proves). Restoring from here costs one
  host→device scatter instead of a full re-prefill.
* **Disk tier** — host-tier overflow demotes to digest-verified block
  dirs written with the checkpoint store's manifest/SHA-256 protocol
  (:func:`dlti_tpu.checkpoint.store.save_pytree` — atomic staging +
  rename, per-file SHA-256 in ``MANIFEST.json``). A bit-flipped or
  truncated block fails verification on read, is *quarantined* into
  ``_quarantine/`` (the checkpoint store's convention), and reads as a
  cache miss — never an engine fault.

Tier payloads are keyed by the allocator's exact chain key (nested token
tuples), so a lower-tier hit carries the same no-collision guarantee as
an HBM hit. A hit *pops* the payload (the block promotes back up to HBM;
budgets stay meaningful), and every byte moved down comes back up
bit-identical (round-trip equality is tier-1-tested).

Metric names (tier-labeled; pinned in ``tests/test_bench_contract.py``)
live in :mod:`dlti_tpu.serving.prefix_cache` alongside the allocator
that drives them.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

_QUARANTINE_DIR = "_quarantine"

# Disk-tier health policy: this many consecutive write failures flip the
# tier memory-only (writes skipped, existing blocks still readable) until
# the cooldown expires, when the next demotion probes the disk again. A
# dead disk costs pool misses / re-prefills — never a request error.
DISK_FAIL_LIMIT = 3
DISK_RETRY_COOLDOWN_S = 30.0

# A block payload: {"l00000": {"k": np.ndarray, "v": np.ndarray, ...}, ...}
# — one entry per model layer, every array of the per-layer pool's row
# shape (block_size, kv_heads, head_dim) (plus scale rows for int8 pools).
Payload = Dict[str, Dict[str, np.ndarray]]


def key_digest(key: tuple) -> str:
    """Stable content digest of a chain key (used as the disk dir name).

    The chain key is nested tuples of ints, whose ``repr`` is canonical
    across processes — so a restarted server could in principle re-adopt
    block dirs (today the index is in-memory and rebuilt empty).
    """
    return hashlib.sha256(repr(key).encode()).hexdigest()[:32]


class TieredBlockStore:
    """Bounded host-RAM + disk store of demoted prefix-KV blocks.

    Single-threaded by contract: all calls happen on the engine stepper
    thread (the same contract as the allocator it backs). The one
    exception is the durable writer's ENOSPC reclaimer, which may fire
    on any thread mid-write — the disk index is lock-protected for it.

    Disk-tier storage faults degrade, never error: ``disk_fail_limit``
    consecutive write failures flip the tier memory-only until
    ``disk_retry_cooldown_s`` elapses, then the next demotion probes the
    disk again (automatic recovery once the fault clears).
    """

    def __init__(self, host_blocks: int = 0, disk_dir: str = "",
                 disk_blocks: int = 0,
                 disk_fail_limit: int = DISK_FAIL_LIMIT,
                 disk_retry_cooldown_s: float = DISK_RETRY_COOLDOWN_S,
                 clock: Callable[[], float] = time.monotonic):
        if disk_blocks > 0 and not disk_dir:
            raise ValueError("disk_blocks > 0 needs a disk_dir")
        self.host_blocks = int(host_blocks)
        self.disk_dir = os.path.abspath(disk_dir) if disk_dir else ""
        self.disk_blocks = int(disk_blocks) if self.disk_dir else 0
        self.disk_fail_limit = int(disk_fail_limit)
        self.disk_retry_cooldown_s = float(disk_retry_cooldown_s)
        self._clock = clock
        # LRU order, oldest first; host maps key -> payload, disk maps
        # key -> block dir path (the index is in-memory: payloads on disk
        # are only trusted after digest verification at read time).
        self._host: "collections.OrderedDict[tuple, Payload]" = \
            collections.OrderedDict()
        self._disk: "collections.OrderedDict[tuple, str]" = \
            collections.OrderedDict()
        self._disk_lock = threading.Lock()
        self._fail_streak = 0
        self._down_until = 0.0     # clock() time the cooldown expires
        self.logger = get_logger()
        self.stats = {"host_puts": 0, "disk_puts": 0, "host_hits": 0,
                      "disk_hits": 0, "disk_evictions": 0,
                      "corrupt_dropped": 0, "disk_write_failures": 0,
                      "disk_degraded_skips": 0}
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
            # ENOSPC escape hatches: quarantined wreckage first, then
            # cold (oldest-LRU) live blocks — a demoted block is a cache
            # entry, and cache entries lose to keeping the system writing.
            durable_io.register_reclaimer(
                f"prefix-quarantine:{self.disk_dir}",
                durable_io.quarantine_reclaimer(self.disk_dir))
            durable_io.register_reclaimer(
                f"prefix-cold-blocks:{self.disk_dir}",
                self._reclaim_cold_blocks)

    @property
    def disk_degraded(self) -> bool:
        """True while the disk tier is flipped memory-only."""
        return (self._fail_streak >= self.disk_fail_limit
                and self._clock() < self._down_until)

    def _reclaim_cold_blocks(self, bytes_needed: int) -> int:
        """Durable-writer reclaimer: drop oldest-LRU disk blocks (each
        one is just a future cache hit) until enough bytes are freed."""
        import shutil

        freed = 0
        while True:
            with self._disk_lock:
                if not self._disk:
                    break
                _vk, vpath = self._disk.popitem(last=False)
            size = durable_io.dir_bytes(vpath)
            shutil.rmtree(vpath, ignore_errors=True)
            self.stats["disk_evictions"] += 1
            freed += size
            if bytes_needed > 0 and freed >= bytes_needed:
                break
        return freed

    # ------------------------------------------------------------------
    @property
    def num_host_blocks(self) -> int:
        return len(self._host)

    @property
    def num_disk_blocks(self) -> int:
        with self._disk_lock:
            return len(self._disk)

    def tier_of(self, key: tuple) -> Optional[str]:
        """Which tier holds ``key`` (index lookup only — a disk entry may
        still fail verification at fetch time)."""
        if key in self._host:
            return "host"
        with self._disk_lock:
            if key in self._disk:
                return "disk"
        return None

    # ------------------------------------------------------------------
    def put(self, key: tuple, payload: Payload) -> Optional[str]:
        """Demote an evicted HBM block's payload into the hierarchy.

        Returns the tier it landed in ("host" | "disk") or None when no
        tier is configured to take it (payload dropped, legacy behavior).
        Host overflow cascades its LRU victim down to disk.
        """
        with self._disk_lock:
            if key in self._host or key in self._disk:
                return None  # already demoted under this content key
        if self.host_blocks > 0:
            self._host[key] = payload
            self._host.move_to_end(key)
            self.stats["host_puts"] += 1
            while len(self._host) > self.host_blocks:
                from dlti_tpu.serving.prefix_cache import (
                    demotions_total, evictions_total,
                )

                vk, vp = self._host.popitem(last=False)  # LRU victim
                evictions_total.labels(tier="host").inc()
                if self._spill_to_disk(vk, vp) is not None:
                    demotions_total.labels(tier="disk").inc()
            return "host"
        return self._spill_to_disk(key, payload)

    def _spill_to_disk(self, key: tuple, payload: Payload) -> Optional[str]:
        if self.disk_blocks <= 0:
            return None  # no disk tier: the payload is dropped
        if self.disk_degraded:
            # Memory-only until the cooldown expires: the demotion reads
            # as a drop (a future pool miss), never a request error.
            self.stats["disk_degraded_skips"] += 1
            return None
        from dlti_tpu.checkpoint.store import save_pytree

        path = os.path.join(self.disk_dir, f"block-{key_digest(key)}")
        try:
            # Checkpoint-store protocol: staging dir + per-file SHA-256
            # manifest + atomic rename — a kill mid-write can never
            # present a torn block as valid. path_class="prefix_tier"
            # gives the writes the tier's (short) retry budget.
            save_pytree(path, payload, path_class="prefix_tier")
        except OSError as e:
            self.stats["disk_write_failures"] += 1
            self._fail_streak += 1
            if self._fail_streak >= self.disk_fail_limit:
                newly = self._clock() >= self._down_until
                self._down_until = self._clock() + self.disk_retry_cooldown_s
                if newly:
                    self.logger.error(
                        "prefix disk tier DEGRADED to memory-only after %d "
                        "consecutive write failures (last: %s); retrying "
                        "in %.0fs", self._fail_streak, e,
                        self.disk_retry_cooldown_s)
            else:
                self.logger.warning("prefix disk tier write failed (%s); "
                                    "block dropped", e)
            return None
        if self._fail_streak:
            self.logger.warning("prefix disk tier recovered (write "
                                "succeeded after %d failures)",
                                self._fail_streak)
        self._fail_streak = 0
        self._down_until = 0.0
        with self._disk_lock:
            self._disk[key] = path
            self._disk.move_to_end(key)
        self.stats["disk_puts"] += 1
        while True:
            with self._disk_lock:
                if len(self._disk) <= self.disk_blocks:
                    break
                vk, vpath = self._disk.popitem(last=False)
            from dlti_tpu.serving.prefix_cache import evictions_total

            import shutil

            shutil.rmtree(vpath, ignore_errors=True)
            self.stats["disk_evictions"] += 1
            evictions_total.labels(tier="disk").inc()
        return "disk"

    # ------------------------------------------------------------------
    def fetch(self, key: tuple) -> Tuple[Optional[Payload], Optional[str]]:
        """Pop ``key``'s payload for promotion back to HBM.

        Returns ``(payload, tier)``; ``(None, None)`` on miss. A disk
        payload that fails digest verification is quarantined and
        reported as a miss — corruption degrades, never faults.
        """
        payload = self._host.pop(key, None)
        if payload is not None:
            self.stats["host_hits"] += 1
            return payload, "host"
        with self._disk_lock:
            path = self._disk.pop(key, None)
        if path is None:
            return None, None
        from dlti_tpu.checkpoint.store import (
            CheckpointCorruptError, load_pytree,
        )

        try:
            payload = load_pytree(path, verify=True)
        except (CheckpointCorruptError, OSError, ValueError, KeyError) as e:
            self._quarantine(path, f"{type(e).__name__}")
            self.stats["corrupt_dropped"] += 1
            return None, None
        import shutil

        shutil.rmtree(path, ignore_errors=True)  # promoted back up
        self.stats["disk_hits"] += 1
        return payload, "disk"

    def _quarantine(self, path: str, reason: str) -> None:
        """Move a failed block dir into ``_quarantine/`` (the checkpoint
        store's convention): the bytes stay for forensics, the index
        forgets them, the request that probed them sees a miss."""
        qdir = os.path.join(self.disk_dir, _QUARANTINE_DIR)
        try:
            os.makedirs(qdir, exist_ok=True)
            base = os.path.basename(path)
            dst = os.path.join(qdir, f"{base}__{reason}")
            k = 0
            while os.path.exists(dst):
                k += 1
                dst = os.path.join(qdir, f"{base}__{reason}__{k}")
            durable_io.replace(path, dst, path_class="prefix_tier")
            self.logger.warning(
                "quarantined corrupt prefix block %s (%s) -> %s",
                path, reason, dst)
        except OSError:
            # Even quarantine failing must read as a plain miss.
            self.logger.warning("could not quarantine %s; dropping index "
                                "entry only", path)
