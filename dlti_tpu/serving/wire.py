"""Fleet wire protocol: length-prefixed, digest-verified frames over TCP.

The multi-process serving fleet (``serving.fleet`` supervisor ↔
``serving.worker`` engine workers) speaks a deliberately small binary
protocol so cross-process behavior stays byte-exact and debuggable:

Frame layout (network byte order)::

    +--------+---------+------+-----+-------------+----------+------------+
    | magic  | version | type | pad | payload_len | payload  | digest     |
    | 4B     | u16     | u8   | u8  | u32         | N bytes  | 16B sha256 |
    +--------+---------+------+-----+-------------+----------+------------+

``digest`` is the first 16 bytes of SHA-256 over the payload — a torn or
bit-flipped frame surfaces as :class:`WireDigestMismatch` instead of a
corrupted adoption. Every malformed-input case has its own exception type
so callers can distinguish "peer died mid-frame" (fail the worker over)
from "peer spoke garbage" (protocol bug / wrong port — evict).

Payloads are encoded with a self-contained tagged binary serializer
(:func:`pack_obj` / :func:`unpack_obj`) whose numpy encoding round-trips
dtype + shape + raw bytes exactly — the property the paged-KV handoff
envelope (:func:`pack_handoff`) needs for byte-identical cross-process
migration (same guarantee as the in-process ``adopt_handoff`` path).
"""

from __future__ import annotations

import hashlib
import socket
import struct
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from dlti_tpu.telemetry.registry import Counter

MAGIC = b"DLTW"
WIRE_VERSION = 1
# Handoff envelopes carry whole paged-KV payload sets; a 7B-class request
# stays far under this, and anything larger is a protocol bug, not data.
DEFAULT_MAX_FRAME = 256 * 1024 * 1024

_HEADER = struct.Struct("!4sHBxI")  # magic, version, frame type, pad, len
_DIGEST_BYTES = 16

# -- frame types -------------------------------------------------------------
FT_SUBMIT = 1        # supervisor -> worker: new/failover request descriptor
FT_STEP = 2          # supervisor -> worker: run one engine step
FT_STEP_RESULT = 3   # worker -> supervisor: per-request token deltas
FT_DRAIN = 4         # supervisor -> worker: export handoffs + queued work
FT_ADOPT = 5         # supervisor -> worker: adopt one handoff envelope
FT_RELOAD = 6        # supervisor -> worker: swap weights (rolling reload)
FT_HEALTH = 7        # supervisor -> worker: liveness + metrics snapshot
FT_ABORT = 8         # supervisor -> worker: abort all in-flight work
FT_SHUTDOWN = 9      # supervisor -> worker: clean exit
FT_OK = 10           # worker -> supervisor: success reply (packed object)
FT_ERROR = 11        # worker -> supervisor: handler failure (message)

FRAME_NAMES = {
    FT_SUBMIT: "submit", FT_STEP: "step", FT_STEP_RESULT: "step_result",
    FT_DRAIN: "drain", FT_ADOPT: "adopt", FT_RELOAD: "reload",
    FT_HEALTH: "health", FT_ABORT: "abort", FT_SHUTDOWN: "shutdown",
    FT_OK: "ok", FT_ERROR: "error",
}

WIRE_METRIC_NAMES = (
    "dlti_fleet_frames_total",
    "dlti_fleet_wire_bytes_total",
)
frames_total = Counter(
    WIRE_METRIC_NAMES[0],
    help="fleet wire-protocol frames sent, by frame kind")
wire_bytes_total = Counter(
    WIRE_METRIC_NAMES[1],
    help="fleet wire-protocol bytes sent (headers + payloads + digests)")


# -- errors ------------------------------------------------------------------
class WireError(RuntimeError):
    """Base for every wire-protocol failure."""


class WireClosed(WireError):
    """Peer closed the connection cleanly at a frame boundary."""


class WireTruncated(WireError):
    """Peer died (or the stream was cut) mid-frame."""


class WireBadMagic(WireError):
    """Stream does not start with the protocol magic — wrong port/peer."""


class WireVersionMismatch(WireError):
    """Frame or envelope written by an incompatible protocol version."""


class WireFrameTooLarge(WireError):
    """Declared payload length exceeds the frame-size bound."""


class WireDigestMismatch(WireError):
    """Payload digest check failed — corrupt or tampered frame."""


class WireRemoteError(WireError):
    """Peer replied with an FT_ERROR frame; message is the remote reason."""


# -- frame I/O ---------------------------------------------------------------
def _recv_exact(sock: socket.socket, n: int, *,
                at_boundary: bool = False) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (ConnectionResetError, BrokenPipeError) as e:
            raise WireTruncated(f"connection reset mid-frame: {e}") from e
        if not chunk:
            if at_boundary and not buf:
                raise WireClosed("peer closed the connection")
            raise WireTruncated(
                f"peer died mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, ftype: int, payload: bytes = b"") -> None:
    digest = hashlib.sha256(payload).digest()[:_DIGEST_BYTES]
    header = _HEADER.pack(MAGIC, WIRE_VERSION, ftype, len(payload))
    try:
        sock.sendall(header + payload + digest)
    except (ConnectionResetError, BrokenPipeError, OSError) as e:
        raise WireTruncated(f"send failed: {e}") from e
    frames_total.labels(kind=FRAME_NAMES.get(ftype, str(ftype))).inc()
    wire_bytes_total.inc(len(header) + len(payload) + _DIGEST_BYTES)


def recv_frame(sock: socket.socket,
               max_frame_bytes: int = DEFAULT_MAX_FRAME,
               ) -> Tuple[int, bytes]:
    header = _recv_exact(sock, _HEADER.size, at_boundary=True)
    magic, version, ftype, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise WireBadMagic(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireVersionMismatch(
            f"peer speaks wire version {version}, this side {WIRE_VERSION}")
    if length > max_frame_bytes:
        raise WireFrameTooLarge(
            f"declared payload {length}B exceeds bound {max_frame_bytes}B")
    payload = _recv_exact(sock, length)
    digest = _recv_exact(sock, _DIGEST_BYTES)
    if hashlib.sha256(payload).digest()[:_DIGEST_BYTES] != digest:
        raise WireDigestMismatch(
            f"payload digest mismatch on {FRAME_NAMES.get(ftype, ftype)} "
            f"frame ({length}B)")
    return ftype, payload


def request_reply(sock: socket.socket, ftype: int, obj: Any = None, *,
                  max_frame_bytes: int = DEFAULT_MAX_FRAME) -> Any:
    """One strict request/response round trip: send ``obj``, return the
    FT_OK reply object; an FT_ERROR reply raises :class:`WireRemoteError`
    (the handler failed remotely, the connection itself is still good)."""
    send_frame(sock, ftype, pack_obj(obj))
    rtype, payload = recv_frame(sock, max_frame_bytes)
    if rtype == FT_ERROR:
        err = unpack_obj(payload)
        raise WireRemoteError(str(err.get("error", "unknown remote error"))
                              if isinstance(err, dict) else str(err))
    if rtype != FT_OK:
        raise WireError(
            f"expected ok/error reply, got {FRAME_NAMES.get(rtype, rtype)}")
    return unpack_obj(payload)


# -- tagged binary object serializer ----------------------------------------
# Tags: N none, T/F bool, i int64, I bigint, f float64, s str, y bytes,
# l list, t tuple, d dict, a ndarray (dtype + shape + raw C-order bytes).
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _pack_into(obj: Any, out: bytearray) -> None:
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif isinstance(obj, int) and not isinstance(obj, bool):
        if _I64_MIN <= obj <= _I64_MAX:
            out += b"i"
            out += struct.pack("!q", obj)
        else:
            enc = str(obj).encode("ascii")
            out += b"I"
            out += struct.pack("!I", len(enc))
            out += enc
    elif isinstance(obj, float):
        out += b"f"
        out += struct.pack("!d", obj)
    elif isinstance(obj, str):
        enc = obj.encode("utf-8")
        out += b"s"
        out += struct.pack("!I", len(enc))
        out += enc
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        data = bytes(obj)
        out += b"y"
        out += struct.pack("!I", len(data))
        out += data
    elif isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        dt_spec = arr.dtype.str
        if arr.dtype.kind == "V":
            # ml_dtypes extension types (bfloat16, float8_*) stringify as
            # anonymous void ("<V2"); their .name is what round-trips.
            dt_spec = arr.dtype.name
        dt = dt_spec.encode("ascii")
        out += b"a"
        out += struct.pack("!H", len(dt))
        out += dt
        out += struct.pack("!B", arr.ndim)
        out += struct.pack(f"!{arr.ndim}q", *arr.shape)
        raw = arr.tobytes()
        out += struct.pack("!Q", len(raw))
        out += raw
    elif isinstance(obj, np.generic):
        _pack_into(obj.item(), out)
    elif isinstance(obj, (list, tuple)):
        out += b"l" if isinstance(obj, list) else b"t"
        out += struct.pack("!I", len(obj))
        for item in obj:
            _pack_into(item, out)
    elif isinstance(obj, dict):
        out += b"d"
        out += struct.pack("!I", len(obj))
        for k, v in obj.items():
            _pack_into(k, out)
            _pack_into(v, out)
    else:
        raise TypeError(f"unserializable type for wire: {type(obj)!r}")


def _resolve_dtype(spec: str) -> np.dtype:
    try:
        return np.dtype(spec)
    except TypeError:
        pass
    try:
        import ml_dtypes  # jax dependency: bfloat16 / float8 families

        return np.dtype(getattr(ml_dtypes, spec))
    except (ImportError, AttributeError, TypeError) as e:
        raise WireError(f"corrupt wire object: unknown dtype {spec!r}") from e


def pack_obj(obj: Any) -> bytes:
    out = bytearray()
    _pack_into(obj, out)
    return bytes(out)


def _unpack_from(buf: bytes, pos: int) -> Tuple[Any, int]:
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"i":
        return struct.unpack_from("!q", buf, pos)[0], pos + 8
    if tag == b"I":
        (n,) = struct.unpack_from("!I", buf, pos)
        pos += 4
        return int(buf[pos:pos + n].decode("ascii")), pos + n
    if tag == b"f":
        return struct.unpack_from("!d", buf, pos)[0], pos + 8
    if tag == b"s":
        (n,) = struct.unpack_from("!I", buf, pos)
        pos += 4
        return buf[pos:pos + n].decode("utf-8"), pos + n
    if tag == b"y":
        (n,) = struct.unpack_from("!I", buf, pos)
        pos += 4
        return buf[pos:pos + n], pos + n
    if tag == b"a":
        (dn,) = struct.unpack_from("!H", buf, pos)
        pos += 2
        dt = _resolve_dtype(buf[pos:pos + dn].decode("ascii"))
        pos += dn
        (ndim,) = struct.unpack_from("!B", buf, pos)
        pos += 1
        shape = struct.unpack_from(f"!{ndim}q", buf, pos)
        pos += 8 * ndim
        (nbytes,) = struct.unpack_from("!Q", buf, pos)
        pos += 8
        arr = np.frombuffer(buf[pos:pos + nbytes], dtype=dt).reshape(shape)
        return arr.copy(), pos + nbytes
    if tag in (b"l", b"t"):
        (n,) = struct.unpack_from("!I", buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _unpack_from(buf, pos)
            items.append(item)
        return (items if tag == b"l" else tuple(items)), pos
    if tag == b"d":
        (n,) = struct.unpack_from("!I", buf, pos)
        pos += 4
        d: Dict[Any, Any] = {}
        for _ in range(n):
            k, pos = _unpack_from(buf, pos)
            v, pos = _unpack_from(buf, pos)
            d[k] = v
        return d, pos
    raise WireError(f"corrupt wire object: unknown tag {tag!r} at {pos - 1}")


def unpack_obj(data: bytes) -> Any:
    try:
        obj, pos = _unpack_from(data, 0)
    except (struct.error, IndexError, UnicodeDecodeError, ValueError) as e:
        raise WireError(f"corrupt wire object: {e}") from e
    if pos != len(data):
        raise WireError(
            f"corrupt wire object: {len(data) - pos} trailing bytes")
    return obj


# -- request descriptor ------------------------------------------------------
# Only cross-process-meaningful fields travel; monotonic timestamps are
# process-local clocks and are re-anchored on the receiving side (byte
# identity is about tokens/logprobs, not wall-clock bookkeeping).
_PARAM_FIELDS = ("temperature", "top_k", "top_p", "max_tokens",
                 "stop_token_ids", "seed", "logprobs")


def request_to_wire(req) -> dict:
    return {
        "request_id": req.request_id,
        "prompt_token_ids": list(req.prompt_token_ids),
        "params": {f: getattr(req.params, f) for f in _PARAM_FIELDS},
        "output_token_ids": list(req.output_token_ids),
        "output_logprobs": (list(req.output_logprobs)
                            if req.output_logprobs is not None else None),
        "finish_reason": req.finish_reason,
        "num_preemptions": req.num_preemptions,
        "num_retries": req.num_retries,
        "num_migrations": req.num_migrations,
        "tenant": req.tenant,
        "priority": req.priority,
        "adapter": req.adapter,
        "cancel_requested": req.cancel_requested,
        # Distributed-trace context: the SAME id in every process that
        # touches any leg of this request (fresh submits, failover
        # resubmits, and — via pack_handoff wrapping this descriptor —
        # drain-migration KV envelopes).
        "trace_id": getattr(req, "trace_id", ""),
    }


def request_from_wire(d: dict):
    from dlti_tpu.serving.engine import Request
    from dlti_tpu.serving.sampling import SamplingParams

    pd = dict(d["params"])
    if pd.get("stop_token_ids") is not None:
        pd["stop_token_ids"] = tuple(pd["stop_token_ids"])
    req = Request(
        request_id=d["request_id"],
        prompt_token_ids=list(d["prompt_token_ids"]),
        params=SamplingParams(**pd),
        arrival_time=time.monotonic(),
    )
    req.output_token_ids = list(d.get("output_token_ids") or [])
    if d.get("output_logprobs") is not None:
        req.output_logprobs = list(d["output_logprobs"])
    req.finish_reason = d.get("finish_reason")
    req.num_preemptions = int(d.get("num_preemptions", 0))
    req.num_retries = int(d.get("num_retries", 0))
    req.num_migrations = int(d.get("num_migrations", 0))
    req.tenant = d.get("tenant", "")
    req.priority = d.get("priority", req.priority)
    req.adapter = d.get("adapter", "")
    req.cancel_requested = bool(d.get("cancel_requested", False))
    # Absent on frames from peers predating distributed tracing: such
    # requests simply go untraced ("" — never re-minted here, which
    # would fork the id between processes).
    req.trace_id = d.get("trace_id", "") or ""
    return req


# -- versioned handoff envelope ----------------------------------------------
HANDOFF_VERSION = 1


def pack_handoff(snap: dict) -> bytes:
    """Serialize an ``export_handoff`` snapshot (request descriptor,
    per-block paged-KV payloads, rng key bytes, gen_count) as a versioned
    binary envelope. The numpy payloads round-trip byte-exactly, so a
    cross-process ``adopt_handoff`` continues the decode stream with the
    same tokens the exporting worker would have produced."""
    body = dict(snap)
    body["request"] = request_to_wire(body["request"])
    return pack_obj({"v": HANDOFF_VERSION, "kind": "kv-handoff",
                     "snap": body})


def unpack_handoff(data: bytes) -> dict:
    obj = unpack_obj(data)
    if not isinstance(obj, dict) or obj.get("kind") != "kv-handoff":
        raise WireError("not a handoff envelope")
    if obj.get("v") != HANDOFF_VERSION:
        raise WireVersionMismatch(
            f"handoff envelope version {obj.get('v')!r}, "
            f"this side {HANDOFF_VERSION}")
    snap = obj["snap"]
    snap["request"] = request_from_wire(snap["request"])
    return snap


# -- shared test/tooling helper ----------------------------------------------
def ephemeral_port(host: str = "127.0.0.1") -> int:
    """Pick a currently-free TCP port on ``host``.

    The single helper every socket-binding test (gateway / server / traces
    / fleet) uses instead of hand-rolled ``bind(0)`` copies, so port
    allocation behavior is uniform and collision handling has one home.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def connect_with_retry(host: str, port: int, *, timeout_s: float,
                       interval_s: float = 0.1) -> socket.socket:
    """TCP connect, retrying until the listener is up or the deadline
    passes (worker processes bind only after their engine is built)."""
    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            last = e
            time.sleep(interval_s)
    raise WireError(f"could not connect to {host}:{port} "
                    f"within {timeout_s}s: {last}")
