"""Continuous delivery: the deployment controller that lets the system
train, canary, and ship itself — no human in the loop.

Every piece it composes already exists: crash-consistent verified
checkpoints (``checkpoint.store``), digest-verified pytree exports
(``save_pytree``), per-replica canary-gated rolling reloads
(``ReplicatedEngine.request_reload`` / ``FleetSupervisor``), numeric
guards, and the SLO machinery. The :class:`DeploymentController` closes
the loop:

1. **Watch** — poll a training run's checkpoint directory (injectable
   clock) for newly COMMITted steps that pass
   :func:`~dlti_tpu.checkpoint.store.verify_checkpoint`
   (via ``latest_verified_step``: anything newer that fails is
   quarantined by the scan itself).
2. **Export** — extract the candidate's ``.params`` subtree host-side
   (:func:`~dlti_tpu.checkpoint.export.export_params_host`, no model
   init) into a digest-verified ``save_pytree`` artifact under the
   export root.
3. **Canary** — build a canary engine from the export (one shadow
   replica materialized BESIDE the serving fleet, so client capacity is
   never reduced), mirror a sampled fraction of live traffic onto it as
   shadow requests (the ``shadow_tap`` hook in
   ``ReplicatedEngine``/``FleetSupervisor`` dispatch; shadow results
   never reach clients and never book into client-facing SLIs), and
   judge concrete gates against the incumbent:

   * greedy logprob drift on a pinned probe set,
   * output-length distribution shift (shadow vs paired live requests),
   * per-phase TTFT/TPOT SLO compliance on the shadow requests,
   * nonfinite logprobs / numeric faults / errored shadow requests.

4. **Promote or roll back** — on pass, promote fleet-wide through the
   rolling ``request_reload`` path (re-verified before every per-replica
   swap) and pin the new manifest digest + step; on fail, discard the
   canary (the fleet never changed — that IS the rollback), quarantine
   the rejected export for forensics, refuse that step forever
   (persisted, so a restart does not retry it), and back off the next
   candidate exponentially so a flapping training run cannot thrash the
   fleet.

The controller is pure bookkeeping on an injectable clock plus two
injectable capabilities — ``exporter(watch_dir, step, out_dir) ->
digest`` and ``canary_factory(export_dir) -> engine`` — so the state
machine is unit-testable with fakes on a fake clock; ``scripts/serve.py``
wires the real checkpoint store and real engines underneath it.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Callable, List, Optional

from dlti_tpu.config import DeployConfig
from dlti_tpu.telemetry.registry import Counter, Gauge
from dlti_tpu.utils import durable_io
from dlti_tpu.utils.logging import get_logger

# Name-stability contract (pinned in tests/test_bench_contract.py).
DEPLOY_METRIC_NAMES = (
    "dlti_deploy_candidates_total",
    "dlti_deploy_canaries_total",
    "dlti_deploy_promotions_total",
    "dlti_deploy_rollbacks_total",
    "dlti_deploy_rejected_total",
    "dlti_deploy_incumbent_step",
)

# Module-level metrics (the lifecycle/watchdog pattern): every controller
# in the process shares them; build_registry registers them for /metrics.
candidates_total = Counter(
    DEPLOY_METRIC_NAMES[0],
    help="new verified checkpoint steps noticed by the watch loop")
canaries_total = Counter(
    DEPLOY_METRIC_NAMES[1],
    help="canary phases started (candidate exported and shadow engine up)")
promotions_total = Counter(
    DEPLOY_METRIC_NAMES[2],
    help="candidates promoted fleet-wide via rolling reload")
rollbacks_total = Counter(
    DEPLOY_METRIC_NAMES[3],
    help="canaried candidates rolled back to the incumbent "
         "(gate failure or mid-roll abort)")
rejected_total = Counter(
    DEPLOY_METRIC_NAMES[4],
    help="checkpoint steps refused forever (export failure or canary "
         "rejection; the export is quarantined)")
incumbent_step_gauge = Gauge(
    DEPLOY_METRIC_NAMES[5],
    help="training step of the checkpoint the fleet currently serves "
         "(-1 until the controller promotes one)")

_REFUSED_FILE = "refused_steps.jsonl"


class _ShadowPair:
    """One mirrored request: the live (incumbent) request the client got,
    and its shadow twin running on the candidate engine."""

    __slots__ = ("live", "shadow")

    def __init__(self, live, shadow):
        self.live = live
        self.shadow = shadow


class DeploymentController:
    """Checkpoint-watching deploy controller with shadow-traffic canary
    and autonomous promote/rollback.

    ``engine`` is the serving fleet facade (``ReplicatedEngine``,
    ``FleetSupervisor``, or anything with ``request_reload`` and a
    ``shadow_tap`` attribute). Heavy work (export, canary engine build,
    probe generation) runs on the controller's own thread — never the
    fleet stepper's — so a slow export cannot stall client decode.
    """

    def __init__(self, engine, cfg: DeployConfig, *,
                 exporter: Optional[Callable] = None,
                 canary_factory: Optional[Callable] = None,
                 incumbent_dir: str = "",
                 incumbent_step: int = -1,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.cfg = cfg
        self.clock = clock
        self.logger = get_logger()
        self.enabled = bool(cfg.enabled)
        self.watch_dir = os.path.abspath(cfg.watch_dir) if cfg.watch_dir \
            else ""
        self.export_root = os.path.abspath(
            cfg.export_dir or os.path.join(self.watch_dir or ".",
                                           "_deploy_exports"))
        if exporter is None:
            from dlti_tpu.checkpoint.export import export_params_host

            exporter = export_params_host
        self.exporter = exporter
        self.canary_factory = canary_factory
        # Incumbent identity: which export dir / training step / manifest
        # digest the fleet is serving. The boot export (--model-dir or
        # --reload-checkpoint) seeds it; every promotion replaces it.
        self.incumbent_dir = os.path.abspath(incumbent_dir) \
            if incumbent_dir else ""
        self.incumbent_step = incumbent_step
        self.incumbent_digest: Optional[str] = None
        if self.incumbent_dir:
            from dlti_tpu.checkpoint.store import manifest_digest

            self.incumbent_digest = manifest_digest(self.incumbent_dir)
        # State machine: idle -> canary -> promoting -> idle.
        self.state = "idle"
        self._last_poll = -math.inf
        self._backoff_until = -math.inf
        self._consecutive_rollbacks = 0
        self._refused: dict = {}  # step -> reason
        self._load_refused()
        # Candidate under canary (all None when idle/promoting done).
        self._candidate: Optional[dict] = None
        self._canary_engine = None
        self._pairs: List[_ShadowPair] = []
        self._tap_queue: List[tuple] = []
        self._tap_lock = threading.Lock()
        self._tap_acc = 0.0
        self._tap_seen = 0
        self._tap_mirrored = 0
        # Pinned probe baseline: [(tokens, logprobs)] per probe prompt,
        # measured on the incumbent weights. Re-pinned at every promote
        # (the candidate's own probe results become the next baseline).
        self._baseline: Optional[list] = None
        self._last_result: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Install the tap (cheap no-op outside a canary phase).
        engine.shadow_tap = self._tap

    # -- persistence of refusals ----------------------------------------
    def _refused_path(self) -> str:
        return os.path.join(self.export_root, _REFUSED_FILE)

    def _load_refused(self) -> None:
        path = self._refused_path()
        if not os.path.isfile(path):
            return
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    self._refused[int(rec["step"])] = rec.get("reason", "")
        except (OSError, ValueError) as e:
            self.logger.warning("deploy: unreadable refused-steps log "
                                "%s: %s", path, e)

    def _refuse(self, step: int, reason: str) -> None:
        """Refuse ``step`` forever: in memory now, durably on disk so a
        controller restart does not re-canary a known-bad checkpoint."""
        if step in self._refused:
            return
        self._refused[step] = reason
        rejected_total.inc()
        try:
            os.makedirs(self.export_root, exist_ok=True)
            durable_io.append_line(
                self._refused_path(),
                json.dumps({"step": step, "reason": reason}),
                path_class="checkpoint")
        except Exception as e:  # noqa: BLE001 — refusal still holds in-mem
            self.logger.error("deploy: could not persist refusal of step "
                              "%d: %s", step, e)

    # -- shadow tap ------------------------------------------------------
    def _tap(self, prompt_token_ids, params, live_req) -> None:
        """Called from the fleet's submit path (any thread) for every
        client request. Samples ``canary_shadow_frac`` of them into the
        mirror queue; the canary loop drains it. Outside a canary phase
        this is two attribute reads."""
        if self.state != "canary":
            return
        with self._tap_lock:
            self._tap_seen += 1
            self._tap_acc += self.cfg.canary_shadow_frac
            if self._tap_acc < 1.0:
                return
            self._tap_acc -= 1.0
            if len(self._tap_queue) >= 4 * max(1, self.cfg.canary_min_requests):
                return  # bounded mirror backlog; drop, never block
            self._tap_mirrored += 1
            self._tap_queue.append((list(prompt_token_ids), params,
                                    live_req))

    # -- probe set -------------------------------------------------------
    def _probe_prompts(self) -> List[List[int]]:
        """Deterministic pinned probe prompts (small token ids, safe for
        any vocab the fleet serves)."""
        n = max(1, self.cfg.probe_prompts)
        k = max(1, self.cfg.probe_prompt_tokens)
        return [[((7 * i + j) % 96) + 1 for j in range(k)]
                for i in range(n)]

    def _run_probes(self, eng) -> Optional[list]:
        """Greedy probe generations on ``eng``: [(tokens, logprobs)] per
        prompt, or None when generation fails (numeric guard trip, engine
        fault) — a verdict, not an error."""
        from dlti_tpu.serving.engine import SamplingParams

        out = []
        try:
            for i, prompt in enumerate(self._probe_prompts()):
                sp = SamplingParams(
                    temperature=0.0,
                    max_tokens=max(1, self.cfg.probe_max_tokens))
                req = eng.submit(prompt, sp, f"deploy-probe-{i}")
                req.shadow = True
                for _ in range(2000):
                    if req.done:
                        break
                    eng.step()
                if not req.done or req.finish_reason == "error":
                    return None
                out.append((list(req.output_token_ids),
                            list(req.output_logprobs)))
        except Exception as e:  # noqa: BLE001 — a failed probe is a verdict
            self.logger.warning("deploy: probe generation failed: %s", e)
            return None
        return out

    # -- tick ------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """One state-machine advance. Called from the controller thread
        in production and directly (with a fake clock) in tests."""
        now = self.clock() if now is None else now
        if not self.enabled:
            return
        if self.state == "idle":
            self._tick_idle(now)
        elif self.state == "canary":
            self._tick_canary(now)
        elif self.state == "promoting":
            self._tick_promoting(now)

    def _tick_idle(self, now: float) -> None:
        if now < self._backoff_until:
            return
        if now - self._last_poll < self.cfg.poll_interval_s:
            return
        self._last_poll = now
        if not self.watch_dir:
            return
        from dlti_tpu.checkpoint.store import latest_verified_step

        step = latest_verified_step(self.watch_dir)
        if step is None or step == self.incumbent_step \
                or step in self._refused:
            return
        candidates_total.inc()
        self.logger.info("deploy: new verified candidate step %d", step)
        out_dir = os.path.join(self.export_root, f"step-{step}")
        try:
            digest = self.exporter(self.watch_dir, step, out_dir)
        except Exception as e:  # noqa: BLE001 — a bad step must not loop
            self.logger.error("deploy: export of step %d failed: %s",
                              step, e)
            self._refuse(step, f"export-failed:{type(e).__name__}")
            self._last_result = {"step": step, "verdict": "rejected",
                                 "reasons": ["export-failed"]}
            return
        if self.canary_factory is None:
            self.logger.error("deploy: no canary factory wired; cannot "
                              "canary step %d", step)
            return
        try:
            self._canary_engine = self.canary_factory(out_dir)
        except Exception as e:  # noqa: BLE001 — unloadable export = reject
            self.logger.error("deploy: canary engine build for step %d "
                              "failed: %s", step, e)
            self._reject(step, out_dir, ["canary-build-failed"], {})
            return
        # Pin the incumbent baseline lazily: built once from the
        # incumbent export, then refreshed from each promoted candidate's
        # own probe results (free — same prompts, same weights).
        if self._baseline is None and self.incumbent_dir \
                and self.canary_factory is not None:
            try:
                ref = self.canary_factory(self.incumbent_dir)
                self._baseline = self._run_probes(ref)
                self._close_engine(ref)
            except Exception as e:  # noqa: BLE001 — drift gate degrades off
                self.logger.warning("deploy: incumbent baseline probe "
                                    "failed (drift gate off): %s", e)
        probes = self._run_probes(self._canary_engine)
        self._candidate = {"step": step, "dir": out_dir, "digest": digest,
                           "probes": probes, "started": now}
        self._pairs = []
        with self._tap_lock:
            self._tap_queue.clear()
            self._tap_acc = 0.0
            self._tap_seen = 0
            self._tap_mirrored = 0
        canaries_total.inc()
        self.state = "canary"
        self.logger.info("deploy: canarying step %d (digest %s) under "
                         "shadow traffic", step, (digest or "")[:12])

    def _tick_canary(self, now: float) -> None:
        cand = self._candidate
        eng = self._canary_engine
        # Numeric gate part 1: nonfinite/failed probes reject immediately
        # — no point mirroring traffic onto a numerically-dead candidate.
        if cand["probes"] is None or any(
                not all(map(math.isfinite, lps)) for _, lps in
                cand["probes"]):
            self._reject(cand["step"], cand["dir"],
                         ["numeric:probe-nonfinite-or-failed"], {})
            return
        # Drain the mirror queue onto the candidate engine.
        with self._tap_lock:
            batch, self._tap_queue = self._tap_queue, []
        for prompt, params, live_req in batch:
            try:
                shadow = eng.submit(prompt, params,
                                    f"shadow-{len(self._pairs)}")
                shadow.shadow = True
                # Shadow twin shares the live request's trace context —
                # a federated timeline shows the mirrored leg beside the
                # client-facing one (telemetry still skips shadow spans;
                # this only links whatever the canary engine does emit).
                shadow.trace_id = getattr(live_req, "trace_id", "") \
                    or getattr(shadow, "trace_id", "")
                self._pairs.append(_ShadowPair(live_req, shadow))
            except Exception as e:  # noqa: BLE001 — submit fault = reject
                self._reject(cand["step"], cand["dir"],
                             [f"numeric:shadow-submit-fault:{e}"], {})
                return
        # Step the candidate (bounded work per tick).
        try:
            for _ in range(64):
                if not getattr(eng, "has_work", False):
                    break
                eng.step()
        except Exception as e:  # noqa: BLE001 — step fault = numeric reject
            self._reject(cand["step"], cand["dir"],
                         [f"numeric:canary-step-fault:{type(e).__name__}"],
                         {})
            return
        done_pairs = [p for p in self._pairs
                      if p.shadow.done and p.live.done]
        waited = now - cand["started"]
        if len(done_pairs) < max(0, self.cfg.canary_min_requests) \
                and waited < self.cfg.canary_max_wait_s:
            return
        verdict, reasons, gates = self._judge(cand, done_pairs)
        if verdict:
            self._begin_promote(cand, gates)
        else:
            self._reject(cand["step"], cand["dir"], reasons, gates)

    def _judge(self, cand: dict, pairs: list):
        """Evaluate the four gates. Returns (ok, reasons, gates-detail)."""
        cfg = self.cfg
        reasons: List[str] = []
        gates: dict = {"pairs": len(pairs)}
        # Gate: numeric faults on shadow requests.
        errored = [p for p in pairs if p.shadow.finish_reason == "error"]
        nonfinite = [p for p in pairs
                     if not all(map(math.isfinite,
                                    p.shadow.output_logprobs))]
        gates["shadow_errors"] = len(errored)
        gates["shadow_nonfinite"] = len(nonfinite)
        if errored or nonfinite:
            reasons.append(
                f"numeric:{len(errored)}-errored,"
                f"{len(nonfinite)}-nonfinite")
        # Gate: greedy logprob drift on the pinned probe set.
        drift = None
        if self._baseline is not None and cand["probes"] is not None:
            deltas = []
            for (_, base_lp), (_, cand_lp) in zip(self._baseline,
                                                  cand["probes"]):
                if not base_lp or not cand_lp:
                    continue
                base_mean = sum(base_lp) / len(base_lp)
                cand_mean = sum(cand_lp) / len(cand_lp)
                deltas.append(abs(cand_mean - base_mean))
            drift = max(deltas) if deltas else 0.0
            gates["logprob_drift"] = drift
            gates["logprob_drift_limit"] = cfg.promote_max_logprob_drift
            if drift > cfg.promote_max_logprob_drift:
                reasons.append(f"drift:{drift:.6g}>"
                               f"{cfg.promote_max_logprob_drift:.6g}")
        # Gate: output-length distribution shift (shadow vs paired live).
        if pairs and cfg.max_length_shift_frac > 0:
            live_mean = sum(len(p.live.output_token_ids)
                            for p in pairs) / len(pairs)
            shadow_mean = sum(len(p.shadow.output_token_ids)
                              for p in pairs) / len(pairs)
            shift = abs(shadow_mean - live_mean) / max(1.0, live_mean)
            gates["length_shift"] = shift
            gates["length_shift_limit"] = cfg.max_length_shift_frac
            if shift > cfg.max_length_shift_frac:
                reasons.append(f"length-shift:{shift:.4g}>"
                               f"{cfg.max_length_shift_frac:.4g}")
        # Gate: per-phase SLO compliance on the shadow requests.
        for name, thr in (("ttft", cfg.slo_ttft_threshold_s),
                          ("tpot", cfg.slo_tpot_threshold_s)):
            if thr <= 0 or not pairs:
                continue
            vals = []
            for p in pairs:
                s = p.shadow
                if s.first_token_time is None:
                    continue
                if name == "ttft":
                    vals.append(s.first_token_time - s.arrival_time)
                else:
                    n_out = len(s.output_token_ids)
                    if n_out > 1 and s.finish_time is not None:
                        vals.append((s.finish_time - s.first_token_time)
                                    / (n_out - 1))
            if not vals:
                continue
            compliance = sum(1 for v in vals if v <= thr) / len(vals)
            gates[f"{name}_compliance"] = compliance
            if compliance < cfg.slo_min_compliance:
                reasons.append(f"slo-{name}:{compliance:.3f}<"
                               f"{cfg.slo_min_compliance:.3f}")
        return (not reasons), reasons, gates

    # -- promote / rollback ---------------------------------------------
    def _begin_promote(self, cand: dict, gates: dict) -> None:
        from dlti_tpu.checkpoint.store import (
            load_pytree, manifest_digest, verify_pytree_dir,
        )

        export_dir = cand["dir"]
        expect = cand["digest"]

        def _provider():
            return load_pytree(export_dir, verify=True)

        def _verify() -> bool:
            if manifest_digest(export_dir) != expect:
                return False
            return verify_pytree_dir(export_dir)[0]

        try:
            queued = self.engine.request_reload(_provider, verify=_verify)
        except TypeError:
            # Facade predating the verify kwarg (custom engines in tests).
            queued = self.engine.request_reload(_provider)
        if not queued:
            # A roll is already in progress (operator-kicked /v1/reload);
            # stay in canary and retry next tick.
            self.logger.info("deploy: promote of step %d deferred (a "
                             "reload is already rolling)", cand["step"])
            return
        self.logger.info("deploy: step %d passed canary gates; rolling "
                         "out fleet-wide", cand["step"])
        cand["gates"] = gates
        self.state = "promoting"

    def _tick_promoting(self, now: float) -> None:
        if getattr(self.engine, "_reload", None) is not None:
            return  # roll still in flight
        cand = self._candidate
        ok = getattr(self.engine, "last_reload_ok", None)
        if ok is False:
            # Mid-roll abort (in-roll canary failure or the per-swap
            # re-verification): the candidate never finished shipping.
            rollbacks_total.inc()
            self._refuse(cand["step"], "reload-aborted")
            self._quarantine_export(cand["dir"], "reload-aborted")
            self._note_rollback(now)
            self._last_result = {"step": cand["step"],
                                 "verdict": "rolled-back",
                                 "reasons": ["reload-aborted"],
                                 "gates": cand.get("gates", {})}
            self.logger.error("deploy: promotion of step %d aborted "
                              "mid-roll; incumbent remains step %d",
                              cand["step"], self.incumbent_step)
        else:
            promotions_total.inc()
            self.incumbent_step = cand["step"]
            self.incumbent_digest = cand["digest"]
            self.incumbent_dir = cand["dir"]
            incumbent_step_gauge.set(cand["step"])
            # The candidate's probe results ARE the new incumbent
            # baseline (same prompts, the now-serving weights).
            if cand["probes"] is not None:
                self._baseline = cand["probes"]
            self._consecutive_rollbacks = 0
            self._last_result = {"step": cand["step"],
                                 "verdict": "promoted",
                                 "reasons": [],
                                 "gates": cand.get("gates", {})}
            self.logger.info("deploy: step %d promoted fleet-wide "
                             "(digest %s)", cand["step"],
                             (cand["digest"] or "")[:12])
        self._teardown_candidate()
        self.state = "idle"

    def _reject(self, step: int, export_dir: str, reasons: list,
                gates: dict) -> None:
        """Canary verdict: fail. The fleet never saw the candidate, so
        rolling back = discarding the canary replica; the export is
        quarantined for forensics and the step refused forever."""
        now = self.clock()
        rollbacks_total.inc()
        self._refuse(step, ";".join(reasons) or "canary-reject")
        self._quarantine_export(export_dir, "canary-reject")
        self._note_rollback(now)
        self._last_result = {"step": step, "verdict": "rolled-back",
                             "reasons": reasons, "gates": gates}
        self.logger.error(
            "deploy: step %d REJECTED by canary gates (%s); canary rolled "
            "back to incumbent step %d, export quarantined",
            step, ";".join(reasons), self.incumbent_step)
        self._teardown_candidate()
        self.state = "idle"

    def _note_rollback(self, now: float) -> None:
        self._consecutive_rollbacks += 1
        cfg = self.cfg
        delay = min(cfg.promote_backoff_max_s,
                    cfg.promote_backoff_s *
                    cfg.promote_backoff_factor
                    ** (self._consecutive_rollbacks - 1))
        self._backoff_until = now + delay
        self.logger.warning("deploy: promotion backoff %.1fs after %d "
                            "consecutive rollback(s)", delay,
                            self._consecutive_rollbacks)

    def _quarantine_export(self, export_dir: str, reason: str) -> None:
        from dlti_tpu.checkpoint.store import quarantine_step

        try:
            quarantine_step(os.path.dirname(export_dir),
                            os.path.basename(export_dir), reason)
        except Exception as e:  # noqa: BLE001 — forensics, never fatal
            self.logger.error("deploy: could not quarantine export %s: "
                              "%s", export_dir, e)

    def _close_engine(self, eng) -> None:
        close = getattr(eng, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001
                pass

    def _teardown_candidate(self) -> None:
        if self._canary_engine is not None:
            self._close_engine(self._canary_engine)
        self._canary_engine = None
        self._candidate = None
        self._pairs = []
        with self._tap_lock:
            self._tap_queue.clear()

    # -- operator surface (/v1/deploy) -----------------------------------
    def set_enabled(self, enabled: bool) -> None:
        """Enable/disable the controller (POST /v1/deploy). Disabling
        cancels an in-flight canary WITHOUT refusing its step — the
        operator paused the pipeline; the candidate was not judged."""
        if not enabled and self.state == "canary":
            cand = self._candidate
            self._last_result = {"step": cand["step"],
                                 "verdict": "cancelled",
                                 "reasons": ["disabled"], "gates": {}}
            self._teardown_candidate()
            self.state = "idle"
            self.logger.info("deploy: canary of step %d cancelled "
                             "(controller disabled)", cand["step"])
        self.enabled = bool(enabled)

    def status(self) -> dict:
        cand = self._candidate
        with self._tap_lock:
            tap = {"seen": self._tap_seen, "mirrored": self._tap_mirrored,
                   "queued": len(self._tap_queue)}
        return {
            "enabled": self.enabled,
            "state": self.state,
            "watch_dir": self.watch_dir,
            "export_dir": self.export_root,
            "incumbent": {"step": self.incumbent_step,
                          "digest": self.incumbent_digest,
                          "dir": self.incumbent_dir},
            "candidate": (None if cand is None else
                          {"step": cand["step"],
                           "digest": cand["digest"],
                           "pairs_done": sum(
                               1 for p in self._pairs
                               if p.shadow.done and p.live.done)}),
            "refused_steps": {str(k): v
                              for k, v in sorted(self._refused.items())},
            "consecutive_rollbacks": self._consecutive_rollbacks,
            "backoff_until": (None if self._backoff_until == -math.inf
                              else self._backoff_until),
            "shadow": tap,
            "last_result": self._last_result,
            "counters": {
                "candidates": candidates_total.value,
                "canaries": canaries_total.value,
                "promotions": promotions_total.value,
                "rollbacks": rollbacks_total.value,
                "rejected": rejected_total.value,
            },
        }

    # Flight-recorder source (deploy.json in every dump).
    def to_dict(self) -> dict:
        return self.status()

    # -- thread ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="deploy-controller")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._thread = None
        # == not `is`: bound methods are materialized per-access, so an
        # identity check would never match the instance installed in
        # __init__ and the tap would leak past stop().
        if getattr(self.engine, "shadow_tap", None) == self._tap:
            self.engine.shadow_tap = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                self.logger.exception("deploy: tick raised")
            # Canary/promote phases poll fast (shadow stepping latency);
            # idle watches at a gentle cadence independent of
            # poll_interval_s (the clock gates the actual dir scan).
            self._stop.wait(0.02 if self.state != "idle" else
                            min(0.5, max(0.05, self.cfg.poll_interval_s / 4)))
