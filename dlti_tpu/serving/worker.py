"""Fleet engine worker: one ``InferenceEngine`` behind the wire protocol.

``EngineWorker`` serves a single supervisor connection at a time (strict
request/response — the supervisor is the only client) and survives garbage
input: a malformed, truncated, oversized, or digest-failing frame gets an
FT_ERROR reply where possible, then the connection is dropped and the
accept loop continues. The worker process never dies from bad bytes; only
the supervisor decides evictions.

Token streaming works by delta: each FT_STEP reply carries, per in-flight
request, the tokens/logprobs appended since the previous report plus the
finish reason once done — the supervisor applies them to its mirror
``Request`` objects, so the HTTP layer's event drain works unchanged
against mirrors. FT_HEALTH doubles as the heartbeat and exports the
worker's metrics registry snapshot for supervisor-side federation.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Any, Callable, Dict, List, Optional, Set

from dlti_tpu.serving import wire
from dlti_tpu.utils.logging import get_logger


def _numeric_only(d: dict) -> dict:
    return {k: v for k, v in d.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


class EngineWorker:
    """Wrap one engine behind the fleet wire protocol on a TCP socket."""

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 worker_id: int = 0, registry=None,
                 reload_fn: Optional[Callable[[Any], Any]] = None,
                 max_frame_bytes: int = wire.DEFAULT_MAX_FRAME,
                 tracer=None, span_ship_max: int = 512):
        self.engine = engine
        self.worker_id = worker_id
        self.registry = registry
        self.logger = get_logger()
        # Span federation: this worker ships its span-ring tail
        # incrementally in FT_STEP/FT_HEALTH replies (cursor = total
        # appends, so ring eviction between ships is counted, not
        # silent). None = the engine's tracer (the process-global one in
        # a real worker process); tests pass private per-worker tracers
        # so thread-fleet fakes get genuinely distinct rings.
        self.tracer = tracer if tracer is not None \
            else getattr(engine.telemetry, "tracer", None)
        self.span_ship_max = span_ship_max
        self._span_cursor = 0
        # Last clock offset the supervisor estimated for this worker
        # (supervisor_clock ≈ our_clock + offset) — echoed down in
        # step/health requests and persisted into flight-dump context so
        # postmortem --all can merge per-worker dumps onto one clock.
        self._clock_offset: Optional[dict] = None
        # Rolling reload: rebuilds the engine from a host param tree
        # (shipped over the wire by the supervisor). None = unsupported.
        self._reload_fn = reload_fn
        self.max_frame_bytes = max_frame_bytes
        self._owned: Set[str] = set()        # request ids this worker holds
        self._reported: Dict[str, int] = {}  # tokens already reported per id
        self._stop = False
        self._conn: Optional[socket.socket] = None  # live supervisor conn
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(2)
        self.host, self.port = self._listener.getsockname()[:2]

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass
        # Unblock a serve thread parked in recv on the live connection —
        # without this, close() from another thread (or the in-process
        # test fake's kill path) leaves the worker hung mid-frame.
        conn = self._conn
        if conn is not None:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        while not self._stop:
            try:
                conn, peer = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conn = conn
            self.logger.info("worker %d: supervisor connected from %s",
                             self.worker_id, peer)
            try:
                self._serve_connection(conn)
            finally:
                self._conn = None
                try:
                    conn.close()
                except OSError:
                    pass
            if self._stop:
                return

    def _serve_connection(self, conn: socket.socket) -> None:
        while not self._stop:
            try:
                ftype, payload = wire.recv_frame(conn, self.max_frame_bytes)
            except wire.WireClosed:
                self.logger.info("worker %d: supervisor disconnected",
                                 self.worker_id)
                return
            except wire.WireError as e:
                # Garbage input never kills the worker: best-effort error
                # reply, then drop the connection and re-accept. The
                # stream past a framing error is unparseable, so the
                # connection cannot be salvaged.
                self.logger.warning("worker %d: protocol error: %s",
                                    self.worker_id, e)
                try:
                    wire.send_frame(conn, wire.FT_ERROR, wire.pack_obj(
                        {"error": f"{type(e).__name__}: {e}"}))
                except wire.WireError:
                    pass
                return
            try:
                reply = self._dispatch(ftype, wire.unpack_obj(payload))
            except Exception as e:  # noqa: BLE001 — handler isolation
                self.logger.exception("worker %d: %s handler failed",
                                      self.worker_id,
                                      wire.FRAME_NAMES.get(ftype, ftype))
                self._dump_fault(ftype, e)
                try:
                    wire.send_frame(conn, wire.FT_ERROR, wire.pack_obj(
                        {"error": f"{type(e).__name__}: {e}"}))
                except wire.WireError:
                    return
                continue
            try:
                wire.send_frame(conn, wire.FT_OK, wire.pack_obj(reply))
            except wire.WireError:
                return
            if self._stop:
                return

    def _dump_fault(self, ftype: int, exc: Exception) -> None:
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None and ftype == wire.FT_STEP:
            # Black box before the supervisor tears this process down:
            # the per-worker dump dir + DLTI_PROCESS_ID tag make this
            # discoverable by postmortem.py --all incident merging.
            rec.dump(reason="worker_step_fault", exc=exc, force=True,
                     extra={"worker": self.worker_id,
                            "in_flight": self.engine.num_active,
                            "queued": len(self.engine.waiting)})

    # -- dispatch ------------------------------------------------------
    def _dispatch(self, ftype: int, obj: Any) -> Any:
        if ftype == wire.FT_SUBMIT:
            return self._on_submit(obj)
        if ftype == wire.FT_STEP:
            return self._on_step(obj)
        if ftype == wire.FT_DRAIN:
            return self._on_drain(obj)
        if ftype == wire.FT_ADOPT:
            return self._on_adopt(obj)
        if ftype == wire.FT_HEALTH:
            return self._on_health(obj)
        if ftype == wire.FT_ABORT:
            return self._on_abort(obj)
        if ftype == wire.FT_RELOAD:
            return self._on_reload(obj)
        if ftype == wire.FT_SHUTDOWN:
            self._stop = True
            return {"ok": True}
        raise wire.WireError(f"unexpected frame type {ftype}")

    def _gauges(self) -> dict:
        eng = self.engine
        return {"active": eng.num_active, "waiting": len(eng.waiting),
                "free_blocks": eng.num_free_blocks,
                "has_work": bool(eng.has_work)}

    def _span_tail(self) -> dict:
        """Unshipped span-ring tail for step/health replies (empty dict
        when tracing is off — replies stay byte-light and old supervisors
        reading with .get() see nothing new)."""
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            return {}
        evs, dropped, self._span_cursor = tracer.events_since(
            self._span_cursor, self.span_ship_max)
        if not evs and not dropped:
            return {}
        return {"spans": evs, "spans_dropped": dropped}

    def _note_clock(self, obj: Any) -> None:
        """Record the supervisor's offset estimate for this worker's
        clock (rides down in step/health requests). Kept on the instance
        and mirrored into the flight recorder context, so a dump from
        this process carries enough to rebase its span tail."""
        if not isinstance(obj, dict) or "clock_offset" not in obj:
            return
        off = {"clock_offset_s": obj.get("clock_offset"),
               "clock_uncertainty_s": obj.get("clock_uncertainty")}
        if off == self._clock_offset:
            return
        self._clock_offset = off
        from dlti_tpu.telemetry import get_recorder

        rec = get_recorder()
        if rec is not None:
            rec.note(**off)

    def _on_submit(self, obj: dict) -> dict:
        desc = obj["request"]
        if obj.get("resubmit"):
            # Failover/rehome of an existing request: keep id, params, and
            # generated-so-far tokens — admission recomputes prompt+output
            # exactly like re-admission after preemption.
            req = wire.request_from_wire(desc)
            self.engine.resubmit(req)
        else:
            params = wire.request_from_wire(desc).params
            adapter = desc.get("adapter", "")
            req = self.engine.submit(
                desc["prompt_token_ids"], params, desc["request_id"],
                # Adopt the supervisor's trace context so every process's
                # spans for this request join one timeline (absent from
                # old supervisors: submit mints a local id instead).
                trace_id=desc.get("trace_id", "") or "",
                **({"adapter": adapter} if adapter else {}))
            req.tenant = desc.get("tenant", "")
            req.priority = desc.get("priority", "")
        self._owned.add(req.request_id)
        self._reported[req.request_id] = len(req.output_token_ids)
        return {"ok": True, **self._gauges()}

    def _on_step(self, obj: dict) -> dict:
        self._note_clock(obj)
        for rid in obj.get("cancels") or ():
            for req in list(self.engine.waiting):
                if req.request_id == rid:
                    req.cancel_requested = True
            for slot in self.engine.slots:
                if (slot.request is not None
                        and slot.request.request_id == rid):
                    slot.request.cancel_requested = True
        if self.engine.has_work:
            self.engine.step()
        events: List[dict] = []
        live = [s.request for s in self.engine.slots
                if s.request is not None]
        live.extend(r for r in list(self.engine.finished)
                    if r.request_id in self._owned)
        for req in live:
            rid = req.request_id
            if rid not in self._owned:
                continue
            seen = self._reported.get(rid, 0)
            ev = {"id": rid,
                  "tokens": list(req.output_token_ids[seen:]),
                  "logprobs": list(req.output_logprobs[seen:]),
                  "preemptions": req.num_preemptions}
            self._reported[rid] = len(req.output_token_ids)
            if req.done:
                ev["finish_reason"] = req.finish_reason
                self._owned.discard(rid)
                self._reported.pop(rid, None)
            if ev["tokens"] or "finish_reason" in ev:
                events.append(ev)
        # "time" gives the supervisor a clock-offset sample on every step
        # RPC (busy workers rarely see FT_HEALTH); the span tail
        # piggybacks so federation lag is one step, not one heartbeat.
        return {"events": events, "stats": dict(self.engine.stats),
                "time": time.monotonic(), **self._span_tail(),
                **self._gauges()}

    def _on_drain(self, obj: dict) -> dict:
        """Export every decodable in-flight request as a handoff envelope
        (queued / mid-prefill ones, with nothing decodable to migrate,
        return as plain resubmit descriptors). The worker keeps nothing:
        its engine ends empty either way."""
        eng = self.engine
        envelopes: List[bytes] = []
        resubmits: List[dict] = []
        for slot in list(eng.slots):
            req = slot.request
            if req is None or req.done:
                continue
            snap = None
            if not slot.prefilling:
                snap = eng.export_handoff(slot)
            if snap is not None:
                envelopes.append(wire.pack_handoff(snap))
            else:
                # export_handoff leaves the slot intact on failure;
                # release it (blocks return to this healthy engine's
                # pool) and hand the request back for a resubmit.
                if slot.request is not None:
                    eng._release(slot)
                resubmits.append(wire.request_to_wire(req))
            self._owned.discard(req.request_id)
            self._reported.pop(req.request_id, None)
        for req in list(eng.waiting):
            resubmits.append(wire.request_to_wire(req))
            self._owned.discard(req.request_id)
            self._reported.pop(req.request_id, None)
        eng.waiting.clear()
        return {"handoffs": envelopes, "resubmits": resubmits,
                **self._gauges()}

    def _on_adopt(self, obj: dict) -> dict:
        snap = wire.unpack_handoff(obj["envelope"])
        req = snap["request"]
        adopted = bool(self.engine.adopt_handoff(snap))
        if adopted:
            self._owned.add(req.request_id)
            # The supervisor's mirror already streamed the generated-so-far
            # tokens; report only what this worker produces from here on.
            self._reported[req.request_id] = len(req.output_token_ids)
        return {"adopted": adopted, **self._gauges()}

    def _on_health(self, obj: Any) -> dict:
        self._note_clock(obj)
        metrics: Dict[str, float] = {}
        if self.registry is not None:
            metrics = _numeric_only(self.registry.stats_dict())
        return {"ok": True, "pid": os.getpid(),
                "worker_id": self.worker_id, "time": time.monotonic(),
                "stats": dict(self.engine.stats), "metrics": metrics,
                **self._span_tail(), **self._gauges()}

    def _on_abort(self, obj: dict) -> dict:
        reason = (obj or {}).get("reason", "abort")
        aborted = self.engine.abort_all(reason=reason)
        self._owned.clear()
        self._reported.clear()
        return {"ok": True,
                "aborted": [r.request_id for r in aborted],
                **self._gauges()}

    def _on_reload(self, obj: dict) -> dict:
        if self._reload_fn is None:
            raise RuntimeError("this worker cannot reload weights "
                               "(no reload_fn wired)")
        if self.engine.num_active or len(self.engine.waiting):
            raise RuntimeError("reload on a non-drained worker refused")
        self.engine = self._reload_fn(obj["params"])
        self._owned.clear()
        self._reported.clear()
        return {"ok": True}
