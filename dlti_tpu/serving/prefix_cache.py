"""Automatic prefix caching for the paged KV cache.

vLLM-style: a *full* KV block's contents are a pure function of the token
chain that produced it (same model, same params), so full blocks are
registered in a content-addressed table and reused across requests that
share a prompt prefix — chat system prompts, few-shot preambles, and
preempted-then-readmitted sequences prefill only their novel suffix.

Design:

* Keys are exact: ``key_i = (key_{i-1}, tokens_of_block_i)`` — no hash
  collisions, verification-free reuse.
* Ref-counted sharing: a cached block may back any number of active
  sequences; it is only evictable at refcount 0.
* Eviction is lazy LRU: unreferenced cached blocks stay registered (and
  allocated in the :class:`BlockManager` pool) until the pool runs dry,
  then the least-recently-used are freed back to the allocator — O(1)
  per eviction via an insertion-ordered dict of refcount-0 entries.
* Only *full* blocks are ever cached. The partial tail block of a
  sequence is exclusively owned and freed normally, so decode writes
  never mutate shared state.
* **Tiering** (:mod:`dlti_tpu.serving.prefix_tiers`): with a
  :class:`~dlti_tpu.serving.prefix_tiers.TieredBlockStore` attached, an
  evicted block's KV payload demotes HBM → host RAM → disk instead of
  being discarded, and a ``match_prefix`` chain that runs past the HBM
  blocks continues into the tiers — the engine restores those blocks
  with a host→device scatter (charged as a *restore*, not a re-prefill)
  and they re-enter the HBM cache pinned for the admitting sequence.

Engine contract: ``match_prefix`` is a pure lookup; call :meth:`acquire`
*before* allocating the suffix blocks (so the matched blocks can't be
evicted to satisfy that very allocation) and :meth:`release` to undo on
allocation failure.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dlti_tpu.serving.block_manager import BlockManager
from dlti_tpu.telemetry.registry import Counter, Gauge

# Exposition-name contract (pinned in tests/test_bench_contract.py, like
# the gateway / ckpt / prefetch sets). All tier-labeled: tier="hbm" |
# "host" | "disk".
PREFIX_CACHE_METRIC_NAMES = (
    "dlti_prefix_cache_hits_total",
    "dlti_prefix_cache_misses_total",
    "dlti_prefix_cache_evictions_total",
    "dlti_prefix_cache_promotions_total",
    "dlti_prefix_cache_demotions_total",
    "dlti_prefix_cache_blocks",
)

hits_total = Counter(
    PREFIX_CACHE_METRIC_NAMES[0],
    help="admissions that reused cached prefix blocks (by serving tier)")
misses_total = Counter(
    PREFIX_CACHE_METRIC_NAMES[1],
    help="admissions that found no reusable blocks in a tier")
evictions_total = Counter(
    PREFIX_CACHE_METRIC_NAMES[2],
    help="blocks evicted from a tier under budget pressure")
promotions_total = Counter(
    PREFIX_CACHE_METRIC_NAMES[3],
    help="blocks promoted back to HBM from a lower tier (restores)")
demotions_total = Counter(
    PREFIX_CACHE_METRIC_NAMES[4],
    help="evicted blocks demoted into a lower tier instead of dropped")
blocks_gauge = Gauge(
    PREFIX_CACHE_METRIC_NAMES[5],
    help="blocks currently cached per tier")


class _Entry:
    __slots__ = ("block", "key", "refcount")

    def __init__(self, block: int, key: tuple):
        self.block = block
        self.key = key
        self.refcount = 0


class PrefixCachingAllocator:
    """Wraps a :class:`BlockManager` with content-addressed block reuse.

    All engine allocation/free traffic must flow through this object so
    refcounts stay consistent.
    """

    def __init__(self, block_manager: BlockManager, tier_store=None,
                 kv_fetch: Optional[Callable[[int], dict]] = None):
        self.bm = block_manager
        self.block_size = block_manager.block_size
        self._by_key: Dict[tuple, _Entry] = {}
        self._by_block: Dict[int, _Entry] = {}
        # refcount-0 entries in LRU order (oldest first) — the evictables.
        self._lru: "collections.OrderedDict[int, _Entry]" = collections.OrderedDict()
        self.stats = {"hits": 0, "hit_tokens": 0, "evictions": 0,
                      # Tier traffic (0 without a tier store, so the
                      # /stats schema is stable either way).
                      "restored_blocks": 0, "restored_tokens": 0,
                      "demotions": 0, "tier_corrupt_dropped": 0}
        # Lower tiers (prefix_tiers.TieredBlockStore) + the engine-owned
        # device→host block fetch used at demotion time. Both optional:
        # without them eviction discards payloads (the legacy behavior).
        self.tier_store = tier_store
        self.kv_fetch = kv_fetch

    # ------------------------------------------------------------------
    @staticmethod
    def _chain_keys(tokens: Sequence[int], block_size: int,
                    ns: Optional[str] = None) -> List[tuple]:
        """Content key for each full block of ``tokens``.

        ``ns`` namespaces the whole chain (multi-LoRA serving: a block's
        KV is a function of the *adapter* as well as the token chain, so
        the same prompt under different adapters must never alias). The
        namespace seeds the chain's root key; ``None``/"" produces the
        legacy keys byte-identical, so adapter-off engines and base
        requests share one namespace."""
        keys, prev = [], (() if not ns else ("adapter", ns))
        for i in range(len(tokens) // block_size):
            prev = (prev, tuple(tokens[i * block_size:(i + 1) * block_size]))
            keys.append(prev)
        return keys

    # ------------------------------------------------------------------
    def match_prefix(self, tokens: Sequence[int],
                     ns: Optional[str] = None) -> Tuple[List[int], int]:
        """Longest cached chain of full blocks covering a prefix of
        ``tokens``; at most ``len(tokens) - 1`` tokens match so prefill
        always has at least one token to process (its logits produce the
        next token). Pure lookup (no stats, no refcounts) — admission may
        be retried many times before it succeeds. ``ns`` is the adapter
        namespace (see :meth:`_chain_keys`). Returns
        (block_ids, n_tokens_covered).
        """
        usable = len(tokens) - 1
        blocks: List[int] = []
        for key in self._chain_keys(tokens[:usable] if usable > 0 else [],
                                    self.block_size, ns):
            entry = self._by_key.get(key)
            if entry is None:
                break
            blocks.append(entry.block)
        return blocks, len(blocks) * self.block_size

    def match_tiers(self, tokens: Sequence[int], start_block: int,
                    ns: Optional[str] = None) -> List[tuple]:
        """Continue a :meth:`match_prefix` chain into the lower tiers:
        chain keys for blocks ``start_block, start_block+1, ...`` that the
        tier store *indexes* (a disk entry may still fail verification at
        fetch time). Pure index lookup, no payload I/O."""
        if self.tier_store is None:
            return []
        usable = len(tokens) - 1
        keys = self._chain_keys(tokens[:usable] if usable > 0 else [],
                                self.block_size, ns)
        out: List[tuple] = []
        for key in keys[start_block:]:
            if self.tier_store.tier_of(key) is None:
                break
            out.append(key)
        return out

    def fetch_restore(self, key: tuple):
        """Pop ``key``'s payload from the tiers for promotion to HBM.

        Returns ``(payload, tier)`` or ``(None, None)`` — a corrupt disk
        block was quarantined by the store and reads as a miss here."""
        if self.tier_store is None:
            return None, None
        before = self.tier_store.stats["corrupt_dropped"]
        payload, tier = self.tier_store.fetch(key)
        dropped = self.tier_store.stats["corrupt_dropped"] - before
        if dropped:
            self.stats["tier_corrupt_dropped"] += dropped
            misses_total.labels(tier="disk").inc(dropped)
        if payload is not None:
            promotions_total.labels(tier=tier).inc()
        return payload, tier

    def register_restored(self, key: tuple, block: int) -> None:
        """Adopt a tier-restored block into the HBM cache, already pinned
        (refcount 1) for the admitting sequence — the engine has scattered
        the payload into physical ``block`` before any program reads it."""
        e = _Entry(block, key)
        e.refcount = 1
        self._by_key[key] = e
        self._by_block[block] = e
        self.stats["restored_blocks"] += 1
        self.stats["restored_tokens"] += self.block_size
        self._set_block_gauges()

    def record_hit(self, block_ids: List[int]) -> None:
        """Count a *successful* admission's reuse (an admission may retry
        acquire/release many times while head-of-line blocked)."""
        if block_ids:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(block_ids) * self.block_size

    def record_admission(self, hbm_blocks: List[int],
                         restored_by_tier: Dict[str, int]) -> None:
        """Per-tier hit/miss accounting for one *successful* admission
        (counted once, after allocation succeeded — retries while
        head-of-line blocked don't inflate the series)."""
        self.record_hit(hbm_blocks)
        if hbm_blocks:
            hits_total.labels(tier="hbm").inc()
        else:
            misses_total.labels(tier="hbm").inc()
        if self.tier_store is not None:
            for tier in ("host", "disk"):
                n = restored_by_tier.get(tier, 0)
                if n > 0:
                    hits_total.labels(tier=tier).inc()
                elif not hbm_blocks:
                    # Tier probed (the HBM chain broke at block 0) and
                    # found nothing: a real lower-tier miss. A chain fully
                    # covered by upper levels is not a miss down here.
                    misses_total.labels(tier=tier).inc()

    def acquire(self, block_ids: List[int]) -> None:
        """Take a reference on matched blocks (pins them against eviction).

        Call before allocating the suffix, undo with :meth:`release` if
        that allocation fails. Raises ``ValueError`` if a block is no
        longer cached (matched, then evicted before the acquire — only
        possible if a caller breaks the match→acquire atomicity contract
        by allocating in between)."""
        for i, b in enumerate(block_ids):
            entry = self._by_block.get(b)
            if entry is None:
                # Undo the refs already taken so the failed acquire is
                # all-or-nothing, like BlockManager.free.
                self.release(block_ids[:i])
                raise ValueError(
                    f"acquire of block {b} which is not cached (evicted "
                    "between match_prefix and acquire? callers must not "
                    "allocate between the two)")
            entry.refcount += 1
            self._lru.pop(b, None)

    def release(self, block_ids: List[int]) -> None:
        """Drop references taken by :meth:`acquire` (blocks stay cached).
        Raises ``ValueError`` on a release without a matching acquire —
        a silent refcount underflow would strand the block outside the
        LRU (unevictable) or let a shared block be evicted under a live
        sequence."""
        for b in block_ids:
            entry = self._by_block.get(b)
            if entry is None:
                raise ValueError(f"release of block {b} which is not cached")
            if entry.refcount <= 0:
                raise ValueError(
                    f"release of block {b} without a matching acquire "
                    "(refcount would go negative)")
            entry.refcount -= 1
            if entry.refcount == 0:
                self._lru[b] = entry
                self._lru.move_to_end(b)

    # ------------------------------------------------------------------
    def allocate(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` fresh blocks, evicting LRU cached blocks if the
        pool is dry. Returns None when even eviction can't satisfy it."""
        if n == 0:
            return []
        while not self.bm.can_allocate(n):
            if not self._evict_one():
                return None
        return self.bm.allocate(n)

    def _evict_one(self) -> bool:
        if not self._lru:
            return False
        block, entry = self._lru.popitem(last=False)  # oldest
        del self._by_key[entry.key]
        del self._by_block[block]
        if self.tier_store is not None and self.kv_fetch is not None:
            # Demote instead of discard: fetch the block's KV device→host
            # (the engine's fetcher stages through pinned_host where the
            # backend has it) and hand it to the tier hierarchy. The
            # payload is read BEFORE the physical block returns to the
            # pool, so a later allocation can't overwrite it first.
            payload = self.kv_fetch(block)
            if payload is not None:
                tier = self.tier_store.put(entry.key, payload)
                if tier is not None:
                    self.stats["demotions"] += 1
                    demotions_total.labels(tier=tier).inc()
        self.bm.free([block])
        self.stats["evictions"] += 1
        evictions_total.labels(tier="hbm").inc()
        self._set_block_gauges()
        return True

    def _set_block_gauges(self) -> None:
        """Point-in-time per-tier block counts. With replicas each
        engine's allocator overwrites the shared gauge (last writer
        wins); the event counters above aggregate exactly."""
        blocks_gauge.labels(tier="hbm").set(len(self._by_block))
        if self.tier_store is not None:
            blocks_gauge.labels(tier="host").set(
                self.tier_store.num_host_blocks)
            blocks_gauge.labels(tier="disk").set(
                self.tier_store.num_disk_blocks)

    # ------------------------------------------------------------------
    def release_sequence(self, tokens: Sequence[int],
                         blocks: List[int],
                         ns: Optional[str] = None) -> None:
        """Return a retiring sequence's blocks.

        Full blocks are registered for reuse (or deduplicated against an
        existing registration); partial/extra blocks go straight back to
        the allocator. ``blocks[i]`` must hold tokens
        ``tokens[i*bs:(i+1)*bs]`` — computed under the same ``ns`` the
        sequence matched with, or cross-adapter aliasing serves one
        adapter's KV to another.
        """
        keys = self._chain_keys(tokens, self.block_size, ns)
        for i, block in enumerate(blocks):
            entry = self._by_block.get(block)
            if entry is not None:
                # A block we were sharing: drop our reference.
                self.release([block])
                continue
            if i < len(keys):
                key = keys[i]
                if key in self._by_key:
                    # Same content already cached under another block
                    # (two requests prefilling the same prompt
                    # concurrently): keep the registered one, free ours.
                    self.bm.free([block])
                    continue
                e = _Entry(block, key)
                self._by_key[key] = e
                self._by_block[block] = e
                self._lru[block] = e
            else:
                self.bm.free([block])
        self._set_block_gauges()

    # ------------------------------------------------------------------
    @property
    def num_cached_blocks(self) -> int:
        return len(self._by_block)

    @property
    def num_free(self) -> int:
        """Free now, without eviction (see also :meth:`num_reclaimable`)."""
        return self.bm.num_free

    @property
    def num_reclaimable(self) -> int:
        return len(self._lru)
