"""Automatic prefix caching for the paged KV cache.

vLLM-style: a *full* KV block's contents are a pure function of the token
chain that produced it (same model, same params), so full blocks are
registered in a content-addressed table and reused across requests that
share a prompt prefix — chat system prompts, few-shot preambles, and
preempted-then-readmitted sequences prefill only their novel suffix.

Design:

* Keys are exact: ``key_i = (key_{i-1}, tokens_of_block_i)`` — no hash
  collisions, verification-free reuse.
* Ref-counted sharing: a cached block may back any number of active
  sequences; it is only evictable at refcount 0.
* Eviction is lazy LRU: unreferenced cached blocks stay registered (and
  allocated in the :class:`BlockManager` pool) until the pool runs dry,
  then the least-recently-used are freed back to the allocator — O(1)
  per eviction via an insertion-ordered dict of refcount-0 entries.
* Only *full* blocks are ever cached. The partial tail block of a
  sequence is exclusively owned and freed normally, so decode writes
  never mutate shared state.

Engine contract: ``match_prefix`` is a pure lookup; call :meth:`acquire`
*before* allocating the suffix blocks (so the matched blocks can't be
evicted to satisfy that very allocation) and :meth:`release` to undo on
allocation failure.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

from dlti_tpu.serving.block_manager import BlockManager


class _Entry:
    __slots__ = ("block", "key", "refcount")

    def __init__(self, block: int, key: tuple):
        self.block = block
        self.key = key
        self.refcount = 0


class PrefixCachingAllocator:
    """Wraps a :class:`BlockManager` with content-addressed block reuse.

    All engine allocation/free traffic must flow through this object so
    refcounts stay consistent.
    """

    def __init__(self, block_manager: BlockManager):
        self.bm = block_manager
        self.block_size = block_manager.block_size
        self._by_key: Dict[tuple, _Entry] = {}
        self._by_block: Dict[int, _Entry] = {}
        # refcount-0 entries in LRU order (oldest first) — the evictables.
        self._lru: "collections.OrderedDict[int, _Entry]" = collections.OrderedDict()
        self.stats = {"hits": 0, "hit_tokens": 0, "evictions": 0}

    # ------------------------------------------------------------------
    @staticmethod
    def _chain_keys(tokens: Sequence[int], block_size: int) -> List[tuple]:
        """Content key for each full block of ``tokens``."""
        keys, prev = [], ()
        for i in range(len(tokens) // block_size):
            prev = (prev, tuple(tokens[i * block_size:(i + 1) * block_size]))
            keys.append(prev)
        return keys

    # ------------------------------------------------------------------
    def match_prefix(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached chain of full blocks covering a prefix of
        ``tokens``; at most ``len(tokens) - 1`` tokens match so prefill
        always has at least one token to process (its logits produce the
        next token). Pure lookup (no stats, no refcounts) — admission may
        be retried many times before it succeeds. Returns
        (block_ids, n_tokens_covered).
        """
        usable = len(tokens) - 1
        blocks: List[int] = []
        for key in self._chain_keys(tokens[:usable] if usable > 0 else [],
                                    self.block_size):
            entry = self._by_key.get(key)
            if entry is None:
                break
            blocks.append(entry.block)
        return blocks, len(blocks) * self.block_size

    def record_hit(self, block_ids: List[int]) -> None:
        """Count a *successful* admission's reuse (an admission may retry
        acquire/release many times while head-of-line blocked)."""
        if block_ids:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(block_ids) * self.block_size

    def acquire(self, block_ids: List[int]) -> None:
        """Take a reference on matched blocks (pins them against eviction).

        Call before allocating the suffix, undo with :meth:`release` if
        that allocation fails.
        """
        for b in block_ids:
            entry = self._by_block[b]
            entry.refcount += 1
            self._lru.pop(b, None)

    def release(self, block_ids: List[int]) -> None:
        """Drop references taken by :meth:`acquire` (blocks stay cached)."""
        for b in block_ids:
            entry = self._by_block[b]
            entry.refcount -= 1
            if entry.refcount == 0:
                self._lru[b] = entry
                self._lru.move_to_end(b)

    # ------------------------------------------------------------------
    def allocate(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` fresh blocks, evicting LRU cached blocks if the
        pool is dry. Returns None when even eviction can't satisfy it."""
        if n == 0:
            return []
        while not self.bm.can_allocate(n):
            if not self._evict_one():
                return None
        return self.bm.allocate(n)

    def _evict_one(self) -> bool:
        if not self._lru:
            return False
        block, entry = self._lru.popitem(last=False)  # oldest
        del self._by_key[entry.key]
        del self._by_block[block]
        self.bm.free([block])
        self.stats["evictions"] += 1
        return True

    # ------------------------------------------------------------------
    def release_sequence(self, tokens: Sequence[int],
                         blocks: List[int]) -> None:
        """Return a retiring sequence's blocks.

        Full blocks are registered for reuse (or deduplicated against an
        existing registration); partial/extra blocks go straight back to
        the allocator. ``blocks[i]`` must hold tokens
        ``tokens[i*bs:(i+1)*bs]``.
        """
        keys = self._chain_keys(tokens, self.block_size)
        for i, block in enumerate(blocks):
            entry = self._by_block.get(block)
            if entry is not None:
                # A block we were sharing: drop our reference.
                self.release([block])
                continue
            if i < len(keys):
                key = keys[i]
                if key in self._by_key:
                    # Same content already cached under another block
                    # (two requests prefilling the same prompt
                    # concurrently): keep the registered one, free ours.
                    self.bm.free([block])
                    continue
                e = _Entry(block, key)
                self._by_key[key] = e
                self._by_block[block] = e
                self._lru[block] = e
            else:
                self.bm.free([block])

    # ------------------------------------------------------------------
    @property
    def num_cached_blocks(self) -> int:
        return len(self._by_block)

    @property
    def num_free(self) -> int:
        """Free now, without eviction (see also :meth:`num_reclaimable`)."""
        return self.bm.num_free

    @property
    def num_reclaimable(self) -> int:
        return len(self._lru)
