"""Distributed runtime: mesh, sharding presets (ZeRO/TP/SP), multi-host init.

The TPU-native replacement for the reference's DeepSpeed/NCCL stack
(SURVEY.md §2d): ``jax.sharding.Mesh`` over ICI/DCN with GSPMD-inserted
collectives instead of NCCL all-reduce/all-gather/reduce-scatter, and
``jax.distributed.initialize`` instead of torchrun/deepspeed launchers.
"""

from dlti_tpu.parallel.mesh import MESH_AXES, build_mesh  # noqa: F401
from dlti_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_local,
)
from dlti_tpu.parallel.sharding import (  # noqa: F401
    batch_pspec,
    make_global_batch,
    make_sharded_train_step,
    opt_state_shardings,
    param_pspec,
    param_shardings,
    shard_train_state,
)
