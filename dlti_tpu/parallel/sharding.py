"""Sharding rules: ZeRO stages + TP + SP as ``NamedSharding`` presets.

The DeepSpeed ZeRO engine (reference ``configs/ds_config_zero{1,2,3}.json``)
re-expressed in the XLA/GSPMD model (SURVEY.md §2b):

* **ZeRO-1** — params replicated; *optimizer state* sharded over ``data``.
  GSPMD then all-gathers the sharded AdamW update into the replicated params
  (the analog of ``allgather_partitions``, ``ds_config_zero1.json:36``).
* **ZeRO-2** — as ZeRO-1, plus gradients constrained to the optimizer-state
  sharding before the update, forcing a reduce-scatter instead of all-reduce
  (the analog of ``reduce_scatter: true``, ``ds_config_zero1.json:40``).
* **ZeRO-3** — parameters themselves sharded over ``fsdp``; XLA all-gathers
  weights per-layer inside the step and re-shards after use (FSDP). Host
  offload of params/optimizer is a separate memory-kind option
  (``ds_config_zero3.json:19-27`` parity).
* **TP** — attention heads + MLP hidden sharded over ``tensor``; the
  all-reduce after o_proj/down_proj is inserted by GSPMD.
* **SP** — batch also sharded over ``sequence`` on the length dim for ring
  attention (see ``dlti_tpu.parallel.ring_attention``).

Rules are *path + shape* based over the model's deterministic param naming
(``q_proj/kernel``: (in, out), etc.) rather than linen metadata — explicit,
inspectable, and independent of module internals.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlti_tpu.config import Config, ZeROStage
from dlti_tpu.training.state import TrainState

# ----------------------------------------------------------------------
# Tensor-parallel rules: param-name regex -> (dim sharded by 'tensor')
# Kernels are (in_features, out_features); None = no TP for that param.
# ----------------------------------------------------------------------
_TP_RULES = [
    (r".*(q_proj|k_proj|v_proj)/kernel$", 1),   # column-parallel (heads)
    (r".*(q_proj|k_proj|v_proj)/lora_b$", 1),   # lora_b out dim follows base
    (r".*o_proj/kernel$", 0),                    # row-parallel
    (r".*o_proj/lora_a$", 0),                    # lora_a in dim follows base
    (r".*(gate_proj|up_proj)/kernel$", 1),       # column-parallel (mlp hidden)
    (r".*(gate_proj|up_proj)/lora_b$", 1),
    (r".*down_proj/kernel$", 0),                 # row-parallel
    (r".*down_proj/lora_a$", 0),
    (r".*embed_tokens$", 0),                     # shard vocab rows
    (r".*lm_head$", 1),                          # shard vocab cols
    (r".*mlp/(w1|w3)$", 2),                      # expert ffn hidden (E,h,m)
    (r".*mlp/w2$", 1),                           # (E,m,h) row-parallel
]

# Expert-parallel rule: stacked expert weights shard dim 0 over 'expert'.
_EP_PATTERN = re.compile(r".*mlp/(w1|w2|w3)$")

# Don't FSDP-shard tiny params (norm scales, LoRA factors with dim < 1024):
# the all-gather latency outweighs memory savings. Shared by the flat and
# pipeline param-sharding rules; tests monkeypatch it to exercise FSDP
# placement on tiny models.
_MIN_FSDP_DIM = 1024


def _path_str(path: tuple) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif isinstance(p, tuple):
            parts.extend(str(q) for q in p)
        else:
            parts.append(str(p))
    return "/".join(parts)


def _tp_dim(path_s: str) -> Optional[int]:
    for pattern, dim in _TP_RULES:
        if re.match(pattern, path_s):
            return dim
    return None


def _largest_divisible_dim(shape: tuple, size: int, taken=()) -> Optional[int]:
    """Pick the largest dim divisible by ``size`` (excluding ``taken`` dims)."""
    if taken is None or isinstance(taken, int):
        taken = (taken,)
    best, best_len = None, 0
    for d, n in enumerate(shape):
        if d in taken:
            continue
        if n % size == 0 and n > best_len:
            best, best_len = d, n
    return best


def _quant_normalized_path(path_s: str, value: Any) -> str:
    """Alias a quant-node leaf ("{kernel}/q" or "{kernel}/scale") to its
    kernel's own path so the TP rules match quantized trees.

    "q" keeps the kernel's rank and sharding; "scale" has size 1 on the
    contraction dim, so divisibility checks at the call sites
    automatically replicate it for row-parallel kernels and shard it with
    the output channels for column-parallel ones. Gated on the quant-node
    layout so ordinary leaves that happen to be *named* scale (RMSNorm's
    param) are never aliased to their parent path.
    """
    if path_s.endswith("/q") and value.dtype == jnp.int8:
        return path_s[:-2]
    if path_s.endswith("/scale") and path_s.rsplit("/", 2)[-2] in (
            "kernel", "embed_tokens", "lm_head", "w1", "w2", "w3"):
        return path_s.rsplit("/", 1)[0]
    return path_s


def strategy_axes(path_s: str, shape: tuple, *, ep: int = 1, tp: int = 1,
                  fsdp: int = 1, dim_shift: int = 0,
                  taken: tuple = ()) -> dict:
    """THE shared EP/TP/FSDP placement rule for one (quant-normalized)
    param leaf: returns ``{dim: axis_name}``.

    ``dim_shift`` relocates the flat rules for stacked pipeline layouts
    (a leading layer dim shifts every flat dim by +1); ``taken`` marks
    dims already claimed (e.g. the stacked layout's 'pipe' dim 0) that
    FSDP must not grab. Both the flat ``param_pspec`` and the pipeline's
    ``pipeline_param_shardings`` call this one function, so the flat and
    pipelined layouts of a given strategy cannot drift apart.
    """
    out: dict = {}
    ep_d = None
    if (ep > 1 and _EP_PATTERN.match(path_s) and dim_shift < len(shape)
            and shape[dim_shift] % ep == 0):
        ep_d = dim_shift  # flat expert dim 0, shifted for stacked layouts
        out[ep_d] = "expert"
    tp_d = None
    if tp > 1:
        d = _tp_dim(path_s)
        if (d is not None and d + dim_shift < len(shape)
                and d + dim_shift != ep_d
                and shape[d + dim_shift] % tp == 0):
            tp_d = d + dim_shift
            out[tp_d] = "tensor"
    if fsdp > 1:
        d = _largest_divisible_dim(shape, fsdp, taken=taken + (tp_d, ep_d))
        if d is not None and shape[d] >= _MIN_FSDP_DIM:
            out[d] = "fsdp"
    return out


def param_pspec(path: tuple, value: Any, cfg: Config, mesh: Mesh) -> P:
    """PartitionSpec for one param leaf under the configured strategy."""
    shape = value.shape
    if len(shape) == 0:
        return P()
    # Weight-only int8 trees (serving) wrap each quantized kernel as
    # {"q": int8, "scale": fp32} — rules match on the kernel's own path.
    path_s = _quant_normalized_path(_path_str(path), value)
    spec: list = [None] * len(shape)
    fsdp_size = (mesh.shape["fsdp"]
                 if cfg.parallel.zero_stage == ZeROStage.ZERO3 else 1)
    for d, axis in strategy_axes(path_s, shape,
                                 ep=mesh.shape.get("expert", 1),
                                 tp=mesh.shape["tensor"],
                                 fsdp=fsdp_size).items():
        spec[d] = axis
    return P(*spec)


def _zero_opt_leaf_pspec(shape: tuple, axis: str, size: int) -> P:
    """Shard an optimizer-state leaf (ZeRO-1/2): largest divisible dim."""
    if len(shape) == 0 or size <= 1:
        return P()
    d = _largest_divisible_dim(shape, size)
    if d is None:
        return P()
    spec: list = [None] * len(shape)
    spec[d] = axis
    return P(*spec)


def _host_memory_kind(mesh: Mesh) -> Optional[str]:
    """"pinned_host" when the backend exposes it, else None (no offload)."""
    try:
        kinds = {m.kind for m in mesh.devices.flat[0].addressable_memories()}
        return "pinned_host" if "pinned_host" in kinds else None
    except Exception:
        return None


def param_shardings(params: Any, cfg: Config, mesh: Mesh) -> Any:
    """Pytree of NamedShardings for the full param tree.

    With ``offload_params`` (ZeRO-3 CPU-offload parity,
    ``ds_config_zero3.json:24-27``) the frozen base params live in pinned
    host memory; ``make_sharded_train_step`` streams them into the step —
    as in-program host operands when the runtime supports it, else via
    boundary transfers. Trainable (LoRA) leaves always stay on device —
    they are updated every step.
    """
    host_kind = None
    if cfg.parallel.offload_params:
        if not cfg.lora.enabled:
            raise ValueError(
                "offload_params currently requires LoRA (it offloads the "
                "frozen base params; a full fine-tune has none)")
        host_kind = _host_memory_kind(mesh)

    def leaf(path, v):
        path_s = _path_str(path)
        kind = host_kind
        if kind is not None and ("lora_a" in path_s or "lora_b" in path_s):
            kind = None  # trainable leaves stay in HBM
        return NamedSharding(mesh, param_pspec(path, v, cfg, mesh),
                             memory_kind=kind)

    return jax.tree_util.tree_map_with_path(leaf, params)


def opt_state_shardings(opt_state: Any, cfg: Config, mesh: Mesh) -> Any:
    """Shardings for optimizer state (ZeRO-1/2/3 semantics).

    Shape-based: each array leaf is sharded on its largest divisible dim —
    over ``data`` for ZeRO-1/2, over ``fsdp`` for ZeRO-3; replicated for the
    baseline (the reference keeps the full optimizer on every rank). Scalars
    (step counts) are replicated.
    """
    stage = cfg.parallel.zero_stage
    if stage in (ZeROStage.ZERO1, ZeROStage.ZERO2):
        axis, size = "data", mesh.shape["data"]
    elif stage == ZeROStage.ZERO3:
        axis, size = "fsdp", mesh.shape["fsdp"]
    else:
        axis, size = "data", 1

    # ZeRO-3 CPU-offload parity (configs/ds_config_zero3.json:19-23): place
    # optimizer state in host memory; XLA streams it in for the update.
    memory_kind = None
    if cfg.parallel.offload_optimizer:
        memory_kind = _host_memory_kind(mesh)

    def leaf(v):
        if not hasattr(v, "shape"):
            return NamedSharding(mesh, P())
        # Scalars (step counts) stay on device: offloading them buys nothing
        # and scalar host-placement trips the SPMD partitioner.
        kind = memory_kind if len(v.shape) >= 1 else None
        return NamedSharding(
            mesh, _zero_opt_leaf_pspec(v.shape, axis, size), memory_kind=kind
        )

    return jax.tree_util.tree_map(leaf, opt_state)


def batch_pspec(cfg: Config) -> P:
    """Batch layout for (accum, micro_bs, seq): batch over data+fsdp,
    sequence over the SP axis."""
    seq_axis = "sequence" if cfg.parallel.sequence > 1 else None
    return P(None, ("data", "fsdp"), seq_axis)


def make_global_batch(batch: dict, cfg: Config, mesh: Mesh) -> dict:
    """Assemble per-host numpy batches into global jax.Arrays.

    On a multi-host pod each process holds only its slice of the global
    batch (``TokenBatchDataset`` shards rows per host); jit with global
    in_shardings requires global arrays. Single-process: pass through.
    """
    if jax.process_count() == 1:
        return batch
    sharding = NamedSharding(mesh, batch_pspec(cfg))
    return {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in batch.items()
    }


def state_shardings(state: TrainState, cfg: Config, mesh: Mesh) -> TrainState:
    """A TrainState-shaped pytree of NamedShardings."""
    p_sh = param_shardings(state.params, cfg, mesh)
    o_sh = opt_state_shardings(state.opt_state, cfg, mesh)
    repl = NamedSharding(mesh, P())
    scaler_sh = (jax.tree_util.tree_map(lambda _: repl, state.scaler)
                 if state.scaler is not None else None)
    return state.replace(
        step=repl, params=p_sh, opt_state=o_sh, scaler=scaler_sh
    )


def place_on_mesh(x, s):
    """Place one host-resident leaf onto a mesh sharding.

    Single-process: plain ``device_put``. Multi-process: assemble the
    global array from this process's local shards
    (``make_array_from_callback`` — the checkpoint store's restore
    placement) instead of ``device_put``, whose uncommitted-array path
    broadcasts every full value through ``multihost_utils.assert_equal``
    — hundreds of redundant gloo collectives for a replicated-init state
    (every process computed the identical value from the same seed), and
    on this image's CPU gloo they desynchronize and crash the pairs.
    """
    if not hasattr(x, "shape"):
        return x
    if jax.process_count() > 1:
        host = np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, s, lambda idx: host[idx])
    return jax.device_put(x, s)


def launder_transfer_created(tree):
    """Multi-process placement products must be laundered before they can
    be DONATED into a compiled step: on this image's CPU jaxlib, donating
    a transfer-created array (``make_array_from_callback`` over host
    numpy) corrupts the process heap — the same root cause the
    checkpoint store's restore path works around (``store._launder``,
    where the full forensics live). Single-process trees pass through
    untouched (their leaves are executable outputs already)."""
    if jax.process_count() <= 1:
        return tree
    from dlti_tpu.checkpoint.store import _launder

    return _launder(tree)


def shard_train_state(state: TrainState, cfg: Config, mesh: Mesh) -> TrainState:
    """Place an (unsharded, host-resident) TrainState onto the mesh."""
    sh = state_shardings(state, cfg, mesh)
    return launder_transfer_created(
        jax.tree_util.tree_map(place_on_mesh, state, sh))


def make_sharded_train_step(
    model,
    state: TrainState,
    cfg: Config,
    mesh: Mesh,
    *,
    accum_steps: int = 1,
    donate: bool = True,
) -> Callable:
    """Jit the train step over the mesh with explicit in/out shardings.

    GSPMD inserts the ZeRO/TP collectives; XLA's latency-hiding scheduler
    overlaps them with compute (the analog of ``overlap_comm: true``,
    ``ds_config_zero1.json:38``).
    """
    from dlti_tpu.training.step import make_train_step

    if cfg.parallel.pipe > 1:
        raise ValueError(
            "make_sharded_train_step does not implement pipeline "
            "parallelism; with parallel.pipe > 1 use "
            "dlti_tpu.parallel.pipeline.make_pipeline_train_step (the GPipe "
            "schedule) — running this step on a pipe mesh would silently "
            "replicate all work across the pipe axis"
        )
    dp = mesh.shape["data"] * mesh.shape["fsdp"]
    if cfg.train.micro_batch_size % dp != 0:
        raise ValueError(
            f"global micro_batch_size={cfg.train.micro_batch_size} must be "
            f"divisible by the batch-sharding extent data*fsdp={dp}"
        )

    st_sh = state_shardings(state, cfg, mesh)
    b_sh = NamedSharding(mesh, batch_pspec(cfg))
    rng_sh = NamedSharding(mesh, P())

    grad_constraint = None
    if cfg.parallel.zero_stage in (ZeROStage.ZERO2, ZeROStage.ZERO3):
        # ZeRO-2 semantics: pin accumulated grads to the optimizer-state
        # layout so XLA reduce-scatters instead of all-reducing.
        axis = "data" if cfg.parallel.zero_stage == ZeROStage.ZERO2 else "fsdp"
        size = mesh.shape[axis]

        def grad_constraint(grads):
            return jax.tree_util.tree_map(
                lambda g: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, _zero_opt_leaf_pspec(g.shape, axis, size))
                ),
                grads,
            )

    def activation_constraint(input_ids):
        return jax.lax.with_sharding_constraint(
            input_ids, NamedSharding(mesh, P(("data", "fsdp"),
                                             "sequence" if cfg.parallel.sequence > 1 else None))
        )

    if cfg.train.loss_chunk and cfg.parallel.sequence > 1:
        raise ValueError(
            "train.loss_chunk does not compose with sequence parallelism "
            "(the chunk reshape would regather the 'sequence'-sharded "
            "activations); set loss_chunk=0")
    step_fn = make_train_step(
        model,
        accum_steps=accum_steps,
        sharding_constraint=activation_constraint,
        grad_constraint=grad_constraint,
        fp16_scale_window=cfg.train.fp16_scale_window,
        fp16_min_scale=cfg.train.fp16_min_scale,
        fp16_hysteresis=cfg.train.fp16_hysteresis,
        loss_chunk=cfg.train.loss_chunk,
    )

    # Host offload (ds_config_zero3.json:19-27 parity): offloaded leaves
    # *rest* in pinned host memory (st_sh carries memory kinds).
    has_offload = any(
        getattr(s, "memory_kind", None) == "pinned_host"
        for s in jax.tree_util.tree_leaves(st_sh))
    st_sh_dev = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s.spec) if isinstance(s, NamedSharding) else s,
        st_sh)

    # Every batch field (input_ids/loss_mask/segment_ids/positions) shares
    # the (accum, batch, seq) layout; a prefix pytree applies b_sh to all.
    jitted = jax.jit(
        step_fn,
        in_shardings=(st_sh_dev, b_sh, rng_sh),
        out_shardings=(st_sh_dev, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )
    if not has_offload:
        return jitted

    frozen_offloaded = any(
        getattr(s, "memory_kind", None) == "pinned_host"
        for s in jax.tree_util.tree_leaves(st_sh.params))
    if frozen_offloaded and _supports_host_compute_inputs(mesh):
        # Per-layer streaming (the DeepSpeed per-layer paging analog,
        # ds_config_zero3.json:19-27): the frozen base params enter the
        # jitted program AS host-memory operands and are excluded from its
        # outputs, so XLA's latency-hiding scheduler streams each weight
        # HBM-ward at its use point inside the step and frees it after —
        # peak HBM holds the trainable/optimizer leaves plus the layers in
        # flight, never the whole frozen tree. Trainable leaves stay
        # device-resident across steps (no boundary transfers at all).
        return _make_streaming_offload_step(
            step_fn, cfg, mesh, st_sh, st_sh_dev, b_sh, rng_sh, donate)

    # Fallback (runtime without host-compute operands, or only the
    # *optimizer* is offloaded): step-boundary transfer via the ONE
    # shared wrapper (also the pipe path's offload mode) — HBM holds
    # offloaded tensors only for the duration of a step. The wrapper
    # derives shardings from ``state``'s actual placement, so it must be
    # the PLACED state (every caller passes the shard_train_state
    # output).
    return wrap_boundary_offload(jitted, state, mesh, cfg.lora.enabled)


def wrap_boundary_offload(step_fn, state, mesh: Mesh, lora_enabled: bool):
    """Step-boundary host-offload fallback for layouts that cannot
    stream in-step (the pipe path; flat layouts use
    ``make_sharded_train_step``'s own wrapper): derive host/device
    shardings from the PLACED state, move offloaded leaves HBM-ward for
    the step's duration, splice the still-valid host frozen-param copies
    back after (they never change — half the DMA traffic for LoRA).

    Returns ``step_fn`` unchanged when nothing actually rests in host
    memory (backend without pinned_host, or offload disabled): wrapping
    anyway would splice back frozen buffers the step's donation already
    invalidated ("Array has been deleted" on step 2).
    """
    from dlti_tpu.training.state import combine_params, partition_params

    def shardings(tree):
        return jax.tree_util.tree_map(
            lambda x: x.sharding if hasattr(x, "sharding") else x, tree)

    opt_host = shardings(state.opt_state)
    par_host = shardings(state.params)

    def on_host(tree):
        return any(getattr(s, "memory_kind", None) == "pinned_host"
                   for s in jax.tree_util.tree_leaves(tree)
                   if isinstance(s, NamedSharding))

    params_offloaded = on_host(par_host)
    if not params_offloaded and not on_host(opt_host):
        return step_fn

    def dev(tree):
        return jax.tree_util.tree_map(
            lambda s: (NamedSharding(mesh, s.spec)
                       if isinstance(s, NamedSharding) else s), tree)

    opt_dev, par_dev = dev(opt_host), dev(par_host)

    def wrapped(st, batch, rng):
        host_state = st
        st = st.replace(
            opt_state=jax.device_put(st.opt_state, opt_dev),
            params=jax.device_put(st.params, par_dev),
        )
        new_state, m = step_fn(st, batch, rng)
        new_params = new_state.params
        if params_offloaded:
            t_new, _ = partition_params(new_params, lora_enabled)
            _, f_host = partition_params(host_state.params, lora_enabled)
            new_params = combine_params(t_new, f_host)
        return new_state.replace(
            opt_state=jax.device_put(new_state.opt_state, opt_host),
            params=new_params,
        ), m

    if params_offloaded:
        # The device param shardings double as the eval-side shim input
        # (eval feeds params into the same pipe shard_map, which cannot
        # take pinned_host stage-sharded operands).
        wrapped.params_dev_shardings = par_dev
    return wrapped


_HOST_COMPUTE_PROBE_CACHE: dict = {}


def _supports_host_compute_inputs(mesh: Mesh) -> bool:
    """Probe: can a jitted program take pinned-host operands into device
    compute? (XLA host-memory-space operands; needed for in-step weight
    streaming; degrade to boundary transfers when absent.)

    Probes BOTH a replicated and a mesh-sharded host operand — the real
    frozen tree contains both kinds, and SPMD-partitioner support for the
    placement annotation has differed between them in past XLA versions.
    The answer is a property of the backend + mesh shape, so it is cached.
    """
    key = (jax.default_backend(), tuple(sorted(mesh.shape.items())))
    if key in _HOST_COMPUTE_PROBE_CACHE:
        return _HOST_COMPUTE_PROBE_CACHE[key]

    def probe(spec, rows) -> None:
        host = NamedSharding(mesh, spec, memory_kind="pinned_host")
        dev = NamedSharding(mesh, spec, memory_kind="device")
        x = jax.device_put(jnp.ones((rows, 16), jnp.float32), host)
        # The exact streaming pattern: host operand, explicit in-program
        # move to device space, then compute.
        f = jax.jit(lambda a: jax.device_put(a, dev) * 2.0,
                    in_shardings=host, out_shardings=NamedSharding(mesh, spec))
        jax.block_until_ready(f(x))

    try:
        probe(P(), 16)
        sharded_axes = [ax for ax, n in mesh.shape.items() if n > 1]
        if sharded_axes:
            ax = sharded_axes[0]
            # Rows sized to the axis so the shard is never ragged.
            probe(P(ax), 8 * mesh.shape[ax])
        ok = True
    except Exception:
        ok = False
    _HOST_COMPUTE_PROBE_CACHE[key] = ok
    return ok


def _make_streaming_offload_step(step_fn, cfg: Config, mesh: Mesh, st_sh,
                                 st_sh_dev, b_sh, rng_sh, donate: bool):
    """Build the in-step streaming wrapper: frozen params are host operands
    of the compiled program; outputs cover only the dynamic state."""
    from dlti_tpu.training.state import combine_params, partition_params

    lora = cfg.lora.enabled

    def split(tree_state):
        tr, fr = partition_params(tree_state.params, lora)
        return tree_state.replace(params=tr), fr

    dyn_sh, frozen_sh = split(st_sh)
    dyn_sh_dev, frozen_sh_dev = split(st_sh_dev)
    frozen_dev_kind = {
        k: NamedSharding(mesh, s.spec, memory_kind="device")
        for k, s in frozen_sh_dev.items()
    }

    def run(dyn, frozen, batch, rng):
        # Explicit per-leaf host->device moves: ops cannot mix memory
        # spaces, so each frozen weight gets a copy op the latency-hiding
        # scheduler places near (and overlaps with) its first use.
        frozen = {k: jax.device_put(v, frozen_dev_kind[k])
                  for k, v in frozen.items()}
        state = dyn.replace(params=combine_params(dyn.params, frozen))
        new_state, metrics = step_fn(state, batch, rng)
        t_new, _ = partition_params(new_state.params, lora)
        return new_state.replace(params=t_new), metrics

    jitted = jax.jit(
        run,
        # Frozen params enter in pinned host memory and are not outputs.
        # The dynamic part (trainable params + optimizer state) is
        # device-in/device-out: host-memory *outputs* are what the SPMD
        # partitioner cannot handle, so offloaded optimizer leaves rest on
        # host between steps via the boundary transfers below (tiny for a
        # LoRA run — the 14 GB frozen tree is what streams in-step).
        in_shardings=(dyn_sh_dev, frozen_sh, b_sh, rng_sh),
        out_shardings=(dyn_sh_dev, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )

    def step_streaming(state, batch, rng):
        dyn, frozen = split(state)
        dyn = jax.device_put(dyn, dyn_sh_dev)      # no-op unless opt offloaded
        new_dyn, metrics = jitted(dyn, frozen, batch, rng)
        new_dyn = jax.device_put(new_dyn, dyn_sh)  # opt leaves back to host
        # Reattach the untouched host-resident frozen arrays — no copies.
        return new_dyn.replace(
            params=combine_params(new_dyn.params, frozen)), metrics

    return step_streaming
