"""Device mesh construction.

Axes (SURVEY.md §2c build targets):

* ``data``     — replicated data parallelism + ZeRO-1/2 optimizer sharding
                 (reference: torchrun DP, ``train_deepspeed_zero1.py:10-12``)
* ``fsdp``     — parameter sharding, the ZeRO-3 equivalent
                 (reference: ``configs/ds_config_zero3.json:17``)
* ``tensor``   — tensor parallelism over ICI (reference claims TP only for
                 the vLLM leg, ``README.md:10``)
* ``sequence`` — context/sequence parallelism (ring attention) for
                 long-context training; the reference truncates to 512 and
                 has no SP (SURVEY.md §5.7) — first-class here.

On real pods ``mesh_utils.create_device_mesh`` lays axes out so that the
innermost (most communication-heavy) axes ride ICI. On CPU (tests) we fall
back to a plain reshape of ``jax.devices()``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from dlti_tpu.config import ParallelConfig

MESH_AXES = ("data", "fsdp", "tensor", "sequence", "pipe", "expert")


def build_mesh(cfg: ParallelConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build a 6-axis mesh (data, fsdp, tensor, sequence, pipe, expert)."""
    if devices is None:
        devices = jax.devices()
    shape = (cfg.data, cfg.fsdp, cfg.tensor, cfg.sequence, cfg.pipe,
             cfg.expert)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    if n < len(devices):
        # Single-process only: use the first n visible devices — the
        # `deepspeed --num_gpus=N` analog of an N-wide job on a larger host
        # (train.ipynb cells 5-33). Multi-process meshes must span every
        # process's local devices, so there the exact count is required.
        if jax.process_count() > 1:
            raise ValueError(
                f"mesh shape {shape} needs {n} devices but {len(devices)} are "
                f"visible across {jax.process_count()} processes; a "
                f"multi-process mesh must use all devices"
            )
        devices = list(devices)[:n]
    if devices[0].platform == "tpu":
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    else:
        dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous — replaces the reference's launcher-set
    MASTER_ADDR/LOCAL_RANK env contract (``train_deepspeed_zero1.py:120-121``,
    ``train.ipynb:640-647``). With no args, JAX auto-detects cluster env
    (GKE/GCE metadata, SLURM, or MEGASCALE vars)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
