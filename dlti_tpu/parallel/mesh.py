"""Device mesh construction.

Axes (SURVEY.md §2c build targets):

* ``data``     — replicated data parallelism + ZeRO-1/2 optimizer sharding
                 (reference: torchrun DP, ``train_deepspeed_zero1.py:10-12``)
* ``fsdp``     — parameter sharding, the ZeRO-3 equivalent
                 (reference: ``configs/ds_config_zero3.json:17``)
* ``tensor``   — tensor parallelism over ICI (reference claims TP only for
                 the vLLM leg, ``README.md:10``)
* ``sequence`` — context/sequence parallelism (ring attention) for
                 long-context training; the reference truncates to 512 and
                 has no SP (SURVEY.md §5.7) — first-class here.

On real pods ``mesh_utils.create_device_mesh`` lays axes out so that the
innermost (most communication-heavy) axes ride ICI. On CPU (tests) we fall
back to a plain reshape of ``jax.devices()``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from dlti_tpu.config import ParallelConfig

MESH_AXES = ("data", "fsdp", "tensor", "sequence", "pipe", "expert")


def build_mesh(cfg: ParallelConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build a 6-axis mesh (data, fsdp, tensor, sequence, pipe, expert)."""
    if devices is None:
        devices = jax.devices()
    shape = (cfg.data, cfg.fsdp, cfg.tensor, cfg.sequence, cfg.pipe,
             cfg.expert)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    if n < len(devices):
        # Single-process only: use the first n visible devices — the
        # `deepspeed --num_gpus=N` analog of an N-wide job on a larger host
        # (train.ipynb cells 5-33). Multi-process meshes must span every
        # process's local devices, so there the exact count is required.
        if jax.process_count() > 1:
            raise ValueError(
                f"mesh shape {shape} needs {n} devices but {len(devices)} are "
                f"visible across {jax.process_count()} processes; a "
                f"multi-process mesh must use all devices"
            )
        devices = list(devices)[:n]
    if devices[0].platform == "tpu":
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    else:
        dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def fit_parallel_to_devices(cfg: ParallelConfig,
                            n_devices: int) -> ParallelConfig:
    """Shrink the batch axes (``data``/``fsdp``) of a mesh config to fit
    ``n_devices`` — the mesh half of elastic reshape-on-failure: when a
    worker dies and the surviving world re-rendezvouses smaller, the
    model-parallel axes (tensor/sequence/pipe/expert) must keep their
    extent (the sharded program depends on them) while the batch extent
    absorbs the loss. No-op when the config already fits."""
    import dataclasses

    if cfg.num_devices <= n_devices:
        return cfg
    fixed = cfg.tensor * cfg.sequence * cfg.pipe * cfg.expert
    rows = n_devices // fixed
    if rows < 1:
        raise ValueError(
            f"cannot reshape mesh to {n_devices} devices: the "
            f"model-parallel extent tensor*sequence*pipe*expert={fixed} "
            "alone exceeds the surviving world")
    if cfg.data > 1 and cfg.fsdp > 1:
        raise ValueError(
            f"cannot reshape a mixed data={cfg.data} x fsdp={cfg.fsdp} "
            "mesh automatically; relaunch with explicit extents")
    if cfg.fsdp > 1:
        return dataclasses.replace(cfg, fsdp=rows)
    return dataclasses.replace(cfg, data=rows)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous — replaces the reference's launcher-set
    MASTER_ADDR/LOCAL_RANK env contract (``train_deepspeed_zero1.py:120-121``,
    ``train.ipynb:640-647``). With no args, JAX auto-detects cluster env
    (GKE/GCE metadata, SLURM, or MEGASCALE vars)."""
    import os

    if (os.environ.get("JAX_PLATFORMS") == "cpu"
            or getattr(jax.config, "jax_platforms", None) == "cpu"):
        # Multi-process CPU (the gloo test/dev path): this jax's CPU
        # client builds with NO cross-process collectives by default, and
        # every multi-process computation then fails with "Multiprocess
        # computations aren't implemented on the CPU backend". Select the
        # gloo TCP implementation before the backend initializes.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax without the flag: gloo was the default
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
