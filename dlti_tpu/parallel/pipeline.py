"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY.md §2c: "PP: No"); this
is the TPU-native extension: the transformer block stack is split into
``pipe`` contiguous stages, each device holds ``num_layers / pipe`` layers,
and microbatches flow through the stages with ``lax.ppermute`` moving
activations stage-to-stage over ICI — the collective-permute pipelining
pattern (scaling-book) rather than host-driven stage processes.

Layout: the per-layer param subtrees of the standard model tree
(``model.layers_{i}``) are stacked into one tree with a leading layer dim
(:func:`to_pipeline_params`), sharded over ``pipe``. Embeddings / final
norm / LM head are replicated and applied outside the pipelined region
(they are a few percent of FLOPs; sharding them rides the ``tensor`` axis
when combined with TP).

Schedule (plain GPipe): with ``P`` stages and ``M`` microbatches, run
``M + P - 1`` ticks; at tick ``t`` stage 0 ingests microbatch ``t`` (while
``t < M``), every stage applies its local layers, and activations
ppermute to the next stage. The last stage's outputs for ticks
``P-1 .. M+P-2`` are microbatch ``0 .. M-1``. Bubble fraction is
``(P-1)/(M+P-1)`` — pick ``M >= 4*P`` for >80% utilization.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlti_tpu.config import Config, LoRAConfig, ModelConfig
from dlti_tpu.models.llama import LlamaBlock, RMSNorm, _dtype, _remat_policy
from dlti_tpu.ops.rope import rope_frequencies


# ----------------------------------------------------------------------
# Param layout: standard tree <-> pipeline (stacked-layer) tree
# ----------------------------------------------------------------------

def to_pipeline_params(params: dict, num_layers: int) -> dict:
    """Standard param tree -> pipeline layout.

    ``model.layers_{i}`` subtrees stack into ``layers`` with a leading
    layer dim; embed/final-norm/lm-head stay as-is.
    """
    model = params["model"]
    layer_trees = [model[f"layers_{i}"] for i in range(num_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layer_trees)
    out = {
        "embed_tokens": model["embed_tokens"],
        "layers": stacked,
        "final_norm": model["final_norm"],
    }
    if "lm_head" in params:
        out["lm_head"] = params["lm_head"]
    return out


def from_pipeline_params(pparams: dict, num_layers: int) -> dict:
    """Inverse of :func:`to_pipeline_params`."""
    model: dict = {
        "embed_tokens": pparams["embed_tokens"],
        "final_norm": pparams["final_norm"],
    }
    for i in range(num_layers):
        model[f"layers_{i}"] = jax.tree_util.tree_map(
            lambda x: x[i], pparams["layers"])
    out = {"model": model}
    if "lm_head" in pparams:
        out["lm_head"] = pparams["lm_head"]
    return out


def pipeline_param_shardings(pparams: dict, mesh: Mesh) -> dict:
    """Stacked layers sharded over ``pipe`` on the layer dim; rest replicated.

    When the mesh also has ``tensor`` > 1 (PP x TP), each stacked leaf
    additionally shards over ``tensor`` on the same dim the training TP
    rules use (shifted +1 for the leading layer dim): stage-internal
    tensor parallelism. The ``tensor`` axis stays a GSPMD *auto* axis
    inside the pipeline's shard_map (see :func:`pipeline_forward`), so XLA
    partitions the block math and inserts the TP collectives.

    ``embed_tokens`` / ``lm_head`` follow the flat-TP vocab rules too
    (rows / cols over ``tensor``): the embed lookup and the (b, s, vocab)
    fp32 head einsum sit *outside* the pipe shard_map as ordinary GSPMD
    ops, so sharding the leaves is all it takes for XLA to partition the
    largest single matmul instead of replicating it per device (r04
    advisor finding).

    With ``fsdp`` > 1 (PP x ZeRO-3), each leaf additionally shards over
    ``fsdp`` on its largest remaining divisible dim (same rule + size
    floor as the flat ZeRO-3 path, ``sharding.param_pspec``). ``fsdp``
    rides as a GSPMD auto axis exactly like ``tensor``: XLA all-gathers a
    stage's layer shard at its use point inside the tick and
    reduce-scatters grads — per-stage FSDP, so a stage holds
    layers_per_stage/fsdp params at rest instead of a full layer shard.

    With ``expert`` > 1 (PP x EP), stacked MoE expert weights shard over
    ``expert`` on the expert dim (flat dim 0 -> stacked dim 1, the flat
    ``_EP_PATTERN`` rule shifted) — expert parallelism inside each
    pipeline stage, dispatch all-to-all inserted by GSPMD.
    """
    tp = mesh.shape.get("tensor", 1)
    fsdp = mesh.shape.get("fsdp", 1)
    ep = mesh.shape.get("expert", 1)

    def leaf(prefix, dim_shift, lead_axis):
        """One EP/TP/FSDP-rule lookup for both layouts: stacked layers
        (dim_shift=1 for the leading 'pipe'-sharded layer dim) and
        top-level leaves (dim_shift=0, path prefixed with the tree key so
        the flat rules match). Delegates to the ONE shared placement rule
        (``sharding.strategy_axes``) so the flat and pipelined layouts of
        a strategy cannot drift apart."""
        def f(path, v):
            from dlti_tpu.parallel.sharding import (
                _path_str, _quant_normalized_path, strategy_axes,
            )

            spec = [None] * v.ndim
            if lead_axis:
                spec[0] = lead_axis
            # int8 trees: alias {kernel}/q and {kernel}/scale to the
            # kernel's path so quantized weights shard too (scale's
            # size-1 contraction dim auto-replicates via the divisibility
            # checks inside strategy_axes).
            p = _quant_normalized_path(
                "/".join(x for x in (prefix, _path_str(path)) if x), v)
            for d, axis in strategy_axes(
                    p, v.shape, ep=ep, tp=tp, fsdp=fsdp,
                    dim_shift=dim_shift,
                    taken=(0,) if lead_axis else ()).items():
                spec[d] = axis
            return NamedSharding(mesh, P(*spec))
        return f

    return {
        k: jax.tree_util.tree_map_with_path(
            leaf("", 1, "pipe") if k == "layers" else leaf(k, 0, None), v)
        for k, v in pparams.items()
    }


# ----------------------------------------------------------------------
# Pipelined forward
# ----------------------------------------------------------------------

def pipeline_forward(
    pparams: dict,
    input_ids: jnp.ndarray,
    cfg: ModelConfig,
    mesh: Mesh,
    *,
    lora: Optional[LoRAConfig] = None,
    num_microbatches: int = 4,
    positions: Optional[jnp.ndarray] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    deterministic: bool = True,
    dropout_rng: Optional[jax.Array] = None,
    return_hidden: bool = False,
    token_mask: Optional[jnp.ndarray] = None,
    return_aux: bool = False,
) -> jnp.ndarray:
    """Run the full model with the block stack pipelined over ``pipe``.

    ``return_aux``: additionally return the per-microbatch router
    aux-loss sums, shape (num_microbatches,) — MoE models only.
    ``token_mask`` (b, s): keeps padding tokens out of expert capacity
    (packed batches derive it from ``segment_ids`` instead).

    ``input_ids``: (batch, seq); batch must divide by ``num_microbatches``.
    Returns float32 logits (batch, seq, vocab) — the same function as
    ``LlamaForCausalLM.apply`` on the equivalent unstacked params.
    """
    num_stages = mesh.shape["pipe"]
    if cfg.num_layers % num_stages != 0:
        raise ValueError(f"num_layers={cfg.num_layers} must divide into "
                         f"pipe={num_stages} stages")
    moe = cfg.num_experts > 0
    b, s = input_ids.shape
    if b % num_microbatches != 0:
        raise ValueError(f"batch={b} must divide by microbatches={num_microbatches}")
    mb = b // num_microbatches
    dtype = _dtype(cfg.dtype)

    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    # Cover the actual sequence even past the preset's design length
    # (same fix as models/llama.py: positions >= table length hit
    # jnp.take's NaN fill and training silently NaNs).
    table_len = max(cfg.max_seq_len, s)
    # Trace-time guard (ADVICE r05): apply_rope clip-gathers, so an
    # under-sized table would silently clamp angles — fail the trace here
    # where the max position (< s) is statically known.
    from dlti_tpu.ops.rope import assert_rope_table_covers

    assert_rope_table_covers(table_len, s, "pipeline forward")
    cos, sin = rope_frequencies(cfg.resolved_head_dim, table_len,
                                cfg.rope_theta)

    # Embed outside the pipelined region (replicated). int8 frozen-base
    # trees quantize the embedding too — gather int8 ROWS then scale
    # (models/llama.py's lookup path): only (b*s, hidden) expands, never
    # the whole (vocab, hidden) matrix in fp.
    from dlti_tpu.models.quantization import is_quant_node, maybe_dequantize

    emb = pparams["embed_tokens"]
    if is_quant_node(emb):
        x = emb["q"][input_ids].astype(dtype) * emb["scale"].astype(dtype)
    else:
        x = jnp.take(emb, input_ids, axis=0).astype(dtype)
    if cfg.embedding_scale:  # Gemma: embeddings scaled by sqrt(hidden)
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, dtype)
    x_mb = x.reshape(num_microbatches, mb, s, -1)
    pos_mb = positions.reshape(num_microbatches, mb, s)
    # PP x DP / PP x ZeRO-3: batch rows shard over 'data' and 'fsdp'
    # (both carry batch, as in the flat batch_pspec) as auto axes inside
    # the shard_map. PP x SP: the sequence dim additionally shards over
    # 'sequence' — inside the stages, ring_attention delegates to
    # reference_attention and GSPMD partitions it over the auto
    # 'sequence' axis (see ring_attention's nested-delegation comment).
    row_axes = tuple(a for a in ("data", "fsdp")
                     if mesh.shape.get(a, 1) > 1) or None
    seq_ax = "sequence" if mesh.shape.get("sequence", 1) > 1 else None
    if row_axes or seq_ax:
        # Keep each microbatch row-sharded. Without the constraint the
        # (b, s) -> (M, mb, s) reshape migrates the batch sharding onto
        # the microbatch index M, and the tick loop's x_mb[m] gathers.
        x_mb = jax.lax.with_sharding_constraint(
            x_mb, NamedSharding(mesh, P(None, row_axes, seq_ax, None)))
        pos_mb = jax.lax.with_sharding_constraint(
            pos_mb, NamedSharding(mesh, P(None, row_axes, seq_ax)))
    # Packed batches: segment ids travel with their microbatch so each
    # stage applies the same intra-doc attention mask the unpipelined
    # model would. A zero array means "one segment" (mask is a no-op) and
    # keeps the scanned stage body shape-stable either way.
    seg_mb = (segment_ids.reshape(num_microbatches, mb, s)
              if segment_ids is not None else None)
    if seg_mb is not None and (row_axes or seq_ax):
        # Same row-sharding pin as x_mb/pos_mb above: without it the
        # reshape migrates the batch sharding onto the microbatch index
        # and every tick's seg_mb[m] gathers across the batch axes.
        seg_mb = jax.lax.with_sharding_constraint(
            seg_mb, NamedSharding(mesh, P(None, row_axes, seq_ax)))

    # Pass the mesh: MoE's expert-dispatch constraint (moe.py
    # _expert_constraint) pins the (E, C, h) dispatched activations to
    # the 'expert' axis — legal inside the pipe shard_map because
    # 'expert' stays a GSPMD auto axis there, and a no-op on dense
    # models / expert==1 meshes. Without it, PP x EP would leave the
    # token->expert all-to-all placement to unpinned propagation.
    block = LlamaBlock(cfg, lora, mesh)

    layers_per_stage = cfg.num_layers // num_stages

    def apply_stage(layer_params, x, pos, seg, tm, rng):
        """Apply this stage's local layers (leading dim = layers/stage).

        Returns (x, aux_sum) — aux_sum is the stage's summed router
        aux losses (0 for dense models)."""
        def body(carry, layer_with_idx):
            h = carry
            one_layer, layer_idx = layer_with_idx
            # Distinct dropout mask per layer (the unpipelined model's
            # layers_{i} module paths fold distinct keys).
            rngs = ({"dropout": jax.random.fold_in(rng, layer_idx)}
                    if not deterministic else None)
            if moe:
                # Collect each MoE layer's sown load-balance loss.
                (out, _), variables = block.apply(
                    {"params": one_layer}, h, cos, sin, pos,
                    seg, None, deterministic, token_mask=tm, rngs=rngs,
                    mutable=["intermediates"])
                from dlti_tpu.models.moe import collect_aux_loss

                aux = collect_aux_loss(variables.get("intermediates", {}))
            else:
                out, _ = block.apply({"params": one_layer}, h, cos, sin, pos,
                                     seg, None, deterministic, rngs=rngs)
                aux = jnp.float32(0.0)
            return out, aux

        stride = cfg.remat_stride if cfg.remat else 0
        if cfg.remat and stride > 1 and layers_per_stage % stride == 0:
            # Selective remat under pipe (flat-path remat_stride parity):
            # scan over GROUPS of `stride` layers, rematting all but the
            # last in each group — every stride-th block keeps its
            # activations, trading ~1/stride of the backward recompute
            # for that fraction of saved activations per stage. Numerics
            # identical (remat changes only what the backward recomputes).
            fn = jax.checkpoint(body, policy=_remat_policy(cfg.remat_policy))

            def group_fn(carry, group):
                params_g, idx_g = group
                h = carry
                aux_sum = jnp.float32(0.0)
                for j in range(stride):  # static unroll within the group
                    layer_j = jax.tree_util.tree_map(
                        lambda v: v[j], params_g)
                    apply_j = body if j == stride - 1 else fn
                    h, aux = apply_j(h, (layer_j, idx_g[j]))
                    aux_sum = aux_sum + aux
                return h, aux_sum

            grouped = (
                jax.tree_util.tree_map(
                    lambda v: v.reshape(
                        (layers_per_stage // stride, stride) + v.shape[1:]),
                    layer_params),
                jnp.arange(layers_per_stage).reshape(-1, stride),
            )
            x, aux_groups = jax.lax.scan(group_fn, x, grouped)
            return x, jnp.sum(aux_groups)
        if cfg.remat:
            # Same policy table as the flat path (llama._remat_policy):
            # the int8/no-remat bench winner aside, 7B-class PP runs need
            # dots_saveable/save_attn_out to fit activations per stage.
            fn = jax.checkpoint(body, policy=_remat_policy(cfg.remat_policy))
        else:
            fn = body
        x, aux_layers = jax.lax.scan(
            fn, x, (layer_params, jnp.arange(layers_per_stage)))
        return x, jnp.sum(aux_layers)

    num_ticks = num_microbatches + num_stages - 1
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    @functools.partial(
        shard_map, mesh=mesh,
        # Only 'pipe' is manual: every other mesh axis (notably 'tensor')
        # stays a GSPMD auto axis, so stacked-layer leaves that carry a
        # 'tensor' sharding (pipeline_param_shardings under PP x TP) keep
        # it inside the body and XLA partitions the stage's block math +
        # inserts the row/column-parallel collectives.
        axis_names=frozenset({"pipe"}),
        in_specs=(jax.tree_util.tree_map(lambda _: P("pipe"), pparams["layers"]),
                  P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
        # check_vma stays ON for every composition (incl. PP x SP, whose
        # nested ring passes the checker via explicit pcasts in
        # ring_attention_local): disabling it makes the shard_map
        # transpose skip the psum for replicated inputs — gradients come
        # out silently wrong.
    )
    def run_pipeline(local_layers, x_mb, pos_mb, seg_mb, tm_mb, rng):
        # Inside: one pipeline stage per device along 'pipe'.
        stage = jax.lax.axis_index("pipe")
        # Initial carries must be device-varying for the scan's carry type
        # to be stable (they become varying after the first ppermute).
        buf = jax.lax.pcast(jnp.zeros_like(x_mb[0]), "pipe", to="varying")
        outputs = jax.lax.pcast(jnp.zeros_like(x_mb), "pipe", to="varying")
        aux_vec = jax.lax.pcast(
            jnp.zeros((num_microbatches,), jnp.float32), "pipe", to="varying")

        def tick(carry, t):
            buf, outputs, aux_vec = carry
            m_in = jnp.clip(t, 0, num_microbatches - 1)
            inp = jnp.where(stage == 0, x_mb[m_in], buf)
            # Positions for the microbatch this stage is processing at tick
            # t: stage k works on microbatch t - k.
            m_here = jnp.clip(t - stage, 0, num_microbatches - 1)
            pos = pos_mb[m_here]
            seg = seg_mb[m_here] if segment_ids is not None else None
            tm = tm_mb[m_here] if moe else None
            # Fold the stage in as well: stage k's layers are globally
            # layers k*K..(k+1)*K-1, so masks differ across stages too.
            out, aux = apply_stage(local_layers, inp, pos, seg, tm,
                                   jax.random.fold_in(
                                       jax.random.fold_in(rng, t), stage))
            # Edge ticks (pipeline fill/drain) recompute a clipped
            # microbatch; their aux must not double-count.
            valid = ((t - stage >= 0)
                     & (t - stage < num_microbatches)).astype(jnp.float32)
            aux_vec = aux_vec + jax.nn.one_hot(
                m_here, num_microbatches, dtype=jnp.float32) * aux * valid
            # Last stage finished microbatch t - (P-1) at this tick.
            m_out = t - (num_stages - 1)
            write = (stage == num_stages - 1) & (m_out >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(m_out, 0), 0)
            outputs = jnp.where(write, updated, outputs)
            buf = jax.lax.ppermute(out, "pipe", perm)
            return (buf, outputs, aux_vec), None

        (buf, outputs, aux_vec), _ = jax.lax.scan(
            tick, (buf, outputs, aux_vec), jnp.arange(num_ticks))
        # Only the last stage holds real outputs; broadcast to every stage
        # (psum over the one-hot mask — a pipe-axis all-reduce on ICI).
        # aux: every stage holds ITS layers' contribution — psum is the
        # sum over the whole layer stack.
        mask = (stage == num_stages - 1).astype(outputs.dtype)
        return (jax.lax.psum(outputs * mask, "pipe"),
                jax.lax.psum(aux_vec, "pipe"))

    rng_arg = (dropout_rng if dropout_rng is not None
               else jax.random.PRNGKey(0))  # unused when deterministic
    seg_arg = (seg_mb if seg_mb is not None
               else jnp.zeros((num_microbatches, mb, s), jnp.int32))
    if moe and token_mask is None and segment_ids is not None:
        token_mask = (segment_ids != 0).astype(jnp.int32)  # packed: 0 = pad
    tm_arg = (token_mask.reshape(num_microbatches, mb, s)
              if (moe and token_mask is not None)
              else jnp.ones((num_microbatches, mb, s), jnp.int32))
    if moe and token_mask is not None and (row_axes or seq_ax):
        # Same row-sharding pin as x_mb/pos_mb/seg_mb above: without it
        # the (b, s) -> (M, mb, s) reshape migrates the batch sharding
        # onto the microbatch index and every tick's tm_mb[m] gathers.
        tm_arg = jax.lax.with_sharding_constraint(
            tm_arg, NamedSharding(mesh, P(None, row_axes, seq_ax)))
    y, aux_vec = run_pipeline(pparams["layers"], x_mb, pos_mb, seg_arg,
                              tm_arg, rng_arg)
    y = y.reshape(b, s, -1)

    # Final norm + head outside the pipeline (replicated).
    norm = RMSNorm(cfg.rms_norm_eps, offset=cfg.rmsnorm_offset)
    y = norm.apply({"params": pparams["final_norm"]}, y)
    if return_hidden:
        # Sequence-chunked loss path: the caller applies the head per
        # chunk (pipeline_head_matrix) so full fp32 logits never sit in
        # HBM — the loss_chunk contract of training.step.
        return (y, aux_vec) if return_aux else y
    if cfg.tie_embeddings or "lm_head" not in pparams:
        # fp32 dequant for the tied head (llama.py head_matrix parity:
        # int8 -> fp32 directly, not via the lookup dtype).
        tied = maybe_dequantize(pparams["embed_tokens"], jnp.float32,
                                anchor=y)
        logits = jnp.einsum("bsh,vh->bsv", y.astype(jnp.float32),
                            jnp.asarray(tied, jnp.float32))
    else:
        lm_head = maybe_dequantize(pparams["lm_head"], y.dtype, anchor=y)
        logits = jnp.dot(y, lm_head.astype(y.dtype),
                         preferred_element_type=jnp.float32)
    logits = logits.astype(jnp.float32)
    return (logits, aux_vec) if return_aux else logits


def pipeline_head_matrix(pparams: dict, cfg: ModelConfig, anchor) -> jnp.ndarray:
    """The (hidden, vocab) head as an explicit matrix on pipeline-layout
    params — the input to ``chunked_causal_lm_loss``. Delegates to the
    ONE shared head contract (``models.llama.head_matrix_from_leaves``)
    so the flat and pipelined chunked paths cannot desynchronize."""
    from dlti_tpu.models.llama import head_matrix_from_leaves

    return head_matrix_from_leaves(
        pparams["embed_tokens"], pparams.get("lm_head"),
        cfg.tie_embeddings, anchor)


def to_pipeline_state(state, num_layers: int):
    """Convert a fresh TrainState to pipeline layout.

    Re-initializes optimizer state over the stacked trainable tree, so use
    at step 0 (converting mid-run would discard Adam moments).
    """
    from dlti_tpu.training.state import partition_params

    pparams = to_pipeline_params(state.params, num_layers)
    trainable, _ = partition_params(pparams, state.lora_enabled)
    return state.replace(params=pparams, opt_state=state.tx.init(trainable))


# ----------------------------------------------------------------------
# Pipelined train step
# ----------------------------------------------------------------------

def make_pipeline_train_step(
    cfg: Config,
    tx,
    mesh: Mesh,
    *,
    num_microbatches: int = 4,
) -> Callable:
    """Build ``step(state, batch, rng) -> (state, metrics)`` where
    ``state.params`` is in *pipeline layout* (see :func:`to_pipeline_params`).

    The loss/optimizer semantics match ``make_train_step`` (token-mean
    causal-LM loss, trainable-subset grads); grad accumulation happens
    through the microbatch schedule itself.
    """
    import optax

    from dlti_tpu.training.state import combine_params, partition_params
    from dlti_tpu.training.step import causal_lm_loss

    layers_per_stage = cfg.model.num_layers // mesh.shape["pipe"]
    if (cfg.model.remat and cfg.model.remat_stride > 1
            and layers_per_stage % cfg.model.remat_stride != 0):
        from dlti_tpu.utils.logging import get_logger

        # Selective remat scans layer GROUPS of `stride`; a stride that
        # does not divide the per-stage layer count cannot group evenly,
        # so every scanned layer remats (plain jax.checkpoint).
        get_logger().warning(
            "remat_stride=%d does not divide layers_per_stage=%d under "
            "pipe=%d; every block remats",
            cfg.model.remat_stride, layers_per_stage, mesh.shape["pipe"])

    lora = cfg.lora if cfg.lora.enabled else None

    loss_chunk = int(cfg.train.loss_chunk or 0)
    moe_coef = (cfg.model.router_aux_loss_coef
                if cfg.model.num_experts > 0 else 0.0)
    if loss_chunk and moe_coef:
        raise ValueError(
            "loss_chunk does not compose with MoE aux-loss collection; "
            "set train.loss_chunk=0 for MoE models")

    def loss_fn(trainable, frozen, batch, rng):
        pparams = combine_params(trainable, frozen)
        loss_mask = batch.get("loss_mask")
        # Unpacked MoE: loss_mask IS the padding mask — keep padding out
        # of expert capacity/aux stats (flat-step parity). Packed batches
        # derive the mask from segment_ids inside pipeline_forward.
        tm = (loss_mask if (moe_coef and loss_mask is not None
                            and batch.get("segment_ids") is None) else None)
        out = pipeline_forward(
            pparams, batch["input_ids"], cfg.model, mesh, lora=lora,
            num_microbatches=num_microbatches,
            positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"),
            deterministic=False, dropout_rng=rng,
            return_hidden=bool(loss_chunk),
            token_mask=tm, return_aux=bool(moe_coef),
        )
        aux_vec = None
        if moe_coef:
            out, aux_vec = out
        if loss_chunk:
            from dlti_tpu.training.step import chunked_causal_lm_loss

            loss_sum, n_tok = chunked_causal_lm_loss(
                out, pipeline_head_matrix(pparams, cfg.model, out),
                batch["input_ids"], loss_mask, loss_chunk)
        else:
            loss_sum, n_tok = causal_lm_loss(
                out, batch["input_ids"], loss_mask)
        n_tok = jnp.maximum(n_tok, 1.0)
        aux_weighted = jnp.float32(0.0)
        if moe_coef:
            # Flat-step parity: each microbatch's aux weighted by its own
            # token count, so the objective equals the grad-accum loop's
            # sum of (loss_sum_m + coef * aux_m * n_tok_m), all / n_tok.
            b, s = batch["input_ids"].shape
            mask = (loss_mask if loss_mask is not None
                    else jnp.ones((b, s), jnp.int32))
            # The flat step weights aux_m by the microbatch's CE token
            # count — the SHIFTED mask (targets are input_ids[:, 1:]).
            n_tok_m = jnp.sum(
                mask.reshape(num_microbatches, -1, s)[:, :, 1:]
                .astype(jnp.float32), axis=(1, 2))
            aux_weighted = jnp.sum(aux_vec * n_tok_m)
        objective = (loss_sum + moe_coef * aux_weighted) / n_tok
        ce_mean = loss_sum / n_tok
        return objective, (ce_mean, aux_weighted / n_tok, n_tok)

    # PP x ZeRO-2/3: pin trainable grads to the optimizer-state layout
    # (sharded over 'data' for ZeRO-2, 'fsdp' for ZeRO-3) so XLA
    # reduce-scatters instead of all-reducing — the same constraint the
    # flat path applies in make_sharded_train_step.
    zstage = int(cfg.parallel.zero_stage)
    if zstage >= 3 and mesh.shape.get("fsdp", 1) > 1:
        pin_axis, pin_size = "fsdp", mesh.shape["fsdp"]
    elif zstage == 2 and mesh.shape.get("data", 1) > 1:
        pin_axis, pin_size = "data", mesh.shape["data"]
    else:
        pin_axis, pin_size = None, 1
    use_grad_pin = pin_axis is not None

    def step(state, batch, rng):
        trainable, frozen = state.trainable_and_frozen()
        loss_scale = (state.scaler["scale"] if state.scaler is not None
                      else jnp.float32(1.0))

        def scaled_loss(trainable, frozen, batch, rng):
            objective, parts = loss_fn(trainable, frozen, batch, rng)
            return objective * loss_scale, parts

        (_, (ce_mean, aux_mean, n_tok)), grads = jax.value_and_grad(
            scaled_loss, has_aux=True)(trainable, frozen, batch, rng)
        if use_grad_pin:
            from jax.sharding import NamedSharding

            from dlti_tpu.parallel.sharding import _zero_opt_leaf_pspec

            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, _zero_opt_leaf_pspec(
                        g.shape, pin_axis, pin_size))), grads)
        grads = jax.tree_util.tree_map(lambda g: g / loss_scale, grads)
        updates, new_opt = state.tx.update(grads, state.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)
        grad_norm = optax.global_norm(grads)
        # Reported loss stays pure CE (aux separate), like the flat step.
        metrics = {"loss": ce_mean, "grad_norm": grad_norm,
                   "num_tokens": n_tok}
        if moe_coef:
            metrics["aux_loss"] = aux_mean
        new_scaler = state.scaler
        if state.scaler is not None:
            from dlti_tpu.training.step import apply_loss_scaler

            new_trainable, new_opt, new_scaler, extra = apply_loss_scaler(
                state.scaler, grad_norm, new_trainable, trainable,
                new_opt, state.opt_state, cfg.train.fp16_scale_window,
                cfg.train.fp16_min_scale, cfg.train.fp16_hysteresis)
            metrics.update(extra)
            metrics["nonfinite"] = extra["overflow"]
            metrics["skipped_update"] = extra["overflow"]
        else:
            # bf16 nonfinite gate — same skip semantics as the flat step.
            from dlti_tpu.training.step import guard_nonfinite_update

            new_trainable, new_opt, extra = guard_nonfinite_update(
                grad_norm, ce_mean, new_trainable, trainable,
                new_opt, state.opt_state)
            metrics.update(extra)
        return state.replace(
            step=state.step + 1,
            params=combine_params(new_trainable, frozen),
            opt_state=new_opt,
            scaler=new_scaler,
        ), metrics

    return jax.jit(step, donate_argnums=(0,))


def make_pipeline_eval_step(cfg: Config, mesh: Mesh) -> Callable:
    """``eval_step(state, batch) -> metrics`` on pipeline-layout params.

    Runs :func:`pipeline_forward` deterministically with a single
    microbatch (the full eval batch flows through the stages once; the
    (P-1)/P bubble is irrelevant at eval cadence) — the pipe-mesh analog
    of :func:`dlti_tpu.training.step.make_eval_step`.
    """
    from dlti_tpu.training.step import causal_lm_loss

    lora = cfg.lora if cfg.lora.enabled else None

    loss_chunk = int(cfg.train.loss_chunk or 0)

    def eval_step(state, batch):
        out = pipeline_forward(
            state.params, batch["input_ids"], cfg.model, mesh, lora=lora,
            num_microbatches=1, deterministic=True,
            positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"),
            return_hidden=bool(loss_chunk),
        )
        if loss_chunk:
            # Mirror the train step: a run whose HBM budget depends on
            # loss_chunk must not OOM at its first periodic eval.
            from dlti_tpu.training.step import chunked_causal_lm_loss

            loss_sum, n_tok = chunked_causal_lm_loss(
                out, pipeline_head_matrix(state.params, cfg.model, out),
                batch["input_ids"], batch.get("loss_mask"), loss_chunk)
        else:
            loss_sum, n_tok = causal_lm_loss(
                out, batch["input_ids"], batch.get("loss_mask"))
        return {"loss": loss_sum / jnp.maximum(n_tok, 1.0),
                "num_tokens": n_tok}

    return jax.jit(eval_step)
