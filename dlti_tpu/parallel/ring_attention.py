"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference "scales sequence length" by truncating every sample to 512
tokens (``train_baseline.py:155``; SURVEY.md §5.7) and ships no sequence /
context parallelism of any kind. Here long-context is first-class: the
``sequence`` mesh axis shards the *length* dimension of activations, and
attention — the only op that mixes positions — is computed exactly with a
ring schedule (Liu et al., "Ring Attention with Blockwise Transformers"):

* Each device holds one contiguous sequence chunk of Q, K, V.
* For ``sequence`` axis size N, the ring runs N steps. At step t a device
  computes blockwise attention of its local Q chunk against the K/V chunk
  it currently holds, folding the result into an online-softmax
  accumulator (the same m/l/acc recurrence as flash attention), then
  passes K/V to its ring neighbor with ``jax.lax.ppermute``.
* ``ppermute`` is a neighbor-exchange, so on TPU the transfer rides a
  single ICI hop per step and XLA overlaps it with the block matmuls —
  communication is hidden behind compute for all but tiny chunk sizes.
* Causal masking is driven by explicit *token positions* that travel the
  ring alongside K/V, so the mask always agrees with the RoPE positions
  the caller embedded — including shifted/custom position schemes. Chunks
  that are entirely in the future (``min(kv_pos) > max(q_pos)``) skip
  their matmuls via ``lax.cond``, so a causal ring does ~half the FLOPs
  of a full one, like any flash-attention kernel.

K/V travel in *unexpanded* GQA form (``num_kv_heads``) and are repeated to
``num_heads`` only inside the local block product, so ring traffic is
proportional to the KV width, not the Q width.

Composition with the other axes: batch dims stay sharded over
``('data','fsdp')`` and the head dim over ``'tensor'`` (when divisible) —
the ring only communicates along ``'sequence'``, so TP×SP×DP all compose
inside one ``shard_map``. The wrapper is differentiable (``ppermute``
transposes to the reverse ring), so the same code path serves training.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dlti_tpu.ops.attention import repeat_kv

# Finite stand-in for -inf. Keeps every exp()/max() total (no inf-inf=NaN
# corner) while exp(NEG_INF - anything_finite) underflows to exactly 0.
NEG_INF = -1e30


def _block_accumulate(carry, q, k, v, q_pos, kv_pos, q_seg, kv_seg, scale,
                      causal, window):
    """Fold one K/V chunk into the online-softmax state.

    carry: (m, l, acc) with m,l (b, h, sq) fp32 and acc (b, sq, h, d) fp32.
    q: (b, sq, h, d); k/v: (b, sk, hk, d); q_pos/kv_pos: (b, sq)/(b, sk)
    global token positions driving the causal (and sliding-window) mask;
    q_seg/kv_seg: optional (b, sq)/(b, sk) segment ids for packed batches
    (id 0 = padding, matching ``reference_attention``).
    """
    m, l, acc = carry
    kr = repeat_kv(k, q.shape[2] // k.shape[2])
    vr = repeat_kv(v, q.shape[2] // v.shape[2])

    # (b, h, sq, sk) scores, fp32 accumulation on the MXU.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr, preferred_element_type=jnp.float32)
    s = s.astype(jnp.float32) * scale
    allowed = None
    if causal:
        # (b, 1, sq, sk): kv token visible iff its position <= the query's.
        allowed = kv_pos[:, None, None, :] <= q_pos[:, None, :, None]
        if window:
            allowed &= kv_pos[:, None, None, :] > (q_pos[:, None, :, None]
                                                   - window)
    if q_seg is not None:
        same = ((q_seg[:, None, :, None] == kv_seg[:, None, None, :])
                & (kv_seg[:, None, None, :] != 0))
        allowed = same if allowed is None else (allowed & same)
    if allowed is not None:
        s = jnp.where(allowed, s, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if allowed is not None:
        # Fully-masked rows have m_new == NEG_INF, making exp(s - m_new)
        # == 1 at every masked entry — zero them explicitly.
        p = jnp.where(allowed, p, 0.0)
    alpha = jnp.exp(m - m_new)  # (b, h, sq)

    l_new = alpha * l + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vr.dtype), vr,
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + pv.astype(jnp.float32)
    return m_new, l_new, acc_new


def ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_pos: jnp.ndarray,
    q_seg: Optional[jnp.ndarray] = None,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
    window: int = 0,
) -> jnp.ndarray:
    """Per-shard ring attention body. Must run under ``shard_map`` with
    ``axis_name`` bound; each call sees the local (b, s_local, h|hk, d)
    chunks of globally (b, s, h|hk, d) arrays sharded on dim 1, and the
    matching local slices of token positions ``q_pos`` (b, s_local) and
    (for packed batches) segment ids ``q_seg`` (b, s_local).
    """
    b, sq, h, d = q.shape
    scale = d ** -0.5

    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    acc = jnp.zeros((b, sq, h, d), jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    kv_pos = q_pos
    kv_seg = q_seg
    carry = (m, l, acc)
    for t in range(axis_size):
        # Runtime whole-chunk skips (the ring analog of flash's block
        # skipping). All are conservative: a skipped chunk provably
        # contributes nothing to any row.
        skip = None
        if causal:
            # Chunk entirely in the future for every row. With default
            # contiguous positions this reduces to the classic "source
            # shard index > mine" skip (~half the ring FLOPs).
            skip = jnp.min(kv_pos) > jnp.max(q_pos)
            if window:
                # Chunk entirely behind every row's sliding window.
                skip |= jnp.max(kv_pos) <= jnp.min(q_pos) - window
        if q_seg is not None:
            # Segment-id intervals disjoint -> no equal pair can exist.
            seg_disjoint = jnp.logical_or(
                jnp.min(q_seg) > jnp.max(kv_seg),
                jnp.max(q_seg) < jnp.min(kv_seg))
            skip = seg_disjoint if skip is None else (skip | seg_disjoint)

        if skip is not None:
            carry = jax.lax.cond(
                skip,
                lambda op: op[0],
                lambda op: _block_accumulate(op[0], q, op[1], op[2],
                                             q_pos, op[3], q_seg, op[4],
                                             scale, causal, window),
                (carry, k, v, kv_pos,
                 kv_seg if kv_seg is not None else kv_pos),
            )
        else:
            carry = _block_accumulate(carry, q, k, v, q_pos, kv_pos, q_seg,
                                      kv_seg, scale, causal, window)

        if t != axis_size - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
            if kv_seg is not None:
                kv_seg = jax.lax.ppermute(kv_seg, axis_name, perm)

    _, l, acc = carry
    # Fully-masked rows (padding tokens in packed batches) have l == 0 and
    # acc == 0: the max() guard makes their output exactly zero.
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    positions: Optional[jnp.ndarray] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    causal: bool = True,
    window: Optional[int] = None,
    seq_axis: str = "sequence",
    batch_axes: tuple = ("data", "fsdp"),
    head_axis: str = "tensor",
) -> jnp.ndarray:
    """Global-view ring attention entry point (callable inside ``jit``).

    q: (b, s, h, d); k/v: (b, s, hk, d) — *global* shapes; the wrapper
    shard_maps them as P(batch_axes, seq_axis, head_axis?, None).
    ``positions`` (b, s) are the token positions RoPE was applied at; the
    causal mask is computed from them so the two can never disagree
    (default: contiguous 0..s-1). ``segment_ids`` (b, s) enables packed
    batches (tokens attend within their own segment; id 0 = padding,
    producing zero output rows); the ids travel the ring with K/V and
    segment-disjoint chunks skip their matmuls. ``window`` is
    Mistral-style sliding-window locality (requires ``causal``); chunks
    entirely behind every query's window are skipped, so a long ring
    does O(window) work per query, not O(seq). The head dim is sharded
    over ``head_axis`` (TP) only when both h and hk divide; otherwise
    heads stay replicated and GSPMD reconciles with the surrounding
    layout.
    """
    n = mesh.shape[seq_axis]
    if n == 1:
        from dlti_tpu.ops.attention import reference_attention

        return reference_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            q_positions=positions, kv_positions=positions, window=window,
        )
    b, s = q.shape[0], q.shape[1]
    if s % n != 0:
        raise ValueError(
            f"ring attention: seq len {s} not divisible by "
            f"{seq_axis} axis size {n}"
        )
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :],
                                     (b, s))
    else:
        positions = jnp.broadcast_to(positions.astype(jnp.int32), (b, s))

    h, hk = q.shape[2], k.shape[2]
    tp = mesh.shape.get(head_axis, 1)
    h_ax = head_axis if (tp > 1 and h % tp == 0 and hk % tp == 0) else None
    spec = P(batch_axes, seq_axis, h_ax, None)
    pos_spec = P(batch_axes, seq_axis)

    # Inside an enclosing shard_map (PP x SP: the pipe schedule is manual
    # over 'pipe'), a NESTED manual ring is off the table on this jax: a
    # nested shard_map either computes silently wrong gradients
    # (check_vma=False skips the transpose's replication psums — measured
    # embed grads off by 17-370x) or fails verification/lowering
    # (check_vma=True: cond-branch vma mismatches in the skip cond's
    # transpose, then an sdy.manual_computation local-shape error).
    # Delegate to reference_attention instead and let GSPMD partition it
    # over the AUTO 'sequence' axis — all-gather-style sequence
    # parallelism: activations stay sequence-sharded outside attention,
    # XLA inserts the k/v gathers, numerics and gradients are exact by
    # construction (no nested manual region at all). The flat path below
    # keeps the true ring schedule.
    # No try/except here: if a jax upgrade changes this introspection
    # API, fail LOUD — silently assuming "not nested" would route PP x SP
    # into the known-broken nested manual ring (wrong gradients).
    am = jax.sharding.get_abstract_mesh()
    nested = (am is not None and not am.empty
              and any(ty == jax.sharding.AxisType.Manual
                      and am.shape[name] > 1
                      for name, ty in zip(am.axis_names, am.axis_types)))
    if nested:
        from dlti_tpu.ops.attention import reference_attention

        return reference_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            q_positions=positions, kv_positions=positions, window=window,
        )

    body = functools.partial(
        ring_attention_local, axis_name=seq_axis, axis_size=n, causal=causal,
        window=int(window or 0),
    )
    if segment_ids is None:
        f = jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec, pos_spec),
            out_specs=spec, check_vma=False,
        )
        return f(q, k, v, positions)
    segment_ids = jnp.broadcast_to(segment_ids.astype(jnp.int32), (b, s))
    f = jax.shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, pos_spec, pos_spec),
        out_specs=spec, check_vma=False,
    )
    return f(q, k, v, positions, segment_ids)
