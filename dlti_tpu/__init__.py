"""dlti_tpu — TPU-native distributed LLM training + inference framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
``rokulkarni15/distributed-llm-training-inference`` (the reference repo):

* LoRA fine-tuning of Llama-family models (reference:
  ``training/train_baseline.py``, ``train_deepspeed_zero{1,2,3}.py``)
* ZeRO-1/2/3-equivalent distributed training, expressed as
  ``jax.sharding.NamedSharding`` presets over a device mesh instead of the
  reference's DeepSpeed/NCCL engine (reference: ``configs/ds_config_zero*.json``)
* Dataset preparation with the Llama-2 chat format contract (reference:
  ``scripts/prepare_dataset.py``)
* Metrics/analysis with the reference CSV schema (reference:
  ``training/utils.py``, ``scripts/compare_training.py``)
* The serving + load-test leg the reference README claims (vLLM/Locust,
  ``README.md:10-17``) but never implements: a TPU-native engine with a
  paged KV cache, continuous batching, and an OpenAI-compatible server.

The package name abbreviates the reference repo name
(``distributed-llm-training-inference`` → ``dlti``) with a ``_tpu`` suffix,
since hyphens are not importable in Python.
"""

__version__ = "0.3.0"

from dlti_tpu.config import (  # noqa: F401
    Config,
    DataConfig,
    LoRAConfig,
    ModelConfig,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
    ZeROStage,
)
