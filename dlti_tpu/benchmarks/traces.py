"""Replayable JSONL traffic traces: capture, synthesis, and the schema.

SLO drills are only regression tests if the traffic is reproducible.
This module defines the trace format loadgen replays (``--trace FILE``)
and records (``--record-trace FILE``), plus seeded synthetic generators
for the shapes production traffic actually takes — diurnal load curves,
flash crowds, heavy-tailed prompt/output mixtures, zipf-skewed tenants.

**Trace JSONL schema** (``dlti-trace/1``): line 1 is a header object,
every following line one arrival event; all objects are sorted-key
compact JSON, offsets rounded to microseconds, so a fixed seed yields a
byte-identical file (pinned in tests/test_traces.py).

Header::

    {"duration_s": 60.0, "format": "dlti-trace/1", "generator":
     "flash_crowd", "num_events": 240, "seed": 7}

Event (offsets ascending; ``offset_s`` is seconds since replay start)::

    {"adapter": "", "deadline_s": 0.0, "max_tokens": 48, "offset_s":
     1.25, "priority": "interactive", "prompt_tokens": 96,
     "session": "t0/s3", "tenant": "t0"}

``deadline_s`` (0 = none) is carried for deadline-aware schedulers;
``session`` keys co-route multi-turn traffic; ``adapter`` names a LoRA
slot. Unknown keys are ignored on read, so the format can grow.

Generators thin a homogeneous Poisson process at the ceiling rate
against the instantaneous rate curve — the standard exact sampler for
inhomogeneous arrivals — and draw lengths from clamped lognormals
(heavy-tailed: a p99 prompt is many times the median, as in real
mixtures).
"""

from __future__ import annotations

import argparse
import json
import math
import random
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence, Tuple

TRACE_FORMAT = "dlti-trace/1"

GENERATORS = ("poisson", "diurnal", "flash_crowd")


@dataclass
class TraceEvent:
    """One arrival in a traffic trace."""

    offset_s: float
    prompt_tokens: int
    max_tokens: int
    tenant: str = "t0"
    priority: str = "interactive"
    session: str = ""
    adapter: str = ""
    deadline_s: float = 0.0

    @classmethod
    def from_dict(cls, d: dict) -> "TraceEvent":
        known = {f: d[f] for f in cls.__dataclass_fields__ if f in d}
        return cls(**known)


def _dumps(obj: dict) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def write_trace(path: str, events: Sequence[TraceEvent],
                meta: Optional[dict] = None) -> None:
    """Write header + events as deterministic JSONL (events re-sorted by
    offset; offsets rounded to 1 µs so replays and diffs are stable)."""
    events = sorted(events, key=lambda e: e.offset_s)
    header = {"format": TRACE_FORMAT, "num_events": len(events)}
    header.update(meta or {})
    with open(path, "w") as f:
        f.write(_dumps(header) + "\n")
        for e in events:
            d = asdict(e)
            d["offset_s"] = round(d["offset_s"], 6)
            d["deadline_s"] = round(d["deadline_s"], 6)
            f.write(_dumps(d) + "\n")


def read_trace(path: str) -> Tuple[dict, List[TraceEvent]]:
    """(header, events). A headerless file (first line is an event) gets
    a synthesized header; events come back offset-sorted."""
    header: dict = {"format": TRACE_FORMAT}
    events: List[TraceEvent] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            if i == 0 and "format" in d:
                header = d
                continue
            events.append(TraceEvent.from_dict(d))
    events.sort(key=lambda e: e.offset_s)
    header.setdefault("num_events", len(events))
    return header, events


# ----------------------------------------------------------------------
# Synthesis
# ----------------------------------------------------------------------

def _zipf_weights(n: int, alpha: float) -> List[float]:
    w = [1.0 / (i + 1) ** alpha for i in range(n)]
    s = sum(w)
    return [x / s for x in w]


def _lognormal_tokens(rng: random.Random, mean: int, sigma: float,
                      cap: int) -> int:
    v = int(round(rng.lognormvariate(math.log(max(1, mean)), sigma)))
    return max(1, min(cap, v))


def synthesize(generator: str = "poisson", *,
               duration_s: float = 60.0, rate: float = 4.0, seed: int = 0,
               tenants: int = 4, zipf_alpha: float = 1.1,
               interactive_frac: float = 0.8, session_frac: float = 0.0,
               sessions_per_tenant: int = 4,
               adapters: Sequence[str] = (), adapter_frac: float = 0.0,
               prompt_mean_tokens: int = 96, prompt_sigma: float = 0.6,
               prompt_max_tokens: int = 2048,
               output_mean_tokens: int = 48, output_sigma: float = 0.6,
               output_max_tokens: int = 512,
               deadline_s: float = 0.0,
               diurnal_period_s: float = 60.0,
               diurnal_amplitude: float = 0.8,
               flash_at_s: Optional[float] = None,
               flash_duration_s: Optional[float] = None,
               flash_factor: float = 8.0,
               ) -> Tuple[dict, List[TraceEvent]]:
    """Seeded synthetic trace → (header-meta, events).

    ``rate`` is the *baseline* arrivals/s; the generator shapes it:
    ``poisson`` holds it constant, ``diurnal`` modulates it by
    ``1 + amplitude·sin(2πt/period)``, ``flash_crowd`` multiplies it by
    ``flash_factor`` inside the burst window (default: the middle sixth
    of the trace). Same seed → identical events."""
    if generator not in GENERATORS:
        raise ValueError(f"unknown generator {generator!r} "
                         f"(want one of {GENERATORS})")
    rng = random.Random(seed)
    if flash_at_s is None:
        flash_at_s = duration_s / 3.0
    if flash_duration_s is None:
        flash_duration_s = duration_s / 6.0

    def rate_at(t: float) -> float:
        if generator == "diurnal":
            return rate * max(
                0.0, 1.0 + diurnal_amplitude *
                math.sin(2.0 * math.pi * t / diurnal_period_s))
        if generator == "flash_crowd":
            in_burst = flash_at_s <= t < flash_at_s + flash_duration_s
            return rate * (flash_factor if in_burst else 1.0)
        return rate

    ceiling = rate * max(
        1.0,
        (1.0 + abs(diurnal_amplitude)) if generator == "diurnal"
        else (flash_factor if generator == "flash_crowd" else 1.0))
    weights = _zipf_weights(max(1, tenants), zipf_alpha)
    tenant_names = [f"t{i}" for i in range(max(1, tenants))]
    events: List[TraceEvent] = []
    t = 0.0
    while True:
        t += rng.expovariate(ceiling)
        if t >= duration_s:
            break
        if rng.random() * ceiling > rate_at(t):
            continue                      # thinned out of the curve
        tenant = rng.choices(tenant_names, weights=weights)[0]
        session = ""
        if session_frac > 0 and rng.random() < session_frac:
            session = f"{tenant}/s{rng.randrange(max(1, sessions_per_tenant))}"
        adapter = ""
        if adapters and adapter_frac > 0 and rng.random() < adapter_frac:
            adapter = adapters[rng.randrange(len(adapters))]
        events.append(TraceEvent(
            offset_s=round(t, 6),
            prompt_tokens=_lognormal_tokens(
                rng, prompt_mean_tokens, prompt_sigma, prompt_max_tokens),
            max_tokens=_lognormal_tokens(
                rng, output_mean_tokens, output_sigma, output_max_tokens),
            tenant=tenant,
            priority=("interactive" if rng.random() < interactive_frac
                      else "batch"),
            session=session,
            adapter=adapter,
            deadline_s=round(deadline_s, 6),
        ))
    meta = {
        "generator": generator, "seed": int(seed),
        "duration_s": round(float(duration_s), 6),
        "rate": round(float(rate), 6),
        "tenants": int(tenants), "zipf_alpha": round(float(zipf_alpha), 6),
    }
    if generator == "flash_crowd":
        meta.update(flash_at_s=round(float(flash_at_s), 6),
                    flash_duration_s=round(float(flash_duration_s), 6),
                    flash_factor=round(float(flash_factor), 6))
    if generator == "diurnal":
        meta.update(diurnal_period_s=round(float(diurnal_period_s), 6),
                    diurnal_amplitude=round(float(diurnal_amplitude), 6))
    return meta, events


def trace_summary(events: Sequence[TraceEvent]) -> Dict[str, float]:
    """Cheap shape check for a trace (tests + CLI)."""
    if not events:
        return {"num_events": 0}
    by_tenant: Dict[str, int] = {}
    for e in events:
        by_tenant[e.tenant] = by_tenant.get(e.tenant, 0) + 1
    dur = events[-1].offset_s or 1.0
    return {
        "num_events": len(events),
        "duration_s": round(events[-1].offset_s, 3),
        "mean_rate": round(len(events) / dur, 3),
        "interactive_frac": round(
            sum(1 for e in events if e.priority == "interactive")
            / len(events), 3),
        "mean_prompt_tokens": round(
            sum(e.prompt_tokens for e in events) / len(events), 1),
        "mean_max_tokens": round(
            sum(e.max_tokens for e in events) / len(events), 1),
        "tenants": len(by_tenant),
        "top_tenant_frac": round(max(by_tenant.values()) / len(events), 3),
    }


def main() -> None:
    p = argparse.ArgumentParser(
        description="synthesize a replayable JSONL traffic trace")
    p.add_argument("--out", required=True)
    p.add_argument("--generator", default="poisson", choices=GENERATORS)
    p.add_argument("--duration-s", type=float, default=60.0)
    p.add_argument("--rate", type=float, default=4.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--zipf-alpha", type=float, default=1.1)
    p.add_argument("--interactive-frac", type=float, default=0.8)
    p.add_argument("--session-frac", type=float, default=0.0)
    p.add_argument("--prompt-mean-tokens", type=int, default=96)
    p.add_argument("--output-mean-tokens", type=int, default=48)
    p.add_argument("--deadline-s", type=float, default=0.0)
    p.add_argument("--flash-factor", type=float, default=8.0)
    p.add_argument("--flash-at-s", type=float, default=None)
    p.add_argument("--flash-duration-s", type=float, default=None)
    args = p.parse_args()
    meta, events = synthesize(
        args.generator, duration_s=args.duration_s, rate=args.rate,
        seed=args.seed, tenants=args.tenants, zipf_alpha=args.zipf_alpha,
        interactive_frac=args.interactive_frac,
        session_frac=args.session_frac,
        prompt_mean_tokens=args.prompt_mean_tokens,
        output_mean_tokens=args.output_mean_tokens,
        deadline_s=args.deadline_s, flash_factor=args.flash_factor,
        flash_at_s=args.flash_at_s, flash_duration_s=args.flash_duration_s)
    write_trace(args.out, events, meta)
    print(json.dumps({"out": args.out, **trace_summary(events)}, indent=2))


if __name__ == "__main__":
    main()
